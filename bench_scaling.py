"""Scaling-efficiency harness: train tokens/sec/device at 1/2/4/8-device
mesh sizes (BASELINE.md target "Scaling efficiency — measure 1→64 chips").

On a pod this runs against real chips; on a development host it re-execs
itself per mesh size under ``XLA_FLAGS=--xla_force_host_platform_device_
count=N JAX_PLATFORMS=cpu`` so the same data-parallel program (global batch
sharded over the mesh's data axis, gradient psum inserted by XLA) is
exercised end-to-end on a virtual mesh.  Weak scaling: per-device batch is
fixed, so ideal scaling keeps tokens/sec/device flat and efficiency(N) =
tps(N) / (N · tps(1)).

Prints ONE JSON line:
  {"metric": "scaling efficiency", "value": eff@max, "unit": ...,
   "points": [{"devices": N, "tokens_per_sec": ..., "per_device": ...}]}
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

MESH_SIZES = (1, 2, 4, 8)
PER_DEVICE_BATCH = 4
BLOCK = 256
DEPTH = 4
D_MODEL = 256
STEPS = 3
TIMED = 4


def _child(n_devices: int) -> None:
    """Measure tokens/sec for one mesh size; prints a JSON line."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import CompiledArch
    from penroz_tpu.parallel import mesh as mesh_lib
    from penroz_tpu.parallel import sharding as sharding_lib
    from __graft_entry__ import OPTIMIZER

    devices = jax.devices()[:n_devices]
    if len(devices) != n_devices:
        raise SystemExit(f"requested {n_devices} devices but only "
                         f"{len(devices)} available — refusing to report "
                         f"a mislabeled scaling point")
    # BENCH_SCALING_MODEL=gpt2-xl runs a real ladder size (BASELINE.md's
    # "gpt2-xl multi-host /train/" scaling config — for pods; the default
    # shrunken stack keeps the virtual CPU mesh tractable).
    preset = os.environ.get("BENCH_SCALING_MODEL")
    from penroz_tpu.models import presets
    if preset:
        layers = presets.gpt2(preset, block=BLOCK)
    else:
        layers = presets.gpt2_custom(d=D_MODEL, heads=4, depth=DEPTH,
                                     vocab=2048, block=BLOCK)
    mapper = Mapper(layers, OPTIMIZER)
    arch = CompiledArch.get(mapper.layers)
    params, _ = mapper.init_params(arch.mods, seed=0)
    opt_state = mapper.to_optimizer().init(params)

    mesh = mesh_lib.make_mesh(devices)
    params = sharding_lib.shard_params(params, mesh)
    opt_state = jax.device_put(opt_state, mesh_lib.replicated(mesh))

    batch = PER_DEVICE_BATCH * n_devices
    rng = np.random.default_rng(0)
    x = rng.integers(0, 2048, (STEPS, batch, BLOCK), dtype=np.int32)
    y = rng.integers(0, 2048, (STEPS, batch, BLOCK), dtype=np.int32)
    xs = sharding_lib.shard_batch(x, mesh, leading_steps=True)
    ys = sharding_lib.shard_batch(y, mesh, leading_steps=True)

    epoch_fn = arch.train_epoch_fn(mapper.optimizer, STEPS)
    key = jax.random.key(0)
    buffers = {}
    for _ in range(2):  # compile + warm
        params, opt_state, buffers, cost, _ = epoch_fn(params, opt_state,
                                                       buffers, xs, ys, key)
    float(cost)
    t0 = time.perf_counter()
    for _ in range(TIMED):
        params, opt_state, buffers, cost, _ = epoch_fn(params, opt_state,
                                                       buffers, xs, ys, key)
    float(cost)
    elapsed = time.perf_counter() - t0
    tokens = TIMED * STEPS * batch * BLOCK
    rec = {"devices": n_devices, "tokens_per_sec": tokens / elapsed}

    if os.environ.get("BENCH_SCALING_ZERO") == "1" and n_devices > 1:
        # ZeRO ladder memory: bytes of params + optimizer state resident on
        # device 0 under the replicated/TP layout vs FSDP+WUS
        # (PENROZ_FSDP=1).  The training-math equivalence is test-asserted
        # (tests/test_parallel.py); this records the memory win.
        def dev0_bytes(tree):
            total = 0
            for leaf in jax.tree.leaves(tree):
                for s in getattr(leaf, "addressable_shards", []):
                    if s.device == devices[0] and s.data is not None:
                        total += s.data.size * s.data.dtype.itemsize
            return total

        repl = dev0_bytes(params) + dev0_bytes(opt_state)
        f_params = jax.device_put(
            params, sharding_lib.param_shardings(params, mesh, fsdp=True))
        f_opt = jax.device_put(opt_state, sharding_lib.opt_state_sharding_tree(
            opt_state, f_params, mesh, wus=True))
        jax.block_until_ready((f_params, f_opt))
        rec["state_bytes_per_device"] = repl
        rec["zero_state_bytes_per_device"] = dev0_bytes(f_params) \
            + dev0_bytes(f_opt)
    print(json.dumps(rec))


def main() -> None:
    points = []
    for n in MESH_SIZES:
        env = dict(os.environ)
        if n == MESH_SIZES[-1]:
            env["BENCH_SCALING_ZERO"] = "1"
        env["JAX_PLATFORMS"] = env.get("BENCH_SCALING_PLATFORM", "cpu")
        if env["JAX_PLATFORMS"] == "cpu":
            env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                                f" --xla_force_host_platform_device_count={n}"
                                ).strip()
            # A remote-accelerator plugin on PYTHONPATH would still dial its
            # backend under JAX_PLATFORMS=cpu; scrub to repo-only.
            env["PYTHONPATH"] = os.path.dirname(os.path.abspath(__file__))
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child", str(n)],
            env=env, capture_output=True, text=True, timeout=1200)
        if out.returncode != 0:
            print(out.stderr[-2000:], file=sys.stderr)
            raise SystemExit(f"child failed for {n} devices")
        line = [l for l in out.stdout.splitlines() if l.startswith("{")][-1]
        rec = json.loads(line)
        rec["per_device"] = rec["tokens_per_sec"] / rec["devices"]
        points.append(rec)

    base = points[0]["tokens_per_sec"]
    top = points[-1]
    virtual = os.environ.get("BENCH_SCALING_PLATFORM", "cpu") == "cpu"
    if virtual:
        # All "devices" share one host CPU, so per-device weak scaling is
        # physically impossible — the meaningful number is how much total
        # throughput the sharded program retains versus single-device
        # (collective/partitioning overhead).  Real chips report true
        # per-device efficiency below.
        metric = (f"virtual-mesh total-throughput retention "
                  f"@{top['devices']} devices")
        value = top["tokens_per_sec"] / base
    else:
        metric = f"train scaling efficiency @{top['devices']} devices"
        value = top["tokens_per_sec"] / (top["devices"] * base)
    out = {
        "metric": metric,
        "value": round(value, 4),
        "unit": "fraction of linear",
        "vs_baseline": round(value, 4),  # linear scaling = 1.0
        "virtual_mesh": virtual,
        "points": [{k: (round(v, 1) if isinstance(v, float) else v)
                    for k, v in p.items()} for p in points],
    }
    if "zero_state_bytes_per_device" in top:
        out["zero_memory_reduction"] = round(
            top["state_bytes_per_device"]
            / max(top["zero_state_bytes_per_device"], 1), 2)
    print(json.dumps(out))


if __name__ == "__main__":
    if len(sys.argv) > 2 and sys.argv[1] == "--child":
        _child(int(sys.argv[2]))
    else:
        main()
