"""Scaling-efficiency harness: train tokens/sec/device at 1/2/4/8-device
mesh sizes (BASELINE.md target "Scaling efficiency — measure 1→64 chips").

On a pod this runs against real chips; on a development host it re-execs
itself per mesh size under ``XLA_FLAGS=--xla_force_host_platform_device_
count=N JAX_PLATFORMS=cpu`` so the same data-parallel program (global batch
sharded over the mesh's data axis, gradient psum inserted by XLA) is
exercised end-to-end on a virtual mesh.  Weak scaling: per-device batch is
fixed, so ideal scaling keeps tokens/sec/device flat and efficiency(N) =
tps(N) / (N · tps(1)).

Prints ONE JSON line:
  {"metric": "scaling efficiency", "value": eff@max, "unit": ...,
   "points": [{"devices": N, "tokens_per_sec": ..., "per_device": ...}]}
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

MESH_SIZES = (1, 2, 4, 8)
PER_DEVICE_BATCH = 4
BLOCK = 256
DEPTH = 4
D_MODEL = 256
STEPS = 3
TIMED = 4


def _child(n_devices: int) -> None:
    """Measure tokens/sec for one mesh size; prints a JSON line."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import CompiledArch
    from penroz_tpu.parallel import mesh as mesh_lib
    from penroz_tpu.parallel import sharding as sharding_lib
    from __graft_entry__ import OPTIMIZER

    devices = jax.devices()[:n_devices]
    if len(devices) != n_devices:
        raise SystemExit(f"requested {n_devices} devices but only "
                         f"{len(devices)} available — refusing to report "
                         f"a mislabeled scaling point")
    # BENCH_SCALING_MODEL=gpt2-xl runs a real ladder size (BASELINE.md's
    # "gpt2-xl multi-host /train/" scaling config — for pods; the default
    # shrunken stack keeps the virtual CPU mesh tractable).
    preset = os.environ.get("BENCH_SCALING_MODEL")
    from penroz_tpu.models import presets
    if preset:
        layers = presets.gpt2(preset, block=BLOCK)
    else:
        layers = presets.gpt2_custom(d=D_MODEL, heads=4, depth=DEPTH,
                                     vocab=2048, block=BLOCK)
    mapper = Mapper(layers, OPTIMIZER)
    arch = CompiledArch.get(mapper.layers)
    params, _ = mapper.init_params(arch.mods, seed=0)
    opt_state = mapper.to_optimizer().init(params)

    mesh = mesh_lib.make_mesh(devices)
    params = sharding_lib.shard_params(params, mesh)
    opt_state = jax.device_put(opt_state, mesh_lib.replicated(mesh))

    batch = PER_DEVICE_BATCH * n_devices
    rng = np.random.default_rng(0)
    x = rng.integers(0, 2048, (STEPS, batch, BLOCK), dtype=np.int32)
    y = rng.integers(0, 2048, (STEPS, batch, BLOCK), dtype=np.int32)
    xs = sharding_lib.shard_batch(x, mesh, leading_steps=True)
    ys = sharding_lib.shard_batch(y, mesh, leading_steps=True)

    epoch_fn = arch.train_epoch_fn(mapper.optimizer, STEPS)
    key = jax.random.key(0)
    buffers = {}
    for _ in range(2):  # compile + warm
        params, opt_state, buffers, cost, _ = epoch_fn(params, opt_state,
                                                       buffers, xs, ys, key)
    float(cost)
    # Best-of-3 timed windows: the virtual-device points run on one
    # contended CPU, and a single window is hostage to whatever else the
    # host is doing (r03's retention read 645/166/122 tok/s/dev at 2/4/8
    # with the 4-point below the 8-point).  Min-elapsed is the standard
    # contended-environment estimator; the artifact stays labeled a
    # contention-bound proxy either way.
    elapsed = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(TIMED):
            params, opt_state, buffers, cost, _ = epoch_fn(
                params, opt_state, buffers, xs, ys, key)
        float(cost)
        elapsed = min(elapsed, time.perf_counter() - t0)
    tokens = TIMED * STEPS * batch * BLOCK
    rec = {"devices": n_devices, "tokens_per_sec": tokens / elapsed,
           "timing": "best_of_3_windows"}

    # Mesh-aware /evaluate/ throughput: the forward-only cost program over
    # the same data-sharded batch (evaluate_model routes through
    # _eval_mesh + eval_cost_fn; pre-round-4 it used one device per
    # process regardless of host capacity).
    ex, ey = xs[0], ys[0]
    float(arch.eval_cost_fn(params, buffers, ex, ey))  # compile + warm
    eval_elapsed = float("inf")
    for _ in range(3):  # best-of-3, same contention rationale as above
        t0 = time.perf_counter()
        for _ in range(TIMED):
            float(arch.eval_cost_fn(params, buffers, ex, ey))
        eval_elapsed = min(eval_elapsed, time.perf_counter() - t0)
    rec["eval_tokens_per_sec"] = TIMED * batch * BLOCK / eval_elapsed

    if os.environ.get("BENCH_SCALING_ZERO") == "1" and n_devices > 1:
        # ZeRO ladder memory: bytes of params + optimizer state resident on
        # device 0 under the replicated/TP layout vs FSDP+WUS
        # (PENROZ_FSDP=1).  The training-math equivalence is test-asserted
        # (tests/test_parallel.py); this records the memory win.
        def dev0_bytes(tree):
            total = 0
            for leaf in jax.tree.leaves(tree):
                for s in getattr(leaf, "addressable_shards", []):
                    if s.device == devices[0] and s.data is not None:
                        total += s.data.size * s.data.dtype.itemsize
            return total

        repl = dev0_bytes(params) + dev0_bytes(opt_state)
        f_params = jax.device_put(
            params, sharding_lib.param_shardings(params, mesh, fsdp=True))
        f_opt = jax.device_put(opt_state, sharding_lib.opt_state_sharding_tree(
            opt_state, f_params, mesh, wus=True))
        jax.block_until_ready((f_params, f_opt))
        rec["state_bytes_per_device"] = repl
        rec["zero_state_bytes_per_device"] = dev0_bytes(f_params) \
            + dev0_bytes(f_opt)
    print(json.dumps(rec))


_COLLECTIVE_RE = None


def _collective_stats(hlo_text: str) -> dict:
    """Per-collective op counts and payload bytes from compiled HLO.

    Parses lines shaped ``%x = bf16[2048,256]{...} all-reduce(...)`` (and
    tuple-result variants) for the XLA collectives GSPMD inserted; the sum
    is the per-step communication volume the strategy costs — measurable
    without hardware, unlike ICI bandwidth."""
    import re
    global _COLLECTIVE_RE
    if _COLLECTIVE_RE is None:
        _COLLECTIVE_RE = re.compile(
            r"=\s*(\([^)]*\)|[a-z0-9]+\[[^\]]*\](?:\{[^}]*\})?)\s*"
            r"(all-reduce|all-gather|reduce-scatter|collective-permute|"
            r"all-to-all)(-start)?\(")
    itemsize = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "s32": 4,
                "u32": 4, "s64": 8, "u64": 8, "s8": 1, "u8": 1,
                "pred": 1, "s16": 2, "u16": 2}
    shape_re = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")
    ops: dict = {}
    op_bytes: dict = {}
    total = 0
    for m in _COLLECTIVE_RE.finditer(hlo_text):
        shapes, op, started = m.group(1), m.group(2), m.group(3)
        nbytes = 0
        for dtype, dims in shape_re.findall(shapes):
            if dtype not in itemsize:
                continue
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            nbytes += n * itemsize[dtype]
        if started:
            # Async ``-start`` results are (aliased input, output) tuples:
            # halving removes the double count (exact for all-reduce; a
            # small under/over-estimate for all-gather/reduce-scatter whose
            # halves differ by the 1/shards factor).  The sync forms the
            # CPU backend emits need no correction.
            nbytes //= 2
        ops[op] = ops.get(op, 0) + 1
        op_bytes[op] = op_bytes.get(op, 0) + nbytes
        total += nbytes
    return {"ops": ops, "bytes": total, "bytes_per_op": op_bytes}


def _comm_child() -> None:
    """Per-strategy collective volume + step time on the 8-device mesh.

    One JSON line: for each of DP/TP/SP/EP/FSDP/PP, the collectives GSPMD
    scheduled per training step (op counts + payload bytes from the
    compiled HLO) and the measured step time.  Bytes are exact compiler
    output; times on a VIRTUAL mesh are contention-bound and only useful
    relative to each other."""
    import jax
    import numpy as np

    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import CompiledArch, NeuralNetworkModel
    from penroz_tpu.models import presets
    from penroz_tpu.parallel import mesh as mesh_lib
    from penroz_tpu.parallel import sharding as sharding_lib
    from __graft_entry__ import OPTIMIZER

    devices = jax.devices()[:8]
    assert len(devices) == 8, "comm breakdown wants 8 devices"
    vocab = 2048
    batch = 8

    def dense_layers():
        return presets.gpt2_custom(d=D_MODEL, heads=4, depth=DEPTH,
                                   vocab=vocab, block=BLOCK)

    def moe_layers():
        # capacity dispatch: the EP-scalable mode — tokens route to their
        # expert's owning device via all_to_all (ops/modules.
        # _apply_capacity_ep) instead of every device computing its
        # experts for every token and psum-combining (the r04 census
        # pathology: 34 all-reduces, 11.1s step, zero all-to-all).
        layers = dense_layers()
        moe_mlp = {"sequential": [
            {"layernorm": {"normalized_shape": D_MODEL}},
            {"moe": {"in_features": D_MODEL,
                     "intermediate_size": 2 * D_MODEL,
                     "num_experts": 4, "top_k": 2,
                     "dispatch": "capacity"}}]}
        for i in range(2, 2 + DEPTH):
            layers[i]["residual"][1] = moe_mlp
        return layers

    def measure(epoch_fn, params, opt_state, buffers, xs, ys, key):
        """(collective stats, step ms) for one compiled epoch program."""
        compiled = epoch_fn.lower(params, opt_state, buffers, xs, ys,
                                  key).compile()
        stats = _collective_stats(compiled.as_text())
        for _ in range(2):
            params, opt_state, buffers, cost, _ = epoch_fn(
                params, opt_state, buffers, xs, ys, key)
        float(cost)
        best = float("inf")
        for _ in range(3):  # best-of-3: see the retention-point comment
            t0 = time.perf_counter()
            for _ in range(TIMED):
                params, opt_state, buffers, cost, _ = epoch_fn(
                    params, opt_state, buffers, xs, ys, key)
            float(cost)
            best = min(best, time.perf_counter() - t0)
        step_ms = best * 1000 / (TIMED * STEPS)
        return stats, step_ms

    configs = [
        ("dp", {}, dense_layers, False, False),
        ("tp", {"model": 4}, dense_layers, False, False),
        ("sp", {"sequence": 4}, dense_layers, True, False),
        # moe_dp: the SAME MoE model on pure data parallelism — the fair
        # step-time denominator for the ep row (the dense `dp` row runs a
        # smaller model; capacity-MoE carries ~2.5x its MLP FLOPs).
        ("moe_dp", {}, moe_layers, False, False),
        ("ep", {"expert": 4}, moe_layers, False, False),
        ("fsdp", {}, dense_layers, False, True),
    ]
    out = []
    for name, axes, layer_fn, use_sp, fsdp in configs:
        use_ep = "expert" in axes
        mapper = Mapper(layer_fn(), OPTIMIZER)
        arch = CompiledArch.get(mapper.layers)
        params, buffers = mapper.init_params(arch.mods, seed=0)
        opt_state = mapper.to_optimizer().init(params)
        mesh = mesh_lib.make_mesh(devices, **axes)
        out_shardings = None
        if fsdp:
            params = sharding_lib.shard_params(params, mesh, fsdp=True)
            out_shardings = (
                sharding_lib.param_shardings(params, mesh, fsdp=True),
                sharding_lib.opt_state_sharding_tree(opt_state, params,
                                                     mesh, wus=True))
            opt_state = sharding_lib.place_tree(opt_state, out_shardings[1])
        else:
            params = sharding_lib.shard_params(params, mesh)
            opt_state = jax.device_put(opt_state, mesh_lib.replicated(mesh))
        rng = np.random.default_rng(0)
        x = rng.integers(0, vocab, (STEPS, batch, BLOCK), dtype=np.int32)
        y = rng.integers(0, vocab, (STEPS, batch, BLOCK), dtype=np.int32)
        xs = sharding_lib.shard_batch(x, mesh, leading_steps=True,
                                      shard_sequence=use_sp)
        ys = sharding_lib.shard_batch(y, mesh, leading_steps=True,
                                      shard_sequence=use_sp)
        epoch_fn = arch.train_epoch_fn(
            mapper.optimizer, STEPS, sp_mesh=mesh if use_sp else None,
            out_shardings=out_shardings,
            ep_mesh=mesh if use_ep else None)
        stats, step_ms = measure(epoch_fn, params, opt_state, buffers,
                                 xs, ys, jax.random.key(0))
        out.append({"strategy": name, "mesh": dict(mesh.shape),
                    "collective_ops": stats["ops"],
                    "collective_bytes_per_op": stats["bytes_per_op"],
                    "collective_bytes_per_epoch": stats["bytes"],
                    "step_time_ms": round(step_ms, 2)})

    # PP goes through the product path (stacked layout + GPipe epoch fn)
    os.environ["PENROZ_MESH_PIPE"] = "2"
    try:
        model = NeuralNetworkModel("comm-pp", Mapper(dense_layers(),
                                                     OPTIMIZER))
        mesh = model._training_mesh(batch, BLOCK)
        pipe_cfg, out_shardings = model._enter_pipe_layout(mesh, batch)
        # pipe_remat pinned so recorded step times don't silently shift if
        # the training default changes: 'block' is what /train/ ships.
        epoch_fn = model.arch.train_epoch_fn(
            OPTIMIZER, STEPS, out_shardings=out_shardings,
            pipe_cfg=pipe_cfg, pipe_remat="block")
        rng = np.random.default_rng(0)
        import jax.numpy as jnp  # noqa: F401
        x = rng.integers(0, vocab, (STEPS, batch, BLOCK), dtype=np.int32)
        y = rng.integers(0, vocab, (STEPS, batch, BLOCK), dtype=np.int32)
        xs = sharding_lib.shard_batch(x, mesh, leading_steps=True)
        ys = sharding_lib.shard_batch(y, mesh, leading_steps=True)
        stats, step_ms = measure(epoch_fn, model.params, model.opt_state,
                                 model.buffers, xs, ys, jax.random.key(0))
        out.append({"strategy": "pp", "mesh": dict(mesh.shape),
                    "collective_ops": stats["ops"],
                    "collective_bytes_per_op": stats["bytes_per_op"],
                    "collective_bytes_per_epoch": stats["bytes"],
                    "step_time_ms": round(step_ms, 2)})
    finally:
        os.environ.pop("PENROZ_MESH_PIPE", None)
    print(json.dumps(out))


def _mh_child() -> None:
    """One process of the 2-process × 4-device multi-host point."""
    import jax
    import numpy as np

    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import CompiledArch
    from penroz_tpu.models import presets
    from penroz_tpu.parallel import dist, mesh as mesh_lib
    from penroz_tpu.parallel import sharding as sharding_lib
    from __graft_entry__ import OPTIMIZER

    assert dist.initialize(), "multi-host env not picked up"
    vocab = 2048
    layers = presets.gpt2_custom(d=D_MODEL, heads=4, depth=DEPTH,
                                 vocab=vocab, block=BLOCK)
    mapper = Mapper(layers, OPTIMIZER)
    arch = CompiledArch.get(mapper.layers)
    params, buffers = mapper.init_params(arch.mods, seed=0)
    opt_state = mapper.to_optimizer().init(params)
    mesh = mesh_lib.make_mesh(jax.devices())  # 8 global over 2 processes
    params = sharding_lib.shard_params(params, mesh)
    opt_state = jax.device_put(opt_state, mesh_lib.replicated(mesh))
    n_global = len(jax.devices())
    local_batch = PER_DEVICE_BATCH * len(jax.local_devices())
    rng = np.random.default_rng(dist.process_index())
    x = rng.integers(0, vocab, (STEPS, local_batch, BLOCK), dtype=np.int32)
    y = rng.integers(0, vocab, (STEPS, local_batch, BLOCK), dtype=np.int32)
    xs = sharding_lib.global_batch(x, mesh, leading_steps=True)
    ys = sharding_lib.global_batch(y, mesh, leading_steps=True)
    epoch_fn = arch.train_epoch_fn(mapper.optimizer, STEPS)
    key = jax.random.key(0)
    for _ in range(2):
        params, opt_state, buffers, cost, _ = epoch_fn(params, opt_state,
                                                       buffers, xs, ys, key)
    float(cost)
    t0 = time.perf_counter()
    for _ in range(TIMED):
        params, opt_state, buffers, cost, _ = epoch_fn(params, opt_state,
                                                       buffers, xs, ys, key)
    float(cost)
    elapsed = time.perf_counter() - t0
    tokens = TIMED * STEPS * PER_DEVICE_BATCH * n_global * BLOCK
    if dist.master_proc():
        print(json.dumps({"devices": n_global,
                          "processes": dist.process_count(),
                          "tokens_per_sec": tokens / elapsed}))


def _multihost_point():
    """Launch the 2-process × 4-device point; None on any failure (the
    single-host artifact stays useful without it)."""
    import socket
    procs = []
    try:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        for pid in range(2):
            env = dict(os.environ)
            env.update({
                "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
                "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
                "JAX_NUM_PROCESSES": "2",
                "JAX_PROCESS_ID": str(pid),
                "PYTHONPATH": os.path.dirname(os.path.abspath(__file__)),
            })
            procs.append(subprocess.Popen(
                [sys.executable, os.path.abspath(__file__), "--mh-child"],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=1200)
            outs.append(out)
        if any(p.returncode != 0 for p in procs):
            for i, (p, out) in enumerate(zip(procs, outs)):
                if p.returncode != 0:
                    print(f"multi-host worker {i} rc={p.returncode}:\n"
                          f"{out[-1500:]}", file=sys.stderr)
            return None
        for out in outs:
            for line in out.splitlines():
                if line.startswith("{"):
                    return json.loads(line)
        return None
    except Exception as exc:  # noqa: BLE001
        print(f"multi-host point failed: {exc}", file=sys.stderr)
        for p in procs:
            if p.poll() is None:
                p.kill()
        return None


def main() -> None:
    points = []
    for n in MESH_SIZES:
        env = dict(os.environ)
        if n == MESH_SIZES[-1]:
            env["BENCH_SCALING_ZERO"] = "1"
        env["JAX_PLATFORMS"] = env.get("BENCH_SCALING_PLATFORM", "cpu")
        if env["JAX_PLATFORMS"] == "cpu":
            env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                                f" --xla_force_host_platform_device_count={n}"
                                ).strip()
            # A remote-accelerator plugin on PYTHONPATH would still dial its
            # backend under JAX_PLATFORMS=cpu; scrub to repo-only.
            env["PYTHONPATH"] = os.path.dirname(os.path.abspath(__file__))
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child", str(n)],
            env=env, capture_output=True, text=True, timeout=1200)
        if out.returncode != 0:
            print(out.stderr[-2000:], file=sys.stderr)
            raise SystemExit(f"child failed for {n} devices")
        line = [l for l in out.stdout.splitlines() if l.startswith("{")][-1]
        rec = json.loads(line)
        rec["per_device"] = rec["tokens_per_sec"] / rec["devices"]
        points.append(rec)

    base = points[0]["tokens_per_sec"]
    top = points[-1]
    virtual = os.environ.get("BENCH_SCALING_PLATFORM", "cpu") == "cpu"
    if virtual:
        # All "devices" share one host CPU, so per-device weak scaling is
        # physically impossible — the meaningful number is how much total
        # throughput the sharded program retains versus single-device
        # (collective/partitioning overhead).  Real chips report true
        # per-device efficiency below.
        metric = (f"virtual-mesh total-throughput retention "
                  f"@{top['devices']} devices")
        value = top["tokens_per_sec"] / base
    else:
        metric = f"train scaling efficiency @{top['devices']} devices"
        value = top["tokens_per_sec"] / (top["devices"] * base)

    comm = None
    if os.environ.get("BENCH_SCALING_COMM", "1") == "1":
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=8"
                            ).strip()
        env["PYTHONPATH"] = os.path.dirname(os.path.abspath(__file__))
        try:
            out_c = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--comm"],
                env=env, capture_output=True, text=True, timeout=1800)
            if out_c.returncode == 0:
                lines = [l for l in out_c.stdout.splitlines()
                         if l.startswith("[")]
                comm = json.loads(lines[-1]) if lines else None
            else:
                print(out_c.stderr[-1500:], file=sys.stderr)
        except (subprocess.TimeoutExpired, json.JSONDecodeError) as exc:
            # optional enrichment: never lose the collected scaling points
            print(f"comm breakdown skipped: {exc}", file=sys.stderr)

    mh = None
    if os.environ.get("BENCH_SCALING_MULTIHOST", "1") == "1":
        mh = _multihost_point()

    out = {
        "metric": metric,
        "value": round(value, 4),
        "unit": "fraction of linear",
        "vs_baseline": round(value, 4),  # linear scaling = 1.0
        "virtual_mesh": virtual,
        # An honest label: on the virtual mesh all devices contend for one
        # host CPU, so the retention number bounds partitioning overhead
        # from above — it is NOT an ICI scaling-efficiency measurement.
        "contention_bound_proxy": virtual,
        "points": [{k: (round(v, 1) if isinstance(v, float) else v)
                    for k, v in p.items()} for p in points],
    }
    if "zero_state_bytes_per_device" in top:
        out["zero_memory_reduction"] = round(
            top["state_bytes_per_device"]
            / max(top["zero_state_bytes_per_device"], 1), 2)
    if comm is not None:
        # Exact compiler-scheduled communication per strategy: op counts +
        # payload bytes from the compiled HLO (hardware-independent).
        out["comm_breakdown"] = comm
    if mh is not None:
        mh["per_device"] = round(mh["tokens_per_sec"] / mh["devices"], 1)
        mh["tokens_per_sec"] = round(mh["tokens_per_sec"], 1)
        out["multihost_point"] = mh
    print(json.dumps(out))


if __name__ == "__main__":
    if len(sys.argv) > 2 and sys.argv[1] == "--child":
        _child(int(sys.argv[2]))
    elif len(sys.argv) > 1 and sys.argv[1] == "--comm":
        _comm_child()
    elif len(sys.argv) > 1 and sys.argv[1] == "--mh-child":
        _mh_child()
    else:
        main()
