"""Attention math: RoPE, causal (training/prefill) and cached (decode) paths.

All functions are pure and jit-traceable.  GQA is computed by reshaping the
query heads into ``(kv_heads, group)`` and contracting against un-expanded K/V
— no materialized head expansion (the reference expands KV heads to full query
head count before attending: neural_net_layers.py:76-81).

On TPU the causal path dispatches to a Pallas flash-attention kernel
(ops/pallas/flash_attention.py) when shapes allow; the jnp fallback below is
also the correctness oracle for the kernel tests.
"""

from __future__ import annotations

import functools
import logging
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

log = logging.getLogger(__name__)

_NEG_INF = -1e30

# One-shot trace-time fallback signals (the alltoall-SP fallbacks in
# modules.py warn per occurrence; these run on every decode trace, so they
# warn once per process).  Tests re-arm by clearing the set.
_WARNED_ONCE: set = set()


def _warn_once(key: str, msg: str, *args):
    if key in _WARNED_ONCE:
        return
    _WARNED_ONCE.add(key)
    log.warning(msg, *args)


def _llama3_scale_inv_freq(inv_freq, scaling: dict):
    """Llama-3.1 frequency rescaling (HF ``_compute_llama3_parameters``):
    long-wavelength components divide by ``factor``, short ones pass
    through, and a smooth ramp interpolates between the two bands."""
    factor = float(scaling["factor"])
    low = float(scaling.get("low_freq_factor", 1.0))
    high = float(scaling.get("high_freq_factor", 4.0))
    orig = float(scaling["original_max_position_embeddings"])
    wavelen = 2.0 * np.pi / inv_freq
    smooth = (orig / wavelen - low) / (high - low)
    smoothed = (1.0 - smooth) / factor * inv_freq + smooth * inv_freq
    scaled = jnp.where(wavelen > orig / low, inv_freq / factor, inv_freq)
    is_medium = (wavelen <= orig / low) & (wavelen >= orig / high)
    return jnp.where(is_medium, smoothed, scaled)


def rope_cos_sin(head_dim: int, theta: float, offset, length: int, dtype,
                 scaling: Optional[dict] = None):
    """cos/sin tables of shape (length, head_dim) starting at ``offset`` —
    or (B, length, head_dim) when ``offset`` is a (B,) vector (ragged
    batches: each sequence rotates from its own position).  A (B, length)
    ``offset`` gives every token its OWN absolute position (the ragged
    packed batch, where adjacent packed slots belong to different
    sequences at unrelated positions).

    ``scaling``: an HF ``rope_scaling`` dict with ``rope_type='llama3'``
    rescales the inverse frequencies (Llama 3.1+ long-context models)."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    if scaling:
        rope_type = (scaling.get("rope_type") or scaling.get("type")
                     or "default")
        if rope_type == "linear":
            # HF LinearScalingRotaryEmbedding: positions divide by the
            # factor, equivalently inv_freq /= factor (Gemma-3 global
            # layers ship {'rope_type': 'linear', 'factor': 8.0}).
            inv_freq = inv_freq / float(scaling["factor"])
        else:
            inv_freq = _llama3_scale_inv_freq(inv_freq, scaling)
    steps = jnp.arange(length, dtype=jnp.float32)
    offset = jnp.asarray(offset)
    if offset.ndim == 2:
        if offset.shape[1] != length:
            raise ValueError(f"per-token offset length {offset.shape[1]} "
                             f"!= sequence length {length}")
        t = offset.astype(jnp.float32)  # (B, length): explicit positions
    elif offset.ndim >= 1:
        t = offset.astype(jnp.float32)[:, None] + steps  # (B, length)
    else:
        t = offset.astype(jnp.float32) + steps
    freqs = t[..., None] * inv_freq
    emb = jnp.concatenate([freqs, freqs], axis=-1)
    return jnp.cos(emb).astype(dtype), jnp.sin(emb).astype(dtype)


def _rotate_half(x):
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([-x2, x1], axis=-1)


def apply_rope(q, k, theta: float, offset, scaling: Optional[dict] = None,
               rotary_dim: Optional[int] = None):
    """Apply rotary embeddings to (B, H, T, D) query/key tensors.

    ``rotary_dim`` < D applies partial rotary (GPT-NeoX/Pythia
    ``rotary_pct``): only the first ``rotary_dim`` feature dims are
    rotated, the rest pass through unchanged."""
    head_dim = q.shape[-1]

    def expand(tbl):
        # (L, rd) → (1, 1, L, rd); (B, L, rd) ragged → (B, 1, L, rd)
        return tbl[:, None] if tbl.ndim == 3 else tbl[None, None]

    if rotary_dim is None or rotary_dim >= head_dim:
        cos, sin = rope_cos_sin(head_dim, theta, offset, q.shape[2], q.dtype,
                                scaling=scaling)
        cos, sin = expand(cos), expand(sin)
        q = q * cos + _rotate_half(q) * sin
        k = k * cos + _rotate_half(k) * sin
        return q, k
    cos, sin = rope_cos_sin(rotary_dim, theta, offset, q.shape[2], q.dtype,
                            scaling=scaling)
    cos, sin = expand(cos), expand(sin)
    q_rot, q_pass = q[..., :rotary_dim], q[..., rotary_dim:]
    k_rot, k_pass = k[..., :rotary_dim], k[..., rotary_dim:]
    q_rot = q_rot * cos + _rotate_half(q_rot) * sin
    k_rot = k_rot * cos + _rotate_half(k_rot) * sin
    return (jnp.concatenate([q_rot, q_pass], axis=-1),
            jnp.concatenate([k_rot, k_pass], axis=-1))


def alibi_slopes(num_heads: int) -> np.ndarray:
    """Per-head ALiBi slopes (Press et al. 2022, the HF ``build_alibi_
    tensor`` closed form): geometric sequence ``2^(-8/n)`` powers for
    power-of-two head counts, interleaved from the next power of two
    otherwise."""
    import math

    def pow2(n):
        start = 2.0 ** (-(2.0 ** -(math.log2(n) - 3)))
        return [start ** (i + 1) for i in range(n)]

    if math.log2(num_heads).is_integer():
        return np.asarray(pow2(num_heads), np.float32)
    closest = 2 ** int(math.floor(math.log2(num_heads)))
    extra = pow2(2 * closest)[0::2][:num_heads - closest]
    return np.asarray(pow2(closest) + extra, np.float32)


def _alibi_bias(slopes, q_pos, k_pos, num_kv_heads: int):
    """(…, Hkv, G, T, S) additive logit bias ``slope_h · (k - q)``.

    Softmax rows are shift-invariant, so this equals HF Bloom's
    ``slope_h · k`` form while keeping the biases ≤ 0 in the causal
    region (no large positive logits before masking).  ``q_pos``/
    ``k_pos``: (T, S)-broadcastable int arrays, or (B, T, S) ragged."""
    rel = (k_pos - q_pos).astype(jnp.float32)
    s = jnp.asarray(slopes, jnp.float32).reshape(num_kv_heads, -1)
    if rel.ndim == 3:  # ragged: (B, T, S) → (B, Hkv, G, T, S)
        return s[None, :, :, None, None] * rel[:, None, None]
    return s[:, :, None, None] * rel  # (Hkv, G, T, S)


def _group_query_heads(q, num_kv_heads: int):
    """(B, Hq, T, D) -> (B, Hkv, G, T, D) where G = Hq // Hkv."""
    B, Hq, T, D = q.shape
    group = Hq // num_kv_heads
    return q.reshape(B, num_kv_heads, group, T, D)


def _attend(q, k, v, mask, dropout_rate=0.0, dropout_rng=None, bias=None,
            scale=None, softcap=None):
    """Masked softmax attention with grouped query heads.

    q: (B, Hkv, G, T, D); k, v: (B, Hkv, S, D); mask: broadcastable to
    (B, Hkv, G, T, S) with True = attend; ``bias`` (same broadcast):
    additive pre-softmax logits (ALiBi); ``scale`` overrides the
    1/sqrt(D) score scaling (Gemma-2/3 ``query_pre_attn_scalar``);
    ``softcap`` applies Gemma-2 logit soft-capping ``c·tanh(s/c)`` after
    scaling, before bias/mask.
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    # HIGHEST pins true-f32 dot precision for f32 inputs: attention softmax
    # is precision-sensitive and some backends default f32 dots to bf16-
    # class multiplies.  bf16 inputs keep the MXU-native default.
    precision = (jax.lax.Precision.HIGHEST if q.dtype == jnp.float32
                 else jax.lax.Precision.DEFAULT)
    logits = jnp.einsum("bhgtd,bhsd->bhgts", q, k,
                        preferred_element_type=jnp.float32,
                        precision=precision) * scale
    if softcap is not None:
        logits = softcap * jnp.tanh(logits / softcap)
    if bias is not None:
        logits = logits + bias
    logits = jnp.where(mask, logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    if dropout_rate > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_rate), 0.0)
    probs = probs.astype(v.dtype)
    return jnp.einsum("bhgts,bhsd->bhgtd", probs, v, precision=precision)


def causal_attention_reference(q, k, v, dropout_rate=0.0, dropout_rng=None,
                               window: Optional[int] = None,
                               alibi: Optional[np.ndarray] = None,
                               scale: Optional[float] = None,
                               softcap: Optional[float] = None):
    """Pure-jnp causal attention. q: (B, Hq, T, D); k, v: (B, Hkv, T, D).

    ``window``: sliding-window width — query t attends keys in
    ``(t - window, t]`` (HF Mistral/Gemma-2 semantics: the window *includes*
    the query position and the ``window - 1`` keys before it).
    ``alibi``: per-query-head slopes — linear position bias added to the
    logits instead of any rotary/learned positions."""
    B, Hq, T, D = q.shape
    num_kv_heads = k.shape[1]
    qg = _group_query_heads(q, num_kv_heads)
    q_pos = jnp.arange(T)[:, None]
    k_pos = jnp.arange(T)[None, :]
    mask = k_pos <= q_pos
    if window is not None:
        mask &= k_pos > q_pos - int(window)
    bias = (None if alibi is None
            else _alibi_bias(alibi, q_pos, k_pos, num_kv_heads))
    out = _attend(qg, k, v, mask, dropout_rate, dropout_rng, bias=bias,
                  scale=scale, softcap=softcap)
    return out.reshape(B, Hq, T, D)


def causal_attention(q, k, v, dropout_rate=0.0, dropout_rng=None,
                     platform=None, window: Optional[int] = None,
                     alibi: Optional[np.ndarray] = None,
                     scale: Optional[float] = None,
                     softcap: Optional[float] = None):
    """Causal self-attention; dispatches to the Pallas kernel on TPU.

    ``platform`` is the caller's execution-placement hint ('tpu'/'cpu'/...).
    Inside jit the arrays are tracers, so without the hint the gate can only
    guess from global config — and a model explicitly placed on CPU on a
    TPU-attached host would dispatch kernels that cannot lower for CPU.

    ``alibi``: per-query-head slopes — the kernels add the linear
    position bias in-tile (SMEM slopes, same pattern as the dropout
    seed), so BLOOM/MPT-class models keep the fused path.  ``softcap``
    (Gemma-2 logit capping) routes the TRAINING path to the jnp
    reference — the flash backward has no capped-gradient variant yet;
    the decode kernels apply the cap in-tile, so serving stays fused.
    """
    if softcap is not None:
        # Trace-time, one-shot (matching the SP fallback-signal
        # convention): Gemma-2-class training/prefill silently losing the
        # fused path is a perf cliff the operator should see.
        _warn_once("softcap_reference",
                   "logit softcap: flash kernel unavailable for the "
                   "training/prefill path (no capped-gradient backward); "
                   "using the O(T^2) jnp reference")
        return causal_attention_reference(q, k, v, dropout_rate,
                                          dropout_rng, window=window,
                                          alibi=alibi, scale=scale,
                                          softcap=softcap)
    if _use_flash(q, k, platform):
        from penroz_tpu.ops.pallas import flash_attention as fa
        if dropout_rate > 0.0 and dropout_rng is not None:
            # Stay fused under dropout (the reference keeps fused SDPA with
            # dropout): the kernel derives its keep-mask from an int32 seed
            # via an in-kernel position hash — distributional parity with
            # the bernoulli fallback, zero HBM mask traffic.
            seed = jax.random.randint(dropout_rng, (), 0,
                                      jnp.iinfo(jnp.int32).max,
                                      dtype=jnp.int32)
            return fa.flash_attention(q, k, v, causal=True,
                                      dropout_rate=float(dropout_rate),
                                      seed=seed, window=window,
                                      alibi=alibi, scale=scale)
        return fa.flash_attention(q, k, v, causal=True, window=window,
                                  alibi=alibi, scale=scale)
    return causal_attention_reference(q, k, v, dropout_rate, dropout_rng,
                                      window=window, alibi=alibi,
                                      scale=scale)


def cached_attention(q, k_full, v_full, offset, length,
                     dropout_rate=0.0, dropout_rng=None, platform=None,
                     k_scale=None, v_scale=None,
                     window: Optional[int] = None,
                     alibi: Optional[np.ndarray] = None,
                     scale: Optional[float] = None,
                     softcap: Optional[float] = None):
    """Attention over a preallocated KV cache.

    q: (B, Hq, T, D) new queries at positions ``offset + [0, T)``.
    k_full/v_full: (B, Hkv, S_max, D) cache contents after the current append.
    ``length`` is the total valid length (offset + T).  Keys at index j are
    attended when ``j <= offset + t`` (combined causal + validity mask).
    With ``k_scale``/``v_scale`` (B, Hkv, S_max, 1) the cache is int8
    (TurboQuant): the kernel dequantizes per VMEM tile; this jnp fallback
    dequantizes the dense view (also the numerical oracle).

    Dispatches to the Pallas decode kernel on TPU (compute bounded by the
    valid length, not S_max); this jnp path is its correctness oracle.
    """
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be passed together "
                         "(int8 caches carry scales for both streams)")
    use_kernel = dropout_rate == 0.0 and _use_flash_decode(q, k_full,
                                                           platform)
    if not use_kernel and dropout_rate == 0.0:
        _, Hq, T, D = q.shape
        Hkv, S = k_full.shape[1], k_full.shape[2]
        if (T > 1 and S >= 128 and S % 128 == 0 and D in (64, 128, 256)
                and Hq % Hkv == 0 and (Hq // Hkv) * T > 512
                and not _flash_disabled()
                and _tpu_platform(q, platform)):
            # A multi-token chunk (chunked prefill) whose ONLY disqualifier
            # is the decode kernel's (Hq/Hkv)·T ≤ 512 tile budget runs the
            # dense jnp path over S_max — correct but a perf cliff; static
            # shapes, so this is trace-time like the softcap signal above.
            _warn_once("chunk_off_kernel",
                       "cached attention chunk (T=%d, Hq=%d, Hkv=%d) "
                       "exceeds the decode kernel's tile budget; using "
                       "the jnp reference — a smaller PENROZ_PREFILL_CHUNK "
                       "keeps chunked prefill on the fused path", T, Hq,
                       Hkv)
    if use_kernel:
        from penroz_tpu.ops.pallas import decode_attention as da
        return da.decode_attention(q, k_full, v_full, offset, length,
                                   k_scale=k_scale, v_scale=v_scale,
                                   window=window, alibi=alibi,
                                   scale=scale, softcap=softcap)
    if k_scale is not None:
        k_full = (k_full.astype(jnp.float32) * k_scale).astype(q.dtype)
        v_full = (v_full.astype(jnp.float32) * v_scale).astype(q.dtype)
    B, Hq, T, D = q.shape
    S = k_full.shape[2]
    num_kv_heads = k_full.shape[1]
    qg = _group_query_heads(q, num_kv_heads)
    key_idx = jnp.arange(S, dtype=jnp.int32)
    lengths = jnp.asarray(length, jnp.int32)
    if lengths.ndim >= 1:
        # Ragged batch (same contract as the kernels — an ARRAY length of
        # any size opts in, so a (1,) length with B=1 behaves identically
        # on the kernel and oracle paths): per-sequence valid lengths,
        # each row's queries sit at positions length_b - T + t; ``offset``
        # is ignored, exactly as the kernels derive it from length.
        from penroz_tpu.ops.pallas.decode_attention import normalize_lengths
        lengths = normalize_lengths(lengths, B)
        q_pos = (lengths[:, None] - T) + jnp.arange(T, dtype=jnp.int32)
        mask = key_idx[None, None, :] <= q_pos[:, :, None]  # (B, T, S)
        if window is not None:
            mask &= key_idx[None, None, :] > q_pos[:, :, None] - int(window)
        bias = (None if alibi is None
                else _alibi_bias(alibi, q_pos[:, :, None],
                                 key_idx[None, None, :], num_kv_heads))
        mask = mask[:, None, None]  # (B, 1, 1, T, S)
    else:
        q_pos = offset + jnp.arange(T, dtype=jnp.int32)
        mask = key_idx[None, :] <= q_pos[:, None]  # (T, S)
        if window is not None:
            mask &= key_idx[None, :] > q_pos[:, None] - int(window)
        bias = (None if alibi is None
                else _alibi_bias(alibi, q_pos[:, None], key_idx[None, :],
                                 num_kv_heads))
    out = _attend(qg, k_full, v_full, mask, dropout_rate, dropout_rng,
                  bias=bias, scale=scale, softcap=softcap)
    return out.reshape(B, Hq, T, D)


def paged_cached_attention(q, flat_k, flat_v, block_table, page_size: int,
                           offset, length, dropout_rate=0.0,
                           dropout_rng=None, platform=None,
                           k_scale=None, v_scale=None,
                           window: Optional[int] = None,
                           alibi: Optional[np.ndarray] = None,
                           scale: Optional[float] = None,
                           softcap: Optional[float] = None):
    """Cached attention over a paged KV pool (block table indirection).

    On TPU dispatches to the paged Pallas kernel — one physical page of K/V
    resident in VMEM at a time, so context length is HBM-bounded.  With
    ``k_scale``/``v_scale`` the pools are int8 (TurboQuant + paged) and the
    kernel dequantizes per page in VMEM.  The fallback (also the correctness
    oracle) gathers the dense (dequantized) view and reuses
    :func:`cached_attention`'s jnp path.
    """
    if dropout_rate == 0.0 and _use_paged_kernel(q, flat_k, block_table,
                                                 page_size, platform):
        from penroz_tpu.ops.pallas import paged_attention as pa
        return pa.paged_decode_attention(q, flat_k, flat_v, block_table,
                                         page_size, offset, length,
                                         k_scale=k_scale, v_scale=v_scale,
                                         window=window, alibi=alibi,
                                         scale=scale, softcap=softcap)
    B = q.shape[0]
    pages_per_seq = block_table.shape[1]
    max_len = pages_per_seq * page_size
    all_pos = jnp.arange(max_len, dtype=jnp.int32)
    phys = jnp.maximum(block_table[:, all_pos // page_size], 0)
    rows = phys * page_size + all_pos % page_size  # (B, max_len)
    # flat pools are head-major (Hkv, pool_rows, D)
    gather = lambda flat: jnp.take(flat, rows, axis=1,
                                   mode="clip").transpose(1, 0, 2, 3)
    if k_scale is not None:
        k_full = (gather(flat_k).astype(jnp.float32)
                  * gather(k_scale)).astype(q.dtype)
        v_full = (gather(flat_v).astype(jnp.float32)
                  * gather(v_scale)).astype(q.dtype)
    else:
        k_full, v_full = gather(flat_k), gather(flat_v)
    # Dense-gather fallback; cached_attention may still use the contiguous
    # decode kernel on the gathered views when shapes allow.
    return cached_attention(q, k_full, v_full, offset,
                            length, dropout_rate, dropout_rng,
                            platform=platform, window=window, alibi=alibi,
                            scale=scale, softcap=softcap)


def ragged_paged_attention_reference(q, flat_k, flat_v, block_table,
                                     page_size: int, descs,
                                     k_scale=None, v_scale=None,
                                     window: Optional[int] = None,
                                     alibi: Optional[np.ndarray] = None,
                                     scale: Optional[float] = None,
                                     softcap: Optional[float] = None):
    """Sequential-oracle attention for a PACKED mixed batch.

    q: (1, Hq, Tp, D) packed queries in descriptor order (Tp = num_descs
    · block_q); descs: (num_descs, 4) int32 ``(row, q_pos0, q_valid,
    kv_len)`` — see ops/pallas/ragged_paged_attention.py.  Gathers each
    descriptor's dense KV view through the block table and reuses
    :func:`_attend` with the per-token causal mask, so the result equals
    running each row's phase (prefill chunk / decode step / verify span)
    through :func:`paged_cached_attention` one at a time.  Padding slots
    (row = -1 or t ≥ q_valid) come back zero, matching the kernel.
    """
    _, Hq, Tp, D = q.shape
    Hkv = flat_k.shape[0]
    group = Hq // Hkv
    NB = descs.shape[0]
    BQ = Tp // NB
    pages_per_seq = block_table.shape[1]
    max_len = pages_per_seq * page_size
    descs = jnp.asarray(descs, jnp.int32)
    row = jnp.maximum(descs[:, 0], 0)
    all_pos = jnp.arange(max_len, dtype=jnp.int32)
    phys = jnp.maximum(block_table[row][:, all_pos // page_size], 0)
    rows = phys * page_size + all_pos % page_size  # (NB, max_len)
    gather = lambda flat: jnp.take(flat, rows, axis=1,
                                   mode="clip").transpose(1, 0, 2, 3)
    if k_scale is not None:
        k_dense = (gather(flat_k).astype(jnp.float32)
                   * gather(k_scale)).astype(q.dtype)
        v_dense = (gather(flat_v).astype(jnp.float32)
                   * gather(v_scale)).astype(q.dtype)
    else:
        k_dense, v_dense = gather(flat_k), gather(flat_v)
    # (1, Hq, Tp, D) → (NB, Hkv, group, BQ, D): one "batch" entry per
    # descriptor block (head order is kv-major, pure reshape + transpose).
    qg = q[0].reshape(Hkv, group, NB, BQ, D).transpose(2, 0, 1, 3, 4)
    t = jnp.arange(BQ, dtype=jnp.int32)
    q_abs = descs[:, 1:2] + t[None, :]                    # (NB, BQ)
    valid_q = (t[None, :] < descs[:, 2:3]) & (descs[:, 0:1] >= 0)
    k_idx = jnp.arange(max_len, dtype=jnp.int32)
    mask = valid_q[:, :, None] & (k_idx[None, None, :] <= q_abs[:, :, None])
    if window is not None:
        mask &= k_idx[None, None, :] > q_abs[:, :, None] - int(window)
    bias = (None if alibi is None
            else _alibi_bias(alibi, q_abs[:, :, None],
                             k_idx[None, None, :], Hkv))
    out = _attend(qg, k_dense, v_dense, mask[:, None, None], bias=bias,
                  scale=scale, softcap=softcap)
    # Fully-masked padding slots softmax to uniform in _attend; zero them
    # like the kernel (l = 0 → output 0) so parity is exact slot-for-slot.
    out = out * valid_q[:, None, None, :, None].astype(out.dtype)
    return out.transpose(1, 2, 0, 3, 4).reshape(1, Hq, Tp, D)


def ragged_paged_cached_attention(q, flat_k, flat_v, block_table,
                                  page_size: int, descs, platform=None,
                                  k_scale=None, v_scale=None,
                                  window: Optional[int] = None,
                                  alibi: Optional[np.ndarray] = None,
                                  scale: Optional[float] = None,
                                  softcap: Optional[float] = None):
    """Unified mixed-batch attention over a paged pool (the ragged
    serving fast path).

    On TPU dispatches to the ragged Pallas kernel — one dispatch covers
    prefill chunks, decode steps and spec-verify spans side by side,
    reading KV through the block table (ops/pallas/
    ragged_paged_attention.py).  The fallback (also the correctness
    oracle) gathers per-descriptor dense views.
    """
    if _use_ragged_kernel(q, flat_k, block_table, page_size, descs,
                          platform):
        from penroz_tpu.ops.pallas import ragged_paged_attention as rpa
        return rpa.ragged_paged_attention(q, flat_k, flat_v, block_table,
                                          page_size, descs,
                                          k_scale=k_scale, v_scale=v_scale,
                                          window=window, alibi=alibi,
                                          scale=scale, softcap=softcap)
    return ragged_paged_attention_reference(q, flat_k, flat_v, block_table,
                                            page_size, descs,
                                            k_scale=k_scale,
                                            v_scale=v_scale, window=window,
                                            alibi=alibi, scale=scale,
                                            softcap=softcap)


def _use_ragged_kernel(q, flat_k, block_table, page_size: int, descs,
                       platform=None) -> bool:
    if _flash_disabled() or not _tpu_platform(q, platform):
        return False
    _, Hq, Tp, D = q.shape
    Hkv = flat_k.shape[0]
    NB = descs.shape[0]
    if NB == 0 or Tp % NB != 0:
        return False
    block_q = Tp // NB
    return (D in (64, 128, 256) and page_size % 8 == 0 and page_size >= 8
            and Hq % Hkv == 0 and (Hq // Hkv) * block_q <= 512)


def _use_paged_kernel(q, flat_k, block_table, page_size: int,
                      platform=None) -> bool:
    if _flash_disabled() or not _tpu_platform(q, platform):
        return False
    B, Hq, T, D = q.shape
    Hkv = flat_k.shape[0]
    return (D in (64, 128, 256) and page_size % 8 == 0 and page_size >= 8
            and Hq % Hkv == 0 and (Hq // Hkv) * T <= 512)


def _flash_disabled() -> bool:
    """PENROZ_DISABLE_FLASH=1 disables the Pallas *attention* kernels only —
    other Pallas consumers (fused CE, embedding backward) gate on
    :func:`_tpu_platform` directly so an attention A/B stays isolated."""
    import os
    return os.environ.get("PENROZ_DISABLE_FLASH", "0") == "1"


def _tpu_platform(x, platform=None) -> bool:
    """Whether computation on ``x`` will run on TPU (pure platform check).

    ``platform`` — the caller's placement hint — wins when given.  Otherwise:
    a concrete array knows its device; a tracer doesn't, and
    ``jax.default_backend()`` reports the highest-priority backend even when
    ``jax_default_device`` pins computation elsewhere (e.g. CPU tests on a
    TPU-attached host), so the config is consulted before the backend.
    """
    if platform is not None:
        return platform in ("tpu", "axon")
    try:
        if isinstance(x, jax.Array) and not isinstance(x, jax.core.Tracer):
            platform = next(iter(x.devices())).platform
        else:
            dev = jax.config.jax_default_device
            if dev is None:
                platform = jax.default_backend()
            elif isinstance(dev, str):  # modern JAX accepts platform strings
                platform = dev
            else:
                platform = dev.platform
    except Exception:
        try:
            platform = jax.default_backend()
        except Exception:
            return False
    return platform in ("tpu", "axon")


def _use_flash(q, k, platform=None) -> bool:
    """Whether the Pallas flash kernel applies to these shapes/platform."""
    if _flash_disabled() or not _tpu_platform(q, platform):
        return False
    B, Hq, T, D = q.shape
    Hkv = k.shape[1]
    # MXU-friendly: head dim multiple of 128 lane requirement handled by the
    # kernel via padding; sequence must be long enough to tile.
    return T >= 128 and T % 128 == 0 and D in (64, 128, 256) and Hq % Hkv == 0


def _use_flash_decode(q, k_full, platform=None) -> bool:
    """Whether the Pallas decode kernel applies (static shape checks only —
    offset/length are traced)."""
    if _flash_disabled() or not _tpu_platform(q, platform):
        return False
    B, Hq, T, D = q.shape
    Hkv, S = k_full.shape[1], k_full.shape[2]
    # K/V stream through the kernel grid one tile at a time, so S is
    # HBM-bounded (no VMEM gate) — bandwidth tracks the valid length via
    # the clamped index map, not S_max.
    return (S >= 128 and S % 128 == 0 and D in (64, 128, 256)
            and Hq % Hkv == 0 and (Hq // Hkv) * T <= 512)
