"""Pallas TPU chunked gated linear-attention (SSD) scan.

The token-sequential recurrence

    S_t = g_t S_{t-1} + k_t ⊗ v_t,   y_t = q_t · S_t

is pure VPU latency when unrolled per token.  The chunked (state-space
duality) form turns all but one small carry into MXU matmuls: with
``La_t = Σ_{i≤t} log g_i`` (inclusive, per chunk)

    y_t   = e^{La_t} (q_t · S_0) + Σ_{j≤t} e^{La_t − La_j} (q_t · k_j) v_j
    S_end = e^{La_L} S_0 + Σ_j e^{La_L − La_j} k_j ⊗ v_j

Both exponents are ≤ 0 (gates in (0, 1)), so every decay factor is in
(0, 1] — no rescaling pass needed.

Grid ``(B·H, T/block_t)`` with the chunk axis innermost and ``arbitrary``;
the (dk, dv) carry state lives in a VMEM scratch that persists across the
chunk loop (same structure as ops/pallas/cross_entropy.py's running stats).
The jnp twin :func:`gla_chunked_reference` implements the identical chunk
math for the interpret-mode oracle test, and the *sequential* oracle lives
in ops/ssm.py::gla_full_reference.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_T = 128
_LOG_EPS = 1e-6  # floor before log: sigmoid underflow -> exactly-0 gate

# jax renamed TPUCompilerParams → CompilerParams across versions; take
# whichever this jax ships (same shim as ragged_paged_attention.py).
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or getattr(pltpu, "TPUCompilerParams")


def _chunk_body(q, k, v, lg, s0):
    """One chunk in fp32: (y, s_end) from (block_t, ·) operands + carry."""
    la = jnp.cumsum(lg)  # inclusive
    y = (q * jnp.exp(la)[:, None]) @ s0
    scores = q @ k.T
    t = la.shape[0]
    row = jax.lax.broadcasted_iota(jnp.int32, (t, t), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (t, t), 1)
    decay = jnp.where(row >= col, jnp.exp(la[:, None] - la[None, :]), 0.0)
    y = y + (scores * decay) @ v
    kd = k * jnp.exp(la[-1] - la)[:, None]
    s_end = jnp.exp(la[-1]) * s0 + kd.T @ v
    return y, s_end


def _gla_kernel(q_ref, k_ref, v_ref, lg_ref, o_ref, s_scr, *, block_t: int):
    tj = pl.program_id(1)

    @pl.when(tj == 0)
    def _init():
        s_scr[...] = jnp.zeros_like(s_scr)

    q = q_ref[0].astype(jnp.float32)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    lg = lg_ref[0].astype(jnp.float32)
    y, s_end = _chunk_body(q, k, v, lg, s_scr[...])
    s_scr[...] = s_end
    o_ref[0] = y.astype(o_ref.dtype)


def gla_chunked(q, k, v, g, block_t: int = DEFAULT_BLOCK_T,
                interpret: bool = False):
    """Chunked GLA over (B, T, H, ·) inputs; gates g (B, T, H) in (0, 1).

    Returns y (B, T, H, dv) fp32.  The ragged tail is padded with g = 1,
    k = 0 — the pad tokens leave the carry untouched and their outputs are
    sliced off.
    """
    B, T, H, dk = q.shape
    dv = v.shape[-1]
    block_t = min(block_t, max(T, 8))
    pad = -T % block_t
    lg = jnp.log(jnp.maximum(g.astype(jnp.float32), _LOG_EPS))
    if pad:
        q = jnp.pad(q, ((0, 0), (0, pad), (0, 0), (0, 0)))
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
        lg = jnp.pad(lg, ((0, 0), (0, pad), (0, 0)))
    Tp = T + pad

    def flat(x):  # (B, Tp, H, d) -> (B*H, Tp, d)
        return x.transpose(0, 2, 1, 3).reshape(B * H, Tp, x.shape[-1])

    qf, kf, vf = flat(q), flat(k), flat(v)
    lgf = lg.transpose(0, 2, 1).reshape(B * H, Tp)
    num_t = Tp // block_t
    kernel = functools.partial(_gla_kernel, block_t=block_t)
    out = pl.pallas_call(
        kernel,
        grid=(B * H, num_t),
        in_specs=[
            pl.BlockSpec((1, block_t, dk), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_t, dk), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_t, dv), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_t), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_t, dv), lambda i, j: (i, j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B * H, Tp, dv), jnp.float32),
        scratch_shapes=[pltpu.VMEM((dk, dv), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=int(4 * B * H * Tp * block_t * (dk + dv)),
            bytes_accessed=int(qf.size * 4 + kf.size * 4 + 2 * vf.size * 4),
            transcendentals=int(B * H * Tp * (block_t + 2))),
        interpret=interpret,
    )(qf, kf, vf, lgf)
    return (out.reshape(B, H, Tp, dv).transpose(0, 2, 1, 3))[:, :T]


def gla_chunked_reference(q, k, v, g, block_t: int = DEFAULT_BLOCK_T):
    """jnp twin of the kernel's chunk math (host-side correctness oracle)."""
    B, T, H, dk = q.shape
    dv = v.shape[-1]
    block_t = min(block_t, max(T, 8))
    pad = -T % block_t
    lg = jnp.log(jnp.maximum(g.astype(jnp.float32), _LOG_EPS))
    if pad:
        q = jnp.pad(q, ((0, 0), (0, pad), (0, 0), (0, 0)))
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
        lg = jnp.pad(lg, ((0, 0), (0, pad), (0, 0)))
    Tp = T + pad
    qf = q.transpose(0, 2, 1, 3).reshape(B * H, Tp, dk).astype(jnp.float32)
    kf = k.transpose(0, 2, 1, 3).reshape(B * H, Tp, dk).astype(jnp.float32)
    vf = v.transpose(0, 2, 1, 3).reshape(B * H, Tp, dv).astype(jnp.float32)
    lgf = lg.transpose(0, 2, 1).reshape(B * H, Tp)

    def per_seq(qs, ks, vs, lgs):
        def step(s0, xt):
            qc, kc, vc, lgc = xt
            y, s_end = _chunk_body(qc, kc, vc, lgc, s0)
            return s_end, y
        xs = (qs.reshape(-1, block_t, dk), ks.reshape(-1, block_t, dk),
              vs.reshape(-1, block_t, dv), lgs.reshape(-1, block_t))
        _, ys = jax.lax.scan(step, jnp.zeros((dk, dv), jnp.float32), xs)
        return ys.reshape(Tp, dv)

    out = jax.vmap(per_seq)(qf, kf, vf, lgf)
    return (out.reshape(B, H, Tp, dv).transpose(0, 2, 1, 3))[:, :T]
