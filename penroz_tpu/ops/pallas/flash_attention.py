"""Pallas TPU flash-attention (causal, GQA, dropout) — forward + backward.

Blockwise online-softmax attention.  The query block stays resident in VMEM
while K/V blocks stream through the innermost grid dimension, carrying
running (max, sum, accumulator) statistics in VMEM scratch — so neither the
(T, S) score matrix nor the full (S, D) K/V ever sit in VMEM at once, and
context length is bounded by HBM only.  This is the fusion the reference
gets from ``F.scaled_dot_product_attention``'s cuDNN flash kernels
(reference: neural_net_layers.py:92), built directly on the MXU.

The backward is the standard flash-attention two-kernel split with in-kernel
recompute from the forward's saved logsumexp:

- ``_dq_kernel``    — query blocks resident, K/V streaming; produces dQ.
- ``_dkv_kernel``   — key/value blocks resident, Q/dO streaming; produces
  per-query-head dK/dV (summed over GQA groups outside).

Dropout runs *inside* the kernels via a counter-based position hash
(lowbias32-style mixer over (q_pos, k_pos, seed)), so the keep-mask needs no
HBM storage, is identical across the forward and both backward kernels by
construction, and — unlike the hardware PRNG — can be reproduced exactly by
the jnp oracle (:func:`dropout_keep_mask_reference`) for equivalence tests.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
_NEG_INF = -1e30
_LANES = 128  # f32 scratch lane width for the (m, l) carries
_HEAD_SEED_PRIME = np.int32(0x632BE5A7)


def _dot_precision(dtype):
    """HIGHEST for f32 operands (some backends default f32 dots to bf16-
    class multiplies); default for bf16 — Mosaic rejects fp32 contract
    precision on bf16 operands, and the MXU is bf16-native anyway."""
    return (jax.lax.Precision.HIGHEST if dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)


def _keep_mask(q_pos, k_pos, seed, rate: float):
    """Boolean keep-mask from a position hash (True = keep).

    ``q_pos``/``k_pos``: int32 arrays broadcastable against each other
    (absolute sequence positions); ``seed``: int32 scalar already mixed
    with the (batch, head) index.  Pure jnp — traced identically inside
    the Pallas kernels and in the test oracle, so the mask is exactly
    reproducible.
    """
    x = (q_pos.astype(jnp.uint32) * np.uint32(0x9E3779B1)
         ^ k_pos.astype(jnp.uint32) * np.uint32(0x85EBCA77)
         ^ seed.astype(jnp.uint32) * np.uint32(0xC2B2AE3D))
    x = x ^ (x >> 16)
    x = x * np.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * np.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    threshold = np.uint32(min(int((1.0 - rate) * 2.0 ** 32), 2 ** 32 - 1))
    return x < threshold


def dropout_keep_mask_reference(seed, b, h, num_heads: int, T: int, S: int,
                                rate: float):
    """(T, S) keep-mask the kernels generate for batch ``b``, head ``h``."""
    q_pos = jnp.arange(T, dtype=jnp.int32)[:, None]
    k_pos = jnp.arange(S, dtype=jnp.int32)[None, :]
    seed_bh = (jnp.asarray(seed, jnp.int32)
               + jnp.asarray(b * num_heads + h, jnp.int32)
               * _HEAD_SEED_PRIME)
    return _keep_mask(q_pos, k_pos, seed_bh, rate)


def _block_positions(qi, kj, block_q: int, block_k: int):
    """Absolute (q_pos, k_pos) int32 grids of shape (block_q, block_k)."""
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = kj * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return q_pos, k_pos


def _head_seed(seed_ref, b, h, num_heads: int):
    return seed_ref[0] + (b * num_heads + h) * _HEAD_SEED_PRIME


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _band_mask(q_pos, k_pos, causal: bool, window):
    """Causal (+ optional sliding-window lower bound) mask, or None."""
    if not causal:
        return None
    mask = k_pos <= q_pos
    if window is not None:
        mask &= k_pos > q_pos - window
    return mask


def _live_block(qi, kj, block_q: int, block_k: int, causal: bool, window):
    """Whether block (qi, kj) intersects the attention band.  Under causal,
    blocks strictly above the diagonal contribute nothing; with a sliding
    window, blocks entirely left of the band do not either — this skip is
    where the window's compute savings come from."""
    if not causal:
        return True
    live = kj * block_k <= qi * block_q + block_q - 1
    if window is not None:
        live &= (kj + 1) * block_k - 1 > qi * block_q - window
    return live


def _fwd_kernel(seed_ref, alibi_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, causal: bool, sm_scale: float,
                block_q: int, block_k: int, num_k: int, num_heads: int,
                dropout_rate: float, window=None, use_alibi: bool = False):
    b, h, qi, kj = (pl.program_id(0), pl.program_id(1), pl.program_id(2),
                    pl.program_id(3))

    @pl.when(kj == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    live = _live_block(qi, kj, block_q, block_k, causal, window)

    @pl.when(live)
    def _step():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_dot_precision(q.dtype)) * sm_scale
        q_pos, k_pos = _block_positions(qi, kj, block_q, block_k)
        if use_alibi:
            # ALiBi: per-head linear position bias slope·(k−q), ≤ 0 in
            # the causal region; slopes ride SMEM like the dropout seed.
            s = s + alibi_ref[h] * (k_pos - q_pos).astype(jnp.float32)
        mask = _band_mask(q_pos, k_pos, causal, window)
        if mask is not None:
            s = jnp.where(mask, s, _NEG_INF)
        m_prev = m_scr[:, 0]
        l_prev = l_scr[:, 0]
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        if window is not None:
            # _NEG_INF is finite (-1e30): a row whose window lies entirely
            # outside this tile has s == m_new == -1e30 and exp(s - m_new)
            # would be 1, not 0 — zero masked entries explicitly.
            p = jnp.where(mask, p, 0.0)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        if dropout_rate > 0.0:
            # l accumulates the *undropped* probabilities (dropout applies
            # after softmax normalization); only the V-contraction drops.
            keep = _keep_mask(q_pos, k_pos,
                              _head_seed(seed_ref, b, h, num_heads),
                              dropout_rate)
            p_acc = jnp.where(keep, p, 0.0) * (1.0 / (1.0 - dropout_rate))
        else:
            p_acc = p
        acc_scr[...] = acc_scr[...] * alpha[:, None] + jax.lax.dot_general(
            p_acc.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_dot_precision(v.dtype))
        m_scr[...] = jax.lax.broadcast_in_dim(m_new, m_scr.shape, (0,))
        l_scr[...] = jax.lax.broadcast_in_dim(l_new, l_scr.shape, (0,))

    @pl.when(kj == num_k - 1)
    def _finish():
        l = l_scr[:, 0]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[...] / l_safe[:, None]).astype(o_ref.dtype)
        lse_ref[0, 0] = (m_scr[:, 0] + jnp.log(l_safe))[:, None]


def _largest_dividing_block(n: int, preferred: int) -> int:
    """Largest power-of-two block ≤ preferred that divides n (min 128)."""
    block = min(preferred, n)
    while block > 128 and n % block != 0:
        block //= 2
    return block


def _flash_forward(q, k, v, causal: bool = True,
                   block_q: int = DEFAULT_BLOCK_Q,
                   block_k: int = DEFAULT_BLOCK_K,
                   dropout_rate: float = 0.0, seed=None,
                   interpret: bool = False, return_lse: bool = False,
                   window=None, alibi=None, scale=None):
    B, Hq, T, D = q.shape
    Hkv, S = k.shape[1], k.shape[2]
    group = Hq // Hkv
    # Blocks must tile the sequence exactly — otherwise tail queries would
    # never be written and tail keys never attended.
    block_q = _largest_dividing_block(T, block_q)
    block_k = _largest_dividing_block(S, block_k)
    if T % block_q != 0 or S % block_k != 0:
        raise ValueError(f"flash_attention requires T%{block_q}==0 and "
                         f"S%{block_k}==0; got T={T}, S={S}")
    sm_scale = float(scale) if scale is not None else 1.0 / (D ** 0.5)
    num_k = S // block_k
    if seed is None:
        seed = jnp.zeros((1,), jnp.int32)
    else:
        seed = jnp.asarray(seed, jnp.int32).reshape((1,))

    use_alibi = alibi is not None
    alibi_arr = (jnp.asarray(alibi, jnp.float32) if use_alibi
                 else jnp.zeros((1,), jnp.float32))
    grid = (B, Hq, T // block_q, num_k)
    kernel = functools.partial(
        _fwd_kernel, causal=causal, sm_scale=sm_scale, block_q=block_q,
        block_k=block_k, num_k=num_k, num_heads=Hq,
        dropout_rate=dropout_rate, window=window, use_alibi=use_alibi)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, i, j: (b, h // group, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, i, j: (b, h // group, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            # (…, 1) trailing lane: Mosaic requires the last two block dims
            # be (8, 128)-divisible or equal to the array dims.
            jax.ShapeDtypeStruct((B, Hq, T, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=int(4 * B * Hq * T * S * D * (0.5 if causal else 1.0)),
            bytes_accessed=int((q.size + k.size + v.size + q.size)
                               * q.dtype.itemsize),
            transcendentals=int(B * Hq * T * S)),
        interpret=interpret,
    )(seed, alibi_arr, q, k, v)
    return (out, lse) if return_lse else out


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _recompute_probs(q, k, lse, qi, kj, seed_ref, alibi_ref, b, h, *,
                     causal: bool,
                     sm_scale: float, block_q: int, block_k: int,
                     num_heads: int, dropout_rate: float, window=None,
                     use_alibi: bool = False):
    """Normalized probabilities p (and the dropout keep-scale) for one
    (query-block, key-block) tile, identical to the forward's math."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=_dot_precision(q.dtype)) * sm_scale
    q_pos, k_pos = _block_positions(qi, kj, block_q, block_k)
    if use_alibi:
        s = s + alibi_ref[h] * (k_pos - q_pos).astype(jnp.float32)
    mask = _band_mask(q_pos, k_pos, causal, window)
    if mask is not None:
        s = jnp.where(mask, s, _NEG_INF)
    p = jnp.exp(s - lse[:, None])
    if window is not None:
        # rows fully outside the window in this tile have lse == -1e30 too;
        # exp(s - lse) would be 1 — zero masked entries explicitly
        p = jnp.where(mask, p, 0.0)
    if dropout_rate > 0.0:
        keep = _keep_mask(q_pos, k_pos,
                          _head_seed(seed_ref, b, h, num_heads),
                          dropout_rate)
        drop_scale = jnp.where(keep, 1.0 / (1.0 - dropout_rate), 0.0)
    else:
        drop_scale = None
    return p, drop_scale


def _dq_kernel(seed_ref, alibi_ref, q_ref, k_ref, v_ref, lse_ref, delta_ref,
               do_ref, dq_ref, dq_scr, *, causal: bool, sm_scale: float,
               block_q: int, block_k: int, num_k: int, num_heads: int,
               dropout_rate: float, window=None, use_alibi: bool = False):
    b, h, qi, kj = (pl.program_id(0), pl.program_id(1), pl.program_id(2),
                    pl.program_id(3))

    @pl.when(kj == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    live = _live_block(qi, kj, block_q, block_k, causal, window)

    @pl.when(live)
    def _step():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        p, drop_scale = _recompute_probs(
            q, k, lse_ref[0, 0][:, 0], qi, kj, seed_ref, alibi_ref, b, h,
            causal=causal,
            sm_scale=sm_scale, block_q=block_q, block_k=block_k,
            num_heads=num_heads, dropout_rate=dropout_rate, window=window,
            use_alibi=use_alibi)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_dot_precision(v.dtype))
        if drop_scale is not None:
            dp = dp * drop_scale
        ds = p * (dp - delta_ref[0, 0]) * sm_scale
        dq_scr[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_dot_precision(k.dtype))

    @pl.when(kj == num_k - 1)
    def _finish():
        dq_ref[0, 0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(seed_ref, alibi_ref, q_ref, k_ref, v_ref, lse_ref,
                delta_ref, do_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *, causal: bool,
                sm_scale: float, block_q: int, block_k: int, num_q: int,
                num_heads: int, dropout_rate: float, window=None,
                use_alibi: bool = False):
    b, h, kj, qi = (pl.program_id(0), pl.program_id(1), pl.program_id(2),
                    pl.program_id(3))

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    live = _live_block(qi, kj, block_q, block_k, causal, window)

    @pl.when(live)
    def _step():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        p, drop_scale = _recompute_probs(
            q, k, lse_ref[0, 0][:, 0], qi, kj, seed_ref, alibi_ref, b, h,
            causal=causal,
            sm_scale=sm_scale, block_q=block_q, block_k=block_k,
            num_heads=num_heads, dropout_rate=dropout_rate, window=window,
            use_alibi=use_alibi)
        p_drop = p if drop_scale is None else p * drop_scale
        # dV += p̃ᵀ · dO
        dv_scr[...] += jax.lax.dot_general(
            p_drop.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_dot_precision(do.dtype))
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_dot_precision(v.dtype))
        if drop_scale is not None:
            dp = dp * drop_scale
        ds = p * (dp - delta_ref[0, 0]) * sm_scale
        # dK += dSᵀ · Q
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_dot_precision(q.dtype))

    @pl.when(qi == num_q - 1)
    def _finish():
        dk_ref[0, 0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, causal: bool, block_q: int,
                    block_k: int, dropout_rate: float, seed,
                    interpret: bool = False, window=None, alibi=None,
                    scale=None):
    B, Hq, T, D = q.shape
    Hkv, S = k.shape[1], k.shape[2]
    group = Hq // Hkv
    block_q = _largest_dividing_block(T, block_q)
    block_k = _largest_dividing_block(S, block_k)
    sm_scale = float(scale) if scale is not None else 1.0 / (D ** 0.5)
    num_q = T // block_q
    num_k = S // block_k
    if seed is None:
        seed = jnp.zeros((1,), jnp.int32)
    else:
        seed = jnp.asarray(seed, jnp.int32).reshape((1,))

    # δ_i = Σ_d dO_id · O_id — the softmax-backward row term; O(B·H·T·D),
    # cheap enough to fuse outside the kernels.
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)

    use_alibi = alibi is not None
    alibi_arr = (jnp.asarray(alibi, jnp.float32) if use_alibi
                 else jnp.zeros((1,), jnp.float32))
    seed_spec = pl.BlockSpec(memory_space=pltpu.SMEM)
    q_spec = pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0),
                          memory_space=pltpu.VMEM)
    kv_spec = pl.BlockSpec((1, 1, block_k, D),
                           lambda b, h, i, j: (b, h // group, j, 0),
                           memory_space=pltpu.VMEM)
    row_spec = pl.BlockSpec((1, 1, block_q, 1),
                            lambda b, h, i, j: (b, h, i, 0),
                            memory_space=pltpu.VMEM)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, causal=causal, sm_scale=sm_scale,
                          block_q=block_q, block_k=block_k, num_k=num_k,
                          num_heads=Hq, dropout_rate=dropout_rate,
                          window=window, use_alibi=use_alibi),
        grid=(B, Hq, num_q, num_k),
        in_specs=[seed_spec, seed_spec, q_spec, kv_spec, kv_spec, row_spec,
                  row_spec, q_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=int(5 * B * Hq * T * S * D * (0.5 if causal else 1.0)),
            bytes_accessed=int((3 * q.size + 2 * k.size)
                               * q.dtype.itemsize),
            transcendentals=int(B * Hq * T * S)),
        interpret=interpret,
    )(seed, alibi_arr, q, k, v, lse, delta, g)

    # K/V-resident kernel: Q, dO, lse, δ stream through the inner grid.
    # index maps take (b, h, kj, qi) — note q-row specs select on qi (dim 3).
    q_stream = pl.BlockSpec((1, 1, block_q, D),
                            lambda b, h, j, i: (b, h, i, 0),
                            memory_space=pltpu.VMEM)
    kv_res = pl.BlockSpec((1, 1, block_k, D),
                          lambda b, h, j, i: (b, h // group, j, 0),
                          memory_space=pltpu.VMEM)
    row_stream = pl.BlockSpec((1, 1, block_q, 1),
                              lambda b, h, j, i: (b, h, i, 0),
                              memory_space=pltpu.VMEM)
    dkv_out = pl.BlockSpec((1, 1, block_k, D),
                           lambda b, h, j, i: (b, h, j, 0),
                           memory_space=pltpu.VMEM)
    dk_ph, dv_ph = pl.pallas_call(
        functools.partial(_dkv_kernel, causal=causal, sm_scale=sm_scale,
                          block_q=block_q, block_k=block_k, num_q=num_q,
                          num_heads=Hq, dropout_rate=dropout_rate,
                          window=window, use_alibi=use_alibi),
        grid=(B, Hq, num_k, num_q),
        in_specs=[seed_spec, seed_spec, q_stream, kv_res, kv_res,
                  row_stream, row_stream, q_stream],
        out_specs=[dkv_out, dkv_out],
        out_shape=[jax.ShapeDtypeStruct((B, Hq, S, D), k.dtype),
                   jax.ShapeDtypeStruct((B, Hq, S, D), v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, D), jnp.float32),
                        pltpu.VMEM((block_k, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=int(5 * B * Hq * T * S * D * (0.5 if causal else 1.0)),
            bytes_accessed=int((3 * q.size + 4 * B * Hq * S * D)
                               * q.dtype.itemsize),
            transcendentals=int(B * Hq * T * S)),
        interpret=interpret,
    )(seed, alibi_arr, q, k, v, lse, delta, g)

    if group > 1:
        dk = dk_ph.reshape(B, Hkv, group, S, D).sum(axis=2).astype(k.dtype)
        dv = dv_ph.reshape(B, Hkv, group, S, D).sum(axis=2).astype(v.dtype)
    else:
        dk = dk_ph.astype(k.dtype)
        dv = dv_ph.astype(v.dtype)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom VJP
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(4, 5, 6, 7, 8, 9, 10, 11))
def _flash(q, k, v, seed, causal, block_q, block_k, dropout_rate, interpret,
           window, alibi, scale=None):
    out = _flash_forward(q, k, v, causal, block_q, block_k,
                         dropout_rate=dropout_rate, seed=seed,
                         interpret=interpret, window=window, alibi=alibi,
                         scale=scale)
    return out


def _flash_fwd_rule(q, k, v, seed, causal, block_q, block_k, dropout_rate,
                    interpret, window, alibi, scale=None):
    out, lse = _flash_forward(q, k, v, causal, block_q, block_k,
                              dropout_rate=dropout_rate, seed=seed,
                              interpret=interpret, return_lse=True,
                              window=window, alibi=alibi, scale=scale)
    return out, (q, k, v, seed, out, lse)


def _flash_bwd_rule(causal, block_q, block_k, dropout_rate, interpret,
                    window, alibi, scale, residuals, g):
    q, k, v, seed, out, lse = residuals
    dq, dk, dv = _flash_backward(q, k, v, out, lse, g, causal, block_q,
                                 block_k, dropout_rate, seed,
                                 interpret=interpret, window=window,
                                 alibi=alibi, scale=scale)
    return dq, dk, dv, np.zeros((), dtype=jax.dtypes.float0)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def _env_block(name: str, default: int) -> int:
    try:
        value = int(os.environ.get(name, default))
        if value <= 0:
            raise ValueError(value)
        return value
    except ValueError:
        import logging
        logging.getLogger(__name__).warning(
            "Invalid %s=%r; using default block %d", name,
            os.environ.get(name), default)
        return default


def flash_attention(q, k, v, causal: bool = True,
                    block_q: int | None = None,
                    block_k: int | None = None,
                    dropout_rate: float = 0.0, seed=None,
                    interpret: bool = False, window=None, alibi=None,
                    scale=None):
    """Flash attention with a fused flash backward.

    q: (B, Hq, T, D); k, v: (B, Hkv, S, D) with Hq % Hkv == 0.
    ``dropout_rate`` > 0 applies post-softmax dropout inside the kernels
    (mask derived from ``seed`` — pass a fresh int32 scalar per step).
    ``window``: sliding-window width (causal only) — query t attends keys
    in ``(t - window, t]``; off-band blocks are skipped in the grid.

    Block sizes default to ``PENROZ_FLASH_BLOCK_Q`` / ``PENROZ_FLASH_
    BLOCK_K`` (else 512) — read at TRACE time, so a long-context tuning
    sweep (bench.bench_long_context) can vary them per compiled program;
    an already-jitted caller does not re-read the env.
    """
    if block_q is None:
        block_q = _env_block("PENROZ_FLASH_BLOCK_Q", DEFAULT_BLOCK_Q)
    if block_k is None:
        block_k = _env_block("PENROZ_FLASH_BLOCK_K", DEFAULT_BLOCK_K)
    if seed is None:
        seed = jnp.zeros((), jnp.int32)
    if alibi is not None:
        # static tuple: slopes are a pure function of the head count, so
        # baking them into the trace costs nothing and keeps the
        # custom_vjp arity fixed
        alibi = tuple(float(a) for a in np.asarray(alibi).reshape(-1))
        if len(alibi) != q.shape[1]:
            raise ValueError(f"alibi needs one slope per query head "
                             f"({q.shape[1]}), got {len(alibi)}")
    return _flash(q, k, v, jnp.asarray(seed, jnp.int32), causal,
                  int(block_q), int(block_k), float(dropout_rate),
                  bool(interpret),
                  int(window) if window is not None else None, alibi,
                  float(scale) if scale is not None else None)
