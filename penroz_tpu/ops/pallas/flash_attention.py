"""Pallas TPU flash-attention (causal, GQA) — forward kernel.

Blockwise online-softmax attention: the query block stays resident in VMEM
while K/V blocks stream through, carrying running (max, sum, accumulator)
statistics.  This keeps the (T, S) score matrix out of HBM entirely — the
fusion the reference gets from ``F.scaled_dot_product_attention``'s cuDNN
flash kernels (reference: neural_net_layers.py:92), built here directly on
the MXU.

The backward pass recomputes attention via the jnp reference implementation
(flash keeps only O(T·D) residuals); a dedicated backward kernel is a later
optimization.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 256
_NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool,
                sm_scale: float):
    block_q = q_ref.shape[2]
    head_dim = q_ref.shape[3]
    seq_k = k_ref.shape[2]
    qi = pl.program_id(2)

    q = q_ref[0, 0]

    def body(j, carry):
        acc, m_prev, l_prev = carry
        k = k_ref[0, 0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, 0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    acc0 = jnp.zeros((block_q, head_dim), jnp.float32)
    m0 = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)

    if causal:
        # Only K blocks at or below this query block's diagonal contribute.
        hi = jax.lax.div(qi * block_q + block_q + block_k - 1, block_k)
        hi = jnp.minimum(hi, seq_k // block_k)
    else:
        hi = seq_k // block_k
    acc, m, l = jax.lax.fori_loop(0, hi, body, (acc0, m0, l0))
    l = jnp.where(l == 0.0, 1.0, l)
    o_ref[0, 0] = (acc / l[:, None]).astype(o_ref.dtype)


def _largest_dividing_block(n: int, preferred: int) -> int:
    """Largest power-of-two block ≤ preferred that divides n (min 128)."""
    block = min(preferred, n)
    while block > 128 and n % block != 0:
        block //= 2
    return block


def _flash_forward(q, k, v, causal: bool, block_q: int, block_k: int,
                   interpret: bool = False):
    B, Hq, T, D = q.shape
    Hkv, S = k.shape[1], k.shape[2]
    group = Hq // Hkv
    # Blocks must tile the sequence exactly — otherwise tail queries would
    # never be written and tail keys never attended.
    block_q = _largest_dividing_block(T, block_q)
    block_k = _largest_dividing_block(S, block_k)
    if T % block_q != 0 or S % block_k != 0:
        raise ValueError(f"flash_attention requires T%{block_q}==0 and "
                         f"S%{block_k}==0; got T={T}, S={S}")
    sm_scale = 1.0 / (D ** 0.5)

    grid = (B, Hq, T // block_q)
    kernel = functools.partial(_fwd_kernel, block_k=block_k, causal=causal,
                               sm_scale=sm_scale)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, i: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, S, D),
                         lambda b, h, i: (b, h // group, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, S, D),
                         lambda b, h, i: (b, h // group, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, D),
                               lambda b, h, i: (b, h, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=int(4 * B * Hq * T * S * D * (0.5 if causal else 1.0)),
            bytes_accessed=int((q.size + k.size + v.size + q.size)
                               * q.dtype.itemsize),
            transcendentals=int(B * Hq * T * S)),
        interpret=interpret,
    )(q, k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal: bool = True,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K):
    """Flash attention. q: (B, Hq, T, D); k, v: (B, Hkv, S, D) with Hq % Hkv == 0."""
    return _flash_forward(q, k, v, causal, block_q, block_k)


def _flash_fwd_rule(q, k, v, causal, block_q, block_k):
    return flash_attention(q, k, v, causal, block_q, block_k), (q, k, v)


def _flash_bwd_rule(causal, block_q, block_k, residuals, g):
    from penroz_tpu.ops.attention import causal_attention_reference
    q, k, v = residuals
    _, vjp = jax.vjp(lambda q_, k_, v_: causal_attention_reference(q_, k_, v_),
                     q, k, v)
    return vjp(g)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)
