"""Pallas TPU ragged paged attention: one dispatch over a mixed batch.

The serving engine's three phase-specialized programs — chunked prefill
(T = chunk), batched decode (T = 1) and speculative verify (T = K+1) —
become ONE kernel over a *packed* query array.  The packed axis is cut
into fixed ``block_q``-token blocks and each block carries a descriptor
``(row, q_pos0, q_valid, kv_len)``: which sequence it belongs to, the
absolute position of its first query token, how many of its ``block_q``
slots are real, and the row's total valid KV length after the current
append.  A decode step is one descriptor with ``q_valid = 1``; a
64-token prefill chunk is ``64 / block_q`` descriptors; a verify row is
``ceil((K+1)/block_q)`` — all side by side in the same grid, which is
what deletes the scheduler's phase distinction (serve/decode_scheduler).

KV is read straight through the paged block table (scalar-prefetched,
one physical page resident in VMEM per grid step, same dataflow as
ops/pallas/paged_attention.py) — no ``row_view`` dense materialization.
Out-of-band pages clamp their index so the DMA is elided, and the
*logical* key positions mask the clamped re-fetch to zero.  ALiBi,
logit softcap, sliding windows, GQA head grouping and int8 (TurboQuant)
per-token dequantization carry over from the decode kernels.

Grid: (descriptor, kv_head, logical_page); the page dimension is
sequential so online-softmax scratch persists across it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from penroz_tpu.ops.pallas.flash_attention import _LANES

# jax renamed TPUCompilerParams → CompilerParams across versions; take
# whichever this install provides.
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or getattr(pltpu, "TPUCompilerParams")

_NEG_INF = -1e30

#: Descriptor columns: (row, q_pos0, q_valid, kv_len).  ``row = -1`` marks
#: a padding descriptor (q_valid = 0); its queries mask out entirely and
#: its output block is zero.
DESC_COLS = 4
DEFAULT_BLOCK_Q = 8


def default_block_q() -> int:
    """Packed query tokens per descriptor block
    (``PENROZ_RAGGED_BLOCK_Q``, default 8 — the fp32 sublane tile, so a
    decode step wastes at most 7 padded query rows while a 256-token
    prefill chunk still amortizes to 32 well-shaped MXU blocks)."""
    import os
    raw = os.environ.get("PENROZ_RAGGED_BLOCK_Q", str(DEFAULT_BLOCK_Q))
    try:
        n = int(raw)
    except ValueError:
        return DEFAULT_BLOCK_Q
    return n if n >= 1 else DEFAULT_BLOCK_Q


def _ragged_kernel(desc_ref, table_ref, q_ref, k_ref, v_ref, *rest,
                   page_size: int, grid_pages: int, block_q: int,
                   group: int, sm_scale: float, quantized: bool,
                   window=None, use_alibi: bool = False, softcap=None):
    """One (descriptor, kv_head, page) step: the block's ``group·block_q``
    grouped query rows attend one physical page.

    q_ref: (1, group, block_q, D) — descriptor d's packed queries, row
    r ↦ (g = r // block_q, t = r % block_q).  k_ref/v_ref: (1, page_size,
    D) — the j-th logical page of the descriptor's sequence, fetched
    through the block table by the index map (clamped in-band).  The
    causal bound is *per query token*: key position kp is attended when
    ``kp ≤ q_pos0 + t`` — exactly the sequential per-phase oracle's mask,
    so a mixed batch is bit-identical to running its phases one by one.
    """
    rest = list(rest)
    ks_ref = vs_ref = slopes_ref = None
    if quantized:
        ks_ref, vs_ref = rest[:2]
        rest = rest[2:]
    if use_alibi:
        slopes_ref = rest[0]
        rest = rest[1:]
    o_ref, m_scr, l_scr, acc_scr = rest
    d = pl.program_id(0)
    j = pl.program_id(2)
    gt = group * block_q
    q_pos0 = desc_ref[d * DESC_COLS + 1]
    q_valid = desc_ref[d * DESC_COLS + 2]
    # Keys this block can ever attend: its own last query position + 1
    # (≤ kv_len — later chunks of the same row carry the larger bound).
    need = q_pos0 + q_valid
    live = j * page_size < need
    if window is not None:
        # pages entirely below every query's window contribute nothing
        live &= (j + 1) * page_size - 1 > q_pos0 - window

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    @pl.when(live)
    def _block():
        q = q_ref[0].reshape(gt, q_ref.shape[-1])
        k = k_ref[0]
        v = v_ref[0]
        if quantized:
            k = (k.astype(jnp.float32) * ks_ref[0]).astype(q.dtype)
            v = (v.astype(jnp.float32) * vs_ref[0]).astype(q.dtype)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if softcap is not None:
            s = softcap * jnp.tanh(s / softcap)
        # Row r is query token t = r % block_q at absolute position
        # q_pos0 + t; rows t ≥ q_valid are packing padding.
        t = jax.lax.broadcasted_iota(jnp.int32, (gt, page_size), 0) % block_q
        k_pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (gt, page_size), 1)
        if use_alibi:
            slope = slopes_ref[0][:, 0]
            s = s + slope[:, None] * (
                k_pos - (q_pos0 + t)).astype(jnp.float32)
        mask = (t < q_valid) & (k_pos <= q_pos0 + t)
        if window is not None:
            mask &= k_pos > q_pos0 + t - window
        s = jnp.where(mask, s, _NEG_INF)
        m_prev = m_scr[:, 0]
        l_prev = l_scr[:, 0]
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        # _NEG_INF is finite: padding rows and clamped re-fetches of
        # in-band pages standing in for out-of-band ones are fully
        # masked and would otherwise get p = exp(-1e30 - -1e30) = 1.
        p = jnp.where(mask, p, 0.0)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc_scr[...] = acc_scr[...] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new[:, None], m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new[:, None], l_scr.shape)

    @pl.when(j == grid_pages - 1)
    def _finish():
        l = l_scr[:, 0]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        out = acc_scr[...] / l_safe[:, None]
        o_ref[0] = out.reshape(o_ref.shape[1:]).astype(o_ref.dtype)


def ragged_paged_attention(q, flat_k, flat_v, block_table, page_size: int,
                           descs, k_scale=None, v_scale=None,
                           interpret: bool = False, window=None,
                           alibi=None, scale=None, softcap=None):
    """Unified mixed-batch attention over a paged pool.

    q: (1, Hq, Tp, D) PACKED queries — Tp = num_descs · block_q slots in
    descriptor order, padding slots arbitrary; flat_k/flat_v: (Hkv,
    num_pages · page_size, D) head-major pools; block_table: (B,
    pages_per_seq); descs: (num_descs, 4) int32 ``(row, q_pos0, q_valid,
    kv_len)`` per packed block (row = -1 padding).  With ``k_scale``/
    ``v_scale`` (``(Hkv, rows, 1)`` fp32) the pools are int8 and pages
    dequantize in VMEM.  Output is packed exactly like ``q``; padding
    slots come back zero.  Matches the jnp oracle
    (ops/attention.py::ragged_paged_attention_reference) exactly.
    """
    _, Hq, Tp, D = q.shape
    Hkv = flat_k.shape[0]
    group = Hq // Hkv
    NB = descs.shape[0]
    if NB == 0 or Tp % NB != 0:
        raise ValueError(f"packed length {Tp} must be a positive multiple "
                         f"of the descriptor count {NB}")
    block_q = Tp // NB
    pages_per_seq = block_table.shape[1]
    grid_pages = pages_per_seq
    sm_scale = float(scale) if scale is not None else 1.0 / (D ** 0.5)
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be passed together "
                         "(int8 pools carry scales for both streams)")
    quantized = k_scale is not None

    # (1, Hq, Tp, D) → (Hkv, group, Tp, D): head order is kv-major
    # (matches _group_query_heads), so this is a pure reshape.
    q_rows = q.reshape(Hkv, group, Tp, D)
    descs_flat = jnp.asarray(descs, jnp.int32).reshape(-1)
    # Unassigned pages (-1) only back masked positions; clamp them onto
    # page 0 so the DMA index is in-pool.
    table = jnp.maximum(block_table, 0).astype(jnp.int32).reshape(-1)

    def page_lookup(d, j, desc_ref, table_ref):
        # Clamp out-of-band steps to the nearest in-band logical page:
        # same physical index ⇒ the DMA is elided, so pages past the
        # block's causal bound (and below its window band) are never
        # fetched from HBM.  Padding descriptors (row = -1) clamp to row
        # 0 — their queries are fully masked.
        row = jnp.maximum(desc_ref[d * DESC_COLS], 0)
        need = (desc_ref[d * DESC_COLS + 1]
                + desc_ref[d * DESC_COLS + 2])
        hi = jax.lax.div(need + page_size - 1, page_size)
        j_eff = jnp.minimum(j, jnp.maximum(hi - 1, 0))
        if window is not None:
            lo_pos = jnp.maximum(
                desc_ref[d * DESC_COLS + 1] - int(window) + 1, 0)
            j_eff = jnp.maximum(j_eff, jax.lax.div(lo_pos, page_size))
        return table_ref[row * pages_per_seq + j_eff]

    def page_spec(width):
        return pl.BlockSpec(
            (1, page_size, width),
            lambda d, h, j, desc_ref, table_ref:
                (h, page_lookup(d, j, desc_ref, table_ref), 0),
            memory_space=pltpu.VMEM)

    use_alibi = alibi is not None
    kernel = functools.partial(
        _ragged_kernel, page_size=page_size, grid_pages=grid_pages,
        block_q=block_q, group=group, sm_scale=sm_scale,
        quantized=quantized,
        window=int(window) if window is not None else None,
        use_alibi=use_alibi,
        softcap=float(softcap) if softcap is not None else None)

    in_specs = [
        pl.BlockSpec((1, group, block_q, D),
                     lambda d, h, j, desc_ref, table_ref: (h, 0, d, 0),
                     memory_space=pltpu.VMEM),
        page_spec(D),
        page_spec(D),
    ]
    operands = [q_rows.reshape(Hkv, group, Tp, D), flat_k, flat_v]
    if quantized:
        in_specs += [page_spec(1), page_spec(1)]
        operands += [k_scale.astype(jnp.float32),
                     v_scale.astype(jnp.float32)]
    if use_alibi:
        # (Hkv, group·block_q, 1) per-query-row slopes — row r belongs to
        # query head h·group + r // block_q
        slope_rows = np.repeat(
            np.asarray(alibi, np.float32).reshape(Hkv, group), block_q,
            axis=1)[..., None]
        in_specs += [pl.BlockSpec(
            (1, group * block_q, 1),
            lambda d, h, j, desc_ref, table_ref: (h, 0, 0),
            memory_space=pltpu.VMEM)]
        operands += [jnp.asarray(slope_rows)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(NB, Hkv, grid_pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, group, block_q, D),
            lambda d, h, j, desc_ref, table_ref: (h, 0, d, 0),
            memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((group * block_q, _LANES), jnp.float32),
            pltpu.VMEM((group * block_q, _LANES), jnp.float32),
            pltpu.VMEM((group * block_q, D), jnp.float32),
        ],
    )
    span = pages_per_seq * page_size
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((Hkv, group, Tp, D), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=int(4 * Hq * Tp * span * D),
            bytes_accessed=int(
                2 * q.size * q.dtype.itemsize
                + NB * (2 * Hkv * span * D * flat_k.dtype.itemsize
                        + (2 * Hkv * span * 4 if quantized else 0))),
            transcendentals=int(Hq * Tp * span)),
        interpret=interpret,
    )(descs_flat, table, *operands)
    return out.reshape(1, Hq, Tp, D)
