"""Pallas TPU paged decode-attention: walks the block table directly.

The jnp paged path (`ops/kv_cache.py::PagedKVState.append`) materializes a
dense (B, Hkv, S_max, D) gather of the page pool before attending — correct,
but it pays a full-cache copy per layer per step and bounds S_max by VMEM.
This kernel instead streams one *physical page* at a time: the block table
is scalar-prefetched, each grid step's BlockSpec index_map looks up the
page's physical row block in the flat pool, and online-softmax statistics
carry across pages in VMEM scratch.  Only page_size × D of K/V is resident
per step, so max context is bounded by HBM, not VMEM — the vLLM-style
paged-attention dataflow built on the MXU.

Grid: (batch, kv_head, logical_page); the page dimension is sequential
("arbitrary") so scratch accumulators persist across it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from penroz_tpu.ops.pallas.decode_attention import normalize_lengths

_NEG_INF = -1e30


def _paged_kernel(len_ref, table_ref, q_ref, *rest,
                  page_size: int, num_queries: int, grid_pages: int,
                  fetch_pages: int, sm_scale: float,
                  quantized: bool = False, window=None,
                  use_alibi: bool = False, softcap=None):
    """One grid step attends ``fetch_pages`` consecutive logical pages.

    Walking one page per step makes per-step DMA latency and scalar-core
    bookkeeping the decode bottleneck (the contiguous kernel streams
    512-row tiles; a lone 128-row page is 4× the step count for the same
    bytes).  Fetching G pages per step — each through its own
    scalar-prefetched BlockSpec, so the G DMAs overlap — restores
    contiguous-sized tiles while keeping the vLLM-style pool layout.
    """
    G = fetch_pages
    k_refs = rest[:G]
    v_refs = rest[G:2 * G]
    rest = rest[2 * G:]
    if quantized:  # int8 pools carry per-token scale pages
        ks_refs = rest[:G]
        vs_refs = rest[G:2 * G]
        rest = rest[2 * G:]
    slopes_ref = None
    if use_alibi:
        slopes_ref = rest[0]
        rest = rest[1:]
    o_ref, acc_ref, m_ref, l_ref = rest
    b = pl.program_id(0)
    j = pl.program_id(2)
    total = len_ref[b]  # ragged: each sequence has its own valid length
    offset = total - num_queries
    gt = q_ref.shape[2]
    span = G * page_size  # tokens covered by one grid step

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    live = j * span < total
    if window is not None:
        # steps entirely below every query's window contribute nothing
        live &= (j + 1) * span - 1 > offset - window

    @pl.when(live)
    def _attend_pages():
        q = q_ref[0, 0]          # (GT, D)
        ks, vs = [], []
        for g in range(G):
            k = k_refs[g][0]     # (page_size, D)
            v = v_refs[g][0]
            if quantized:
                # Dequantize the page in VMEM: int8 values × per-token
                # scales (TurboQuant layout, ops/kv_cache.py:_quantize_int8).
                k = (k.astype(jnp.float32) * ks_refs[g][0]).astype(q.dtype)
                v = (v.astype(jnp.float32) * vs_refs[g][0]).astype(q.dtype)
            ks.append(k)
            vs.append(v)
        k = ks[0] if G == 1 else jnp.concatenate(ks, axis=0)  # (span, D)
        v = vs[0] if G == 1 else jnp.concatenate(vs, axis=0)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # (GT, span)
        if softcap is not None:
            s = softcap * jnp.tanh(s / softcap)
        t = jax.lax.broadcasted_iota(jnp.int32, (gt, span), 0) \
            % num_queries
        k_pos = j * span + jax.lax.broadcasted_iota(
            jnp.int32, (gt, span), 1)
        # Positions past the sequence's occupancy — including clamped
        # re-fetches of in-band pages standing in for out-of-band ones —
        # carry logical k_pos > the causal bound, so this mask kills them.
        if use_alibi:
            # per-query-row ALiBi slope (row r ↦ query head h·group +
            # r // T): bias slope·(k − q), same as the other kernels
            slope = slopes_ref[0][:, 0]
            s = s + slope[:, None] * (
                k_pos - (offset + t)).astype(jnp.float32)
        mask = k_pos <= offset + t
        if window is not None:
            mask &= k_pos > offset + t - window
        s = jnp.where(mask, s, _NEG_INF)
        m_prev = m_ref[:, 0]
        l_prev = l_ref[:, 0]
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        # _NEG_INF is finite: fully-masked rows (early windowed pages, or
        # steps whose pages all sit past the occupancy) would otherwise
        # get p = exp(-1e30 - -1e30) = 1
        p = jnp.where(mask, p, 0.0)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc_ref[...] = acc_ref[...] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:, 0] = m_new
        l_ref[:, 0] = l_new

    @pl.when(j == grid_pages - 1)
    def _finalize():
        l = l_ref[:, 0]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[...] / l[:, None]).astype(o_ref.dtype)


def default_fetch_pages() -> int:
    """Logical pages fetched per kernel grid step
    (``PENROZ_PAGED_FETCH_PAGES``, default 4 → 512-token effective tiles
    at the default 128-token page, matching the contiguous decode
    kernel's tile size)."""
    import os
    raw = os.environ.get("PENROZ_PAGED_FETCH_PAGES", "4")
    try:
        n = int(raw)
        return n if n >= 1 else 4
    except ValueError:
        return 4


def paged_decode_attention(q, flat_k, flat_v, block_table, page_size: int,
                           offset, length, k_scale=None, v_scale=None,
                           interpret: bool = False, window=None,
                           fetch_pages: int | None = None, alibi=None,
                           scale=None, softcap=None):
    """Cached attention over a paged pool.

    q: (B, Hq, T, D) new queries; flat_k/flat_v: (Hkv, num_pages *
    page_size, D) shared head-major pools; block_table: (B, pages_per_seq)
    physical page per logical page (-1 = unassigned); ``length`` = offset +
    T valid tokens — a scalar shared by the batch, or a ``(B,)`` vector for
    RAGGED batches (each sequence attends only its own occupancy; pages
    past a shorter sequence's length are skipped per-sequence, the
    ragged-paged-attention serving layout).
    With ``k_scale``/``v_scale`` (``(Hkv, rows, 1)`` fp32 per-token scales)
    the pools are int8 and each page is dequantized in VMEM (TurboQuant +
    paged).  Matches the jnp oracle (gather + ``cached_attention``) exactly.
    """
    B, Hq, T, D = q.shape
    Hkv = flat_k.shape[0]
    group = Hq // Hkv
    pages_per_seq = block_table.shape[1]
    sm_scale = float(scale) if scale is not None else 1.0 / (D ** 0.5)
    quantized = k_scale is not None
    G = fetch_pages if fetch_pages is not None else default_fetch_pages()
    G = max(1, min(int(G), pages_per_seq))
    grid_pages = (pages_per_seq + G - 1) // G

    q_rows = q.reshape(B, Hkv, group * T, D)
    total = normalize_lengths(length, B)
    # Unassigned pages (-1) sit past the valid length; clamp them onto page
    # 0 so the DMA index is in-pool — their keys are masked by k_pos>total.
    table = jnp.maximum(block_table, 0).astype(jnp.int32).reshape(-1)

    use_alibi = alibi is not None
    kernel = functools.partial(_paged_kernel, page_size=page_size,
                               num_queries=T, grid_pages=grid_pages,
                               fetch_pages=G, sm_scale=sm_scale,
                               quantized=quantized,
                               window=int(window) if window is not None
                               else None, use_alibi=use_alibi,
                               softcap=float(softcap)
                               if softcap is not None else None)

    def page_lookup(b, logical, len_ref, table_ref):
        # Clamp out-of-band steps to the nearest in-band logical page: same
        # physical index ⇒ the DMA is elided, so pages past the sequence's
        # own occupancy (and below the window band) are never fetched.
        hi = jax.lax.div(len_ref[b] + page_size - 1, page_size)
        j_eff = jnp.minimum(logical, jnp.maximum(hi - 1, 0))
        if window is not None:
            lo_pos = jnp.maximum(len_ref[b] - T - int(window) + 1, 0)
            j_eff = jnp.maximum(j_eff, jax.lax.div(lo_pos, page_size))
        return table_ref[b * pages_per_seq + j_eff]

    def page_spec(g, width):
        # One BlockSpec per sub-page: the G DMAs of a grid step issue
        # together and overlap, while each keeps its own block-table slot.
        return pl.BlockSpec(
            (1, page_size, width),
            lambda b, h, j, len_ref, table_ref:
                (h, page_lookup(b, j * G + g, len_ref, table_ref), 0),
            memory_space=pltpu.VMEM)

    in_specs = [
        pl.BlockSpec((1, 1, group * T, D),
                     lambda b, h, j, len_ref, table_ref: (b, h, 0, 0),
                     memory_space=pltpu.VMEM),
    ]
    in_specs += [page_spec(g, D) for g in range(G)]  # k pages
    in_specs += [page_spec(g, D) for g in range(G)]  # v pages
    operands = [q_rows] + [flat_k] * G + [flat_v] * G
    if quantized:
        in_specs += [page_spec(g, 1) for g in range(G)]
        in_specs += [page_spec(g, 1) for g in range(G)]
        operands += [k_scale] * G + [v_scale] * G
    if use_alibi:
        import numpy as np
        slope_rows = np.repeat(
            np.asarray(alibi, np.float32).reshape(Hkv, group), T,
            axis=1)[..., None]
        in_specs += [pl.BlockSpec(
            (1, group * T, 1),
            lambda b, h, j, len_ref, table_ref: (h, 0, 0),
            memory_space=pltpu.VMEM)]
        operands += [jnp.asarray(slope_rows)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, Hkv, grid_pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, group * T, D),
                               lambda b, h, j, len_ref, table_ref:
                                   (b, h, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((group * T, D), jnp.float32),
            pltpu.VMEM((group * T, 1), jnp.float32),
            pltpu.VMEM((group * T, 1), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q_rows.shape, q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=int(4 * B * Hq * T * pages_per_seq * page_size * D),
            bytes_accessed=int((q.size + 2 * B * pages_per_seq * page_size
                                * Hkv * D) * q.dtype.itemsize),
            transcendentals=int(B * Hq * T * pages_per_seq * page_size)),
        interpret=interpret,
    )(total, table, *operands)
    return out.reshape(B, Hq, T, D)
