"""Pallas TPU paged decode-attention: walks the block table directly.

The jnp paged path (`ops/kv_cache.py::PagedKVState.append`) materializes a
dense (B, Hkv, S_max, D) gather of the page pool before attending — correct,
but it pays a full-cache copy per layer per step and bounds S_max by VMEM.
This kernel instead streams one *physical page* at a time: the block table
is scalar-prefetched, each grid step's BlockSpec index_map looks up the
page's physical row block in the flat pool, and online-softmax statistics
carry across pages in VMEM scratch.  Only page_size × D of K/V is resident
per step, so max context is bounded by HBM, not VMEM — the vLLM-style
paged-attention dataflow built on the MXU.

Grid: (batch, kv_head, logical_page); the page dimension is sequential
("arbitrary") so scratch accumulators persist across it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from penroz_tpu.ops.pallas.decode_attention import normalize_lengths

_NEG_INF = -1e30


def _paged_kernel(len_ref, table_ref, q_ref, k_ref, v_ref, *rest,
                  page_size: int, num_queries: int, pages_per_seq: int,
                  sm_scale: float, quantized: bool = False, window=None):
    if quantized:  # int8 pools carry per-token scale pages
        ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = rest
    else:
        o_ref, acc_ref, m_ref, l_ref = rest
    b = pl.program_id(0)
    j = pl.program_id(2)
    total = len_ref[b]  # ragged: each sequence has its own valid length
    offset = total - num_queries
    gt = q_ref.shape[2]

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    live = j * page_size < total
    if window is not None:
        # pages entirely below every query's window contribute nothing
        live &= (j + 1) * page_size - 1 > offset - window

    @pl.when(live)
    def _attend_page():
        q = q_ref[0, 0]          # (GT, D)
        k = k_ref[0]             # (page_size, D)
        v = v_ref[0]
        if quantized:
            # Dequantize the page in VMEM: int8 values × per-token scales
            # (TurboQuant layout, ops/kv_cache.py:_quantize_int8).
            k = (k.astype(jnp.float32) * ks_ref[0]).astype(q.dtype)
            v = (v.astype(jnp.float32) * vs_ref[0]).astype(q.dtype)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # (GT, P)
        t = jax.lax.broadcasted_iota(jnp.int32, (gt, page_size), 0) \
            % num_queries
        k_pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (gt, page_size), 1)
        mask = k_pos <= offset + t
        if window is not None:
            mask &= k_pos > offset + t - window
        s = jnp.where(mask, s, _NEG_INF)
        m_prev = m_ref[:, 0]
        l_prev = l_ref[:, 0]
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        if window is not None:
            # _NEG_INF is finite: fully-masked rows in early pages would
            # otherwise get p = exp(-1e30 - -1e30) = 1
            p = jnp.where(mask, p, 0.0)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc_ref[...] = acc_ref[...] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:, 0] = m_new
        l_ref[:, 0] = l_new

    @pl.when(j == pages_per_seq - 1)
    def _finalize():
        l = l_ref[:, 0]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[...] / l[:, None]).astype(o_ref.dtype)


def paged_decode_attention(q, flat_k, flat_v, block_table, page_size: int,
                           offset, length, k_scale=None, v_scale=None,
                           interpret: bool = False, window=None):
    """Cached attention over a paged pool.

    q: (B, Hq, T, D) new queries; flat_k/flat_v: (Hkv, num_pages *
    page_size, D) shared head-major pools; block_table: (B, pages_per_seq)
    physical page per logical page (-1 = unassigned); ``length`` = offset +
    T valid tokens — a scalar shared by the batch, or a ``(B,)`` vector for
    RAGGED batches (each sequence attends only its own occupancy; pages
    past a shorter sequence's length are skipped per-sequence, the
    ragged-paged-attention serving layout).
    With ``k_scale``/``v_scale`` (``(Hkv, rows, 1)`` fp32 per-token scales)
    the pools are int8 and each page is dequantized in VMEM (TurboQuant +
    paged).  Matches the jnp oracle (gather + ``cached_attention``) exactly.
    """
    B, Hq, T, D = q.shape
    Hkv = flat_k.shape[0]
    group = Hq // Hkv
    pages_per_seq = block_table.shape[1]
    sm_scale = 1.0 / (D ** 0.5)
    quantized = k_scale is not None

    q_rows = q.reshape(B, Hkv, group * T, D)
    total = normalize_lengths(length, B)
    # Unassigned pages (-1) sit past the valid length; clamp them onto page
    # 0 so the DMA index is in-pool — their keys are masked by k_pos>total.
    table = jnp.maximum(block_table, 0).astype(jnp.int32).reshape(-1)

    kernel = functools.partial(_paged_kernel, page_size=page_size,
                               num_queries=T, pages_per_seq=pages_per_seq,
                               sm_scale=sm_scale, quantized=quantized,
                               window=int(window) if window is not None
                               else None)

    def page_lookup(b, j, len_ref, table_ref):
        # Clamp out-of-band steps to the nearest in-band logical page: same
        # physical index ⇒ the DMA is elided, so pages past the sequence's
        # own occupancy (and below the window band) are never fetched.
        hi = jax.lax.div(len_ref[b] + page_size - 1, page_size)
        j_eff = jnp.minimum(j, jnp.maximum(hi - 1, 0))
        if window is not None:
            lo_pos = jnp.maximum(len_ref[b] - T - int(window) + 1, 0)
            j_eff = jnp.maximum(j_eff, jax.lax.div(lo_pos, page_size))
        return table_ref[b * pages_per_seq + j_eff]

    page_spec = pl.BlockSpec(
        (1, page_size, D),
        lambda b, h, j, len_ref, table_ref:
            (h, page_lookup(b, j, len_ref, table_ref), 0),
        memory_space=pltpu.VMEM)
    in_specs = [
        pl.BlockSpec((1, 1, group * T, D),
                     lambda b, h, j, len_ref, table_ref: (b, h, 0, 0),
                     memory_space=pltpu.VMEM),
        page_spec,
        page_spec,
    ]
    operands = [q_rows, flat_k, flat_v]
    if quantized:
        scale_spec = pl.BlockSpec(
            (1, page_size, 1),
            lambda b, h, j, len_ref, table_ref:
                (h, page_lookup(b, j, len_ref, table_ref), 0),
            memory_space=pltpu.VMEM)
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, Hkv, pages_per_seq),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, group * T, D),
                               lambda b, h, j, len_ref, table_ref:
                                   (b, h, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((group * T, D), jnp.float32),
            pltpu.VMEM((group * T, 1), jnp.float32),
            pltpu.VMEM((group * T, 1), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q_rows.shape, q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=int(4 * B * Hq * T * pages_per_seq * page_size * D),
            bytes_accessed=int((q.size + 2 * B * pages_per_seq * page_size
                                * Hkv * D) * q.dtype.itemsize),
            transcendentals=int(B * Hq * T * pages_per_seq * page_size)),
        interpret=interpret,
    )(total, table, *operands)
    return out.reshape(B, Hq, T, D)
