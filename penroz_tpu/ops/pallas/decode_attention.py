"""Pallas TPU decode-attention kernel: cached single/few-token queries.

The decode hot loop attends a handful of new query tokens (T = 1 chunked up
to ~16) against a preallocated KV cache of capacity ``S_max`` holding
``offset + T`` valid entries.  The jnp fallback (ops/attention.py:91-108)
pays compute and bandwidth proportional to ``S_max``; this kernel prefetches
the valid length as a scalar and bounds its work by it, so per-token cost
tracks the *actual* cache occupancy.  GQA is handled by folding the query
group into the row dimension — one kernel instance per (batch, kv-head)
computes all grouped query heads on the MXU at once.

K/V stream through the innermost grid dimension one ``block_k`` tile at a
time (carrying running max/sum/accumulator in VMEM scratch), so VMEM holds
a single tile regardless of ``S_max`` — context length is HBM-bounded, not
VMEM-bounded.  Grid steps past the valid length clamp their block index to
the last valid tile: Pallas elides the HBM→VMEM copy when the index is
unchanged and ``pl.when`` skips the compute, so overrun steps pay no HBM
bandwidth and no FLOPs — only per-grid-step scalar-core bookkeeping, which
grows with ``S_max / block_k``.  At realistic decode capacities (≤ 32k
tokens → ≤ 128 steps) that overhead is noise; for caches orders of
magnitude larger than their occupancy, prefer the paged cache
(``PAGED_KV_CACHE=1``), whose pool is sized by allocation, not capacity.

Replaces the decode half of the reference's
``F.scaled_dot_product_attention`` (neural_net_layers.py:92) the way the
training kernel (pallas/flash_attention.py) replaces the causal half.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from penroz_tpu.ops.pallas.flash_attention import (_LANES,
                                                   _largest_dividing_block)

DEFAULT_BLOCK_K = 256
_NEG_INF = -1e30


def normalize_lengths(length, batch: int):
    """(B,) int32 valid lengths from a scalar (broadcast) or (B,) input —
    the shared ragged-length contract of both decode kernels and the jnp
    oracles."""
    total = jnp.asarray(length, jnp.int32).reshape(-1)
    if total.shape[0] == 1 and batch > 1:
        total = jnp.broadcast_to(total, (batch,))
    if total.shape[0] != batch:
        raise ValueError(f"length must be scalar or (B,); got "
                         f"{total.shape[0]} lengths for batch {batch}")
    return total


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, *refs, block_k: int,
                   num_k: int, num_queries: int, sm_scale: float,
                   quantized: bool, window=None, use_alibi: bool = False,
                   softcap=None):
    """One (batch, kv-head, k-block) step: GT grouped query rows vs one tile.

    q_ref: (1, 1, GT, D) where GT = group * T, row r ↦ (g = r // T, t = r % T).
    k_ref/v_ref: (1, 1, block_k, D) — the j-th valid tile (clamped index map).
    With ``quantized`` two extra (1, 1, block_k, 1) refs carry the int8
    tiles' per-token scales and dequantization happens here in VMEM — the
    full-precision cache never exists in HBM.
    len_ref[b] = that sequence's offset + T valid entries ((B,) prefetch —
    ragged batches).  Scratch carries the online-softmax state across the
    sequential j dimension.
    """
    refs = list(refs)
    ks_ref = vs_ref = slopes_ref = None
    if quantized:
        ks_ref, vs_ref = refs[:2]
        refs = refs[2:]
    if use_alibi:
        slopes_ref = refs[0]
        refs = refs[1:]
    o_ref, m_scr, l_scr, acc_scr = refs
    b = pl.program_id(0)
    j = pl.program_id(2)
    gt = q_ref.shape[2]
    total = len_ref[b]  # ragged: per-sequence valid length
    offset = total - num_queries
    hi = jax.lax.div(total + block_k - 1, block_k)
    live = j < hi
    if window is not None:
        # tiles entirely below every query's window contribute nothing —
        # skipping them keeps decode compute O(window), not O(cache)
        live &= (j + 1) * block_k - 1 > offset - window

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    @pl.when(live)
    def _block():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        if quantized:
            k = (k.astype(jnp.float32) * ks_ref[0, 0]).astype(q.dtype)
            v = (v.astype(jnp.float32) * vs_ref[0, 0]).astype(q.dtype)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if softcap is not None:
            s = softcap * jnp.tanh(s / softcap)
        # Row r is query token t = r % T at absolute position offset + t; it
        # may attend keys at positions ≤ offset + t (combined causal +
        # validity mask of the jnp oracle).
        t = jax.lax.broadcasted_iota(jnp.int32, (gt, block_k), 0) % num_queries
        k_pos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (gt, block_k), 1)
        if use_alibi:
            # per-query-row ALiBi slope (precomputed outside: row r ↦
            # query head h·group + r // T): bias slope·(k − q) like the
            # flash kernels and the jnp oracle
            slope = slopes_ref[0][:, 0]
            s = s + slope[:, None] * (
                k_pos - (offset + t)).astype(jnp.float32)
        mask = k_pos <= offset + t
        if window is not None:
            mask &= k_pos > offset + t - window
        s = jnp.where(mask, s, _NEG_INF)
        m_prev = m_scr[:, 0]
        l_prev = l_scr[:, 0]
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        if window is not None:
            # _NEG_INF is finite: fully-masked rows in early tiles would
            # otherwise get p = exp(-1e30 - -1e30) = 1
            p = jnp.where(mask, p, 0.0)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc_scr[...] = acc_scr[...] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new[:, None], m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new[:, None], l_scr.shape)

    @pl.when(j == num_k - 1)
    def _finish():
        l = l_scr[:, 0]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[...] / l_safe[:, None]).astype(o_ref.dtype)


def decode_attention(q, k_full, v_full, offset, length,
                     block_k: int = DEFAULT_BLOCK_K, interpret: bool = False,
                     k_scale=None, v_scale=None, window=None, alibi=None,
                     scale=None, softcap=None):
    """Fused cached attention.  Same contract as the jnp oracle
    ``cached_attention``: q (B, Hq, T, D); k_full/v_full (B, Hkv, S_max, D);
    ``length`` = offset + T valid entries (post-append) — a shared scalar
    or a ``(B,)`` vector for RAGGED batches (each sequence attends only
    its own occupancy).  With
    ``k_scale``/``v_scale`` (B, Hkv, S_max, 1) the cache is int8 (TurboQuant)
    and tiles dequantize in VMEM."""
    B, Hq, T, D = q.shape
    Hkv, S = k_full.shape[1], k_full.shape[2]
    group = Hq // Hkv
    block_k = _largest_dividing_block(S, block_k)
    if S % block_k != 0:
        raise ValueError(f"decode_attention requires S%{block_k}==0, got {S}")
    sm_scale = float(scale) if scale is not None else 1.0 / (D ** 0.5)
    num_k = S // block_k
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be passed together "
                         "(int8 caches carry scales for both streams)")
    quantized = k_scale is not None

    # Fold the GQA group into the query-row dimension: head order is kv-major
    # (matches _group_query_heads), so this is a pure reshape.
    q_rows = q.reshape(B, Hkv, group * T, D)
    total = normalize_lengths(length, B)

    def kv_index(b, h, j, len_ref):
        # Clamp out-of-band steps to the nearest band tile: same index ⇒
        # Pallas elides the copy, so tiles past the sequence's own
        # occupancy (and, with a window, tiles below the band) are never
        # fetched from HBM.
        hi = jax.lax.div(len_ref[b] + block_k - 1, block_k)
        j_eff = jnp.minimum(j, jnp.maximum(hi - 1, 0))
        if window is not None:
            lo_pos = jnp.maximum(len_ref[b] - T - window + 1, 0)
            j_eff = jnp.maximum(j_eff, jax.lax.div(lo_pos, block_k))
        return (b, h, j_eff, 0)

    use_alibi = alibi is not None
    kernel = functools.partial(_decode_kernel, block_k=block_k, num_k=num_k,
                               num_queries=T, sm_scale=sm_scale,
                               quantized=quantized,
                               window=int(window) if window is not None
                               else None, use_alibi=use_alibi,
                               softcap=float(softcap)
                               if softcap is not None else None)
    in_specs = [
        pl.BlockSpec((1, 1, group * T, D),
                     lambda b, h, j, len_ref: (b, h, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, block_k, D), kv_index,
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, block_k, D), kv_index,
                     memory_space=pltpu.VMEM),
    ]
    operands = [total, q_rows, k_full, v_full]
    if quantized:
        scale_spec = pl.BlockSpec((1, 1, block_k, 1), kv_index,
                                  memory_space=pltpu.VMEM)
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale.astype(jnp.float32),
                     v_scale.astype(jnp.float32)]
    if use_alibi:
        # (Hkv, group·T, 1) per-query-row slopes — row r belongs to query
        # head h·group + r // T, whose slope is constant across its rows
        slope_rows = np.repeat(
            np.asarray(alibi, np.float32).reshape(Hkv, group), T,
            axis=1)[..., None]
        in_specs += [pl.BlockSpec((1, group * T, 1),
                                  lambda b, h, j, len_ref: (h, 0, 0),
                                  memory_space=pltpu.VMEM)]
        operands += [jnp.asarray(slope_rows)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, Hkv, num_k),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, group * T, D),
                               lambda b, h, j, len_ref: (b, h, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((group * T, _LANES), jnp.float32),
            pltpu.VMEM((group * T, _LANES), jnp.float32),
            pltpu.VMEM((group * T, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q_rows.shape, q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=int(4 * B * Hq * T * S * D),
            # per-operand itemsize: the int8 path reads 1-byte K/V tiles
            # plus two f32 scale streams — q-dtype accounting would
            # overstate its HBM traffic ~4x
            bytes_accessed=int(
                2 * q.size * q.dtype.itemsize
                + k_full.size * k_full.dtype.itemsize
                + v_full.size * v_full.dtype.itemsize
                + (2 * B * Hkv * S * 4 if quantized else 0)),
            transcendentals=int(B * Hq * T * S)),
        interpret=interpret,
    )(*operands)
    return out.reshape(B, Hq, T, D)
