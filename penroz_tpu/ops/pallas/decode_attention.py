"""Pallas TPU decode-attention kernel: cached single/few-token queries.

The decode hot loop attends a handful of new query tokens (T = 1 chunked up
to ~16) against a preallocated KV cache of capacity ``S_max`` holding
``offset + T`` valid entries.  The jnp fallback (ops/attention.py:91-108)
pays compute and bandwidth proportional to ``S_max``; this kernel prefetches
the valid length as a scalar and bounds its K/V loop by it, so per-token cost
tracks the *actual* cache occupancy.  GQA is handled by folding the query
group into the row dimension — one kernel instance per (batch, kv-head)
computes all grouped query heads on the MXU at once.

Replaces the decode half of the reference's
``F.scaled_dot_product_attention`` (neural_net_layers.py:92) the way the
training kernel (pallas/flash_attention.py) replaces the causal half.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from penroz_tpu.ops.pallas.flash_attention import _largest_dividing_block

DEFAULT_BLOCK_K = 256
_NEG_INF = -1e30


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, *, block_k: int,
                   num_queries: int, sm_scale: float):
    """One (batch, kv-head) instance: GT grouped query rows vs valid cache.

    q_ref: (1, 1, GT, D) where GT = group * T, row r ↦ (g = r // T, t = r % T).
    k_ref/v_ref: (1, 1, S_max, D).  len_ref[0] = offset + T (valid entries).
    """
    gt = q_ref.shape[2]
    head_dim = q_ref.shape[3]
    total = len_ref[0]
    offset = total - num_queries

    q = q_ref[0, 0]

    def body(j, carry):
        acc, m_prev, l_prev = carry
        k = k_ref[0, 0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, 0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        # Row r is query token t = r % T at absolute position offset + t; it
        # may attend keys at positions ≤ offset + t (combined causal +
        # validity mask of the jnp oracle).
        t = jax.lax.broadcasted_iota(jnp.int32, (gt, block_k), 0) % num_queries
        k_pos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (gt, block_k), 1)
        s = jnp.where(k_pos <= offset + t, s, _NEG_INF)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    acc0 = jnp.zeros((gt, head_dim), jnp.float32)
    m0 = jnp.full((gt,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((gt,), jnp.float32)

    # Only K blocks overlapping [0, total) contribute — the dynamic bound is
    # the whole point of prefetching the length.
    hi = jax.lax.div(total + block_k - 1, block_k)
    acc, m, l = jax.lax.fori_loop(0, hi, body, (acc0, m0, l0))
    l = jnp.where(l == 0.0, 1.0, l)
    o_ref[0, 0] = (acc / l[:, None]).astype(o_ref.dtype)


def decode_attention(q, k_full, v_full, offset, length,
                     block_k: int = DEFAULT_BLOCK_K, interpret: bool = False):
    """Fused cached attention.  Same contract as the jnp oracle
    ``cached_attention``: q (B, Hq, T, D); k_full/v_full (B, Hkv, S_max, D);
    ``length`` = offset + T valid entries (post-append)."""
    B, Hq, T, D = q.shape
    Hkv, S = k_full.shape[1], k_full.shape[2]
    group = Hq // Hkv
    block_k = _largest_dividing_block(S, block_k)
    if S % block_k != 0:
        raise ValueError(f"decode_attention requires S%{block_k}==0, got {S}")
    sm_scale = 1.0 / (D ** 0.5)

    # Fold the GQA group into the query-row dimension: head order is kv-major
    # (matches _group_query_heads), so this is a pure reshape.
    q_rows = q.reshape(B, Hkv, group * T, D)
    total = jnp.asarray(length, jnp.int32).reshape(1)

    kernel = functools.partial(_decode_kernel, block_k=block_k,
                               num_queries=T, sm_scale=sm_scale)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, Hkv),
        in_specs=[
            pl.BlockSpec((1, 1, group * T, D), lambda b, h, len_ref: (b, h, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, S, D), lambda b, h, len_ref: (b, h, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, S, D), lambda b, h, len_ref: (b, h, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, group * T, D),
                               lambda b, h, len_ref: (b, h, 0, 0),
                               memory_space=pltpu.VMEM),
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q_rows.shape, q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel")),
        cost_estimate=pl.CostEstimate(
            flops=int(4 * B * Hq * T * S * D),
            bytes_accessed=int((q.size + k_full.size + v_full.size + q.size)
                               * q.dtype.itemsize),
            transcendentals=int(B * Hq * T * S)),
        interpret=interpret,
    )(total, q_rows, k_full, v_full)
    return out.reshape(B, Hq, T, D)
