"""Pallas TPU fused softmax cross-entropy over large vocabularies.

The CE loss over a (N, V≈50k) logits matrix is pure HBM-bandwidth work, but
both the naive fp32 upcast and a host-level chunked scan leave 4-10× on the
table (measured: optax fp32 ≈ 14.7 ms fwd+bwd, jnp chunk-scan ≈ 29 ms at
N=8192, V=50304 on v5e — against ~2.5 GB of traffic ≈ 3 ms at bandwidth).

Two kernels, mirroring the flash-attention structure
(ops/pallas/flash_attention.py):

- forward — grid (rows, vocab-chunks), vocab innermost and ``arbitrary``:
  streams vocab chunks through VMEM carrying running (max, sumexp) statistics
  plus the label logit picked up in whichever chunk contains it; emits
  per-row ``lse`` and label logit.  The bf16 logits are read exactly once
  and no fp32 copy ever reaches HBM.
- backward — fully parallel grid: ``(softmax - onehot) · scale`` per chunk
  from the forward's saved ``lse``, written directly in the logits dtype.

The public entry is :func:`fused_cross_entropy_mean` in ops/losses.py, which
dispatches here on TPU and to the jnp chunk-scan elsewhere (the jnp path is
the correctness oracle in tests/test_losses.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_N = 256
DEFAULT_BLOCK_V = 2048
_NEG_INF = -1e30
_LANES = 128


def _col_ids(vj, block_n: int, block_v: int):
    return vj * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (block_n, block_v), 1)


def _fwd_kernel(x_ref, t_ref, lse_ref, ll_ref, m_scr, l_scr, ll_scr, *,
                block_n: int, block_v: int, num_v: int, vocab: int):
    vj = pl.program_id(1)

    @pl.when(vj == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        ll_scr[...] = jnp.zeros_like(ll_scr)

    x = x_ref[...].astype(jnp.float32)
    cols = _col_ids(vj, block_n, block_v)
    x = jnp.where(cols < vocab, x, _NEG_INF)  # tail-chunk vocab mask

    m_prev = m_scr[:, 0]
    m_new = jnp.maximum(m_prev, jnp.max(x, axis=-1))
    alpha = jnp.exp(m_prev - m_new)
    l_new = l_scr[:, 0] * alpha + jnp.sum(jnp.exp(x - m_new[:, None]), axis=-1)
    m_scr[...] = jax.lax.broadcast_in_dim(m_new, m_scr.shape, (0,))
    l_scr[...] = jax.lax.broadcast_in_dim(l_new, l_scr.shape, (0,))

    # label logit if this chunk owns it (one hit across the whole vocab loop)
    t = t_ref[:, 0]
    hit = cols == t[:, None]
    ll_scr[...] += jax.lax.broadcast_in_dim(
        jnp.sum(jnp.where(hit, x, 0.0), axis=-1), ll_scr.shape, (0,))

    @pl.when(vj == num_v - 1)
    def _finish():
        l = l_scr[:, 0]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        lse_ref[...] = (m_scr[:, 0] + jnp.log(l_safe))[:, None]
        ll_ref[...] = ll_scr[:, 0:1]


def _bwd_kernel(x_ref, t_ref, lse_ref, scale_ref, dx_ref, *, block_n: int,
                block_v: int, vocab: int):
    vj = pl.program_id(1)
    x = x_ref[...].astype(jnp.float32)
    cols = _col_ids(vj, block_n, block_v)
    t = t_ref[:, 0]
    p = jnp.exp(x - lse_ref[...])  # (block_n, block_v); lse broadcasts
    onehot = cols == t[:, None]
    valid = (t >= 0)[:, None]  # padded rows contribute zero gradient
    g = jnp.where(valid & (cols < vocab),
                  (p - onehot) * scale_ref[0], 0.0)
    dx_ref[...] = g.astype(dx_ref.dtype)


def _pad_rows(x2d, t1d, block_n: int):
    from penroz_tpu.ops.losses import pad_rows
    x2d, t1d, _ = pad_rows(x2d, t1d, block_n)
    return x2d, t1d


def ce_forward(logits2d, targets1d, block_n: int = DEFAULT_BLOCK_N,
               block_v: int = DEFAULT_BLOCK_V, interpret: bool = False):
    """Per-row (lse, label_logit), fp32, shapes (N, 1) each (padded rows
    included — callers mask on ``targets < 0``)."""
    x, t = _pad_rows(logits2d, targets1d, block_n)
    n, v = x.shape
    block_v = min(block_v, v)
    num_v = -(-v // block_v)
    grid = (n // block_n, num_v)
    kernel = functools.partial(_fwd_kernel, block_n=block_n, block_v=block_v,
                               num_v=num_v, vocab=v)
    lse, ll = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_n, block_v), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_n, _LANES), jnp.float32),
            pltpu.VMEM((block_n, _LANES), jnp.float32),
            pltpu.VMEM((block_n, _LANES), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=int(4 * n * v),
            bytes_accessed=int(x.size * x.dtype.itemsize),
            transcendentals=int(n * v)),
        interpret=interpret,
    )(x, t[:, None])
    real_n = logits2d.shape[0]
    return lse[:real_n], ll[:real_n]


def ce_backward(logits2d, targets1d, lse, scale,
                block_n: int = DEFAULT_BLOCK_N,
                block_v: int = DEFAULT_BLOCK_V, interpret: bool = False):
    """``(softmax - onehot) * scale`` in the logits dtype; (N, V)."""
    x, t = _pad_rows(logits2d, targets1d, block_n)
    n, v = x.shape
    pad = n - logits2d.shape[0]
    if pad:
        lse = jnp.pad(lse, ((0, pad), (0, 0)))
    block_v = min(block_v, v)
    num_v = -(-v // block_v)
    grid = (n // block_n, num_v)
    kernel = functools.partial(_bwd_kernel, block_n=block_n, block_v=block_v,
                               vocab=v)
    dx = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_n, block_v), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((block_n, block_v), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n, v), logits2d.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel")),
        cost_estimate=pl.CostEstimate(
            flops=int(4 * n * v),
            bytes_accessed=int(2 * x.size * x.dtype.itemsize),
            transcendentals=int(n * v)),
        interpret=interpret,
    )(x, t[:, None], lse, jnp.asarray(scale, jnp.float32).reshape((1,)))
    return dx[: logits2d.shape[0]]
