"""Functional layer modules for the JSON layer DSL.

Every module is a lightweight Python object that knows how to

- ``init(rng)``  -> flat dict of parameter arrays, and
- ``apply(x, ctx)`` -> output array,

where parameters live in a single flat ``{"layers.0.0.weight": Array}`` dict
whose key names mirror the reference implementation's torch ``state_dict``
naming (reference: neural_net_model.py:58, mappers.py:318-448).  Keeping the
flat naming makes checkpoint round-trips and HuggingFace weight mapping pure
table lookups, while the apply path stays a pure function that ``jax.jit`` can
trace once per shape.

Design notes (TPU-first):
- No module mutates state.  Batch-norm running statistics are "buffers" kept in
  a separate flat dict; updated values are written into ``ctx.buffer_updates``
  during trace and returned from the jitted caller.
- The KV cache is a pytree threaded through ``ctx.kv`` (see ops/kv_cache.py);
  attention layers never hold references to it (reference mutates modules:
  neural_net_layers.py:24-31).
- Position offsets are dynamic scalar arrays (``ctx.pos_offset``) so a single
  compiled decode step serves every generation position (reference mutates
  ``PositionEmbedding.position_offset``: neural_net_layers.py:98-118).
"""

from __future__ import annotations

import functools
import logging
import math
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from penroz_tpu.ops import attention as attn_ops


class Ctx:
    """Per-call context threaded through module application.

    Holds the parameter/buffer dicts plus dynamic state (PRNG key, KV cache,
    position offset).  Constructed fresh inside each jitted function, so its
    attributes may freely hold traced arrays.
    """

    def __init__(self, params, buffers=None, *, training=False, rng=None,
                 kv=None, pos_offset=None, compute_dtype=None, sp_mesh=None,
                 platform=None, sp_mode="ring", sp_manual_axis=None,
                 ep_mesh=None, lora=None, lora_idx=None, ragged_descs=None,
                 ragged_rows=None):
        self.params = params
        self.buffers = buffers or {}
        self.training = training
        self.rng = rng
        self.kv = kv  # ops.kv_cache.KVState or None
        self.pos_offset = pos_offset  # scalar int32 array or None
        self.compute_dtype = compute_dtype
        self.sp_mesh = sp_mesh  # Mesh with a >1 'sequence' axis → SP attn
        self.sp_mode = sp_mode  # 'ring' (ppermute) | 'alltoall' (Ulysses)
        # Set when the caller is ALREADY inside a manual region binding the
        # sequence axis (GPipe schedule with seq manual): attention calls
        # the Ulysses body directly instead of wrapping its own shard_map.
        self.sp_manual_axis = sp_manual_axis
        # Mesh with a >1 'expert' axis → MoE capacity dispatch routes
        # tokens via lax.all_to_all over it instead of the dense-combine
        # psum (set only on the non-pipelined path: nesting an
        # expert-manual shard_map inside the GPipe schedule's manual
        # region is rejected by the Shardy partitioner — "manual axes
        # must come before free axes" on propagated dim shardings — so
        # MoE under pipe keeps the dense-combine inside each stage).
        self.ep_mesh = ep_mesh
        self.platform = platform  # execution platform hint for kernel gates
        # Mixed-adapter LoRA (models/lora.py): ``lora`` maps a Linear's
        # prefix to stacked low-rank factors {a: (L, r, in), b: (L, out, r),
        # scale: (L,)} and ``lora_idx`` (B,) selects each batch row's slot
        # (the last, all-zero slot is the base-model row).  Single-adapter
        # application instead BINDS ``<prefix>.lora_A/B/scale`` keys into
        # ``params`` — Linear.apply picks either up.
        self.lora = lora
        self.lora_idx = lora_idx
        # Ragged unified dispatch (paged caches only): ``ragged_descs`` is
        # the (NB, 4) packed-batch descriptor array (ops/kv_cache.py::
        # build_descriptors) and ``ragged_rows`` the per-packed-token pool
        # scatter rows (PagedKVState.packed_rows — computed once, shared by
        # every layer's append).  When set, attention appends/attends
        # through the packed path and ``pos_offset`` holds the (1, Tp)
        # per-token absolute positions.
        self.ragged_descs = ragged_descs
        self.ragged_rows = ragged_rows
        self.buffer_updates = {}
        self.aux_losses = []  # auxiliary training losses (e.g. MoE balance)
        self._rng_counter = 0

    def next_rng(self):
        if self.rng is None:
            raise ValueError("PRNG key required (dropout in training mode)")
        self._rng_counter += 1
        return jax.random.fold_in(self.rng, self._rng_counter)

    def offset(self):
        """Current sequence position offset (0 when no cache attached)."""
        if self.pos_offset is not None:
            return self.pos_offset
        if self.kv is not None:
            return self.kv.length
        return jnp.zeros((), jnp.int32)


class Module:
    """Base class for DSL layer modules."""

    prefix: str = ""

    def bind(self, prefix: str):
        """Assign the flat-dict key prefix for this module's parameters."""
        self.prefix = prefix
        for name, child in self.children():
            child.bind(f"{prefix}.{name}" if prefix else name)
        return self

    def children(self) -> Sequence[tuple[str, "Module"]]:
        return ()

    def walk(self):
        """Yield self and all descendant modules depth-first."""
        yield self
        for _, child in self.children():
            yield from child.walk()

    def key(self, name: str) -> str:
        return f"{self.prefix}.{name}" if self.prefix else name

    # -- parameters ---------------------------------------------------------
    def init(self, rng) -> dict[str, jax.Array]:
        """Default torch-equivalent initialization of own (non-child) params."""
        return {}

    def init_buffers(self) -> dict[str, jax.Array]:
        return {}

    def param_shapes(self) -> dict[str, tuple]:
        """Shapes of own (non-child) trainable parameters."""
        return {}

    # -- application --------------------------------------------------------
    def apply(self, x, ctx: Ctx):
        raise NotImplementedError

    def _p(self, ctx: Ctx, name: str):
        p = ctx.params[self.key(name)]
        if ctx.compute_dtype is not None and jnp.issubdtype(p.dtype, jnp.floating):
            p = p.astype(ctx.compute_dtype)
        return p


def _uniform(rng, shape, bound, dtype=jnp.float32):
    return jax.random.uniform(rng, shape, dtype, minval=-bound, maxval=bound)


# ---------------------------------------------------------------------------
# Leaf layers
# ---------------------------------------------------------------------------

_GATHER_BWD_CHUNK = 1024


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _gather_rows(table, ids, num_rows: int, dtype_name: str):
    return jnp.take(table, ids, axis=0)


def _gather_rows_fwd(table, ids, num_rows: int, dtype_name: str):
    return jnp.take(table, ids, axis=0), ids


def _gather_rows_bwd(num_rows: int, dtype_name: str, ids, g):
    """one-hotᵀ @ g instead of scatter-add: XLA TPU lowers row-scatter with
    thousands of update rows to a serialized loop, while the matmul rides
    the MXU (the dense AdamW update over the full table dominates the
    optimizer step anyway, so a dense gradient costs nothing extra there).
    The contraction streams id-chunks through a scan so the transient
    one-hot operand stays at (num_rows, chunk) — ~100 MB for a GPT-2 vocab —
    instead of a full (num_rows, B·T) buffer in HBM."""
    flat_ids = ids.reshape(-1)
    d = g.shape[-1]
    gf = g.reshape(-1, d)
    chunk = min(_GATHER_BWD_CHUNK, flat_ids.shape[0])
    pad = -flat_ids.shape[0] % chunk
    if pad:
        # -1 ids produce an all-zero one-hot column → no grad contribution.
        flat_ids = jnp.pad(flat_ids, (0, pad), constant_values=-1)
        gf = jnp.pad(gf, ((0, pad), (0, 0)))
    idc = flat_ids.reshape(-1, chunk)
    gc = gf.reshape(-1, chunk, d)

    def step(acc, ch):
        cid, cg = ch
        onehot = jax.nn.one_hot(cid, num_rows, dtype=cg.dtype, axis=0)
        # fp32 MXU accumulation — a bf16 product would round each chunk's
        # per-id gradient sum to 8 mantissa bits before the fp32 carry add.
        return acc + jnp.matmul(onehot, cg,
                                preferred_element_type=jnp.float32), None

    acc0 = jnp.zeros((num_rows, d), jnp.float32)
    dw, _ = jax.lax.scan(step, acc0, (idc, gc))
    return (dw.astype(jnp.dtype(dtype_name)),
            np.zeros(ids.shape, dtype=jax.dtypes.float0))


_gather_rows.defvjp(_gather_rows_fwd, _gather_rows_bwd)


class Embedding(Module):
    def __init__(self, num_embeddings: int, embedding_dim: int):
        self.num_embeddings = int(num_embeddings)
        self.embedding_dim = int(embedding_dim)

    def param_shapes(self):
        return {"weight": (self.num_embeddings, self.embedding_dim)}

    def init(self, rng):
        w = jax.random.normal(rng, (self.num_embeddings, self.embedding_dim), jnp.float32)
        return {self.key("weight"): w}

    def apply(self, x, ctx):
        w = self._p(ctx, "weight")
        if attn_ops._tpu_platform(w, ctx.platform):
            # TPU: matmul-based backward (see _gather_rows_bwd).
            return _gather_rows(w, x, self.num_embeddings, w.dtype.name)
        return jnp.take(w, x, axis=0)  # CPU scatter-add VJP is fine


class ScaledEmbedding(Embedding):
    """Embedding whose output is scaled by a constant (Gemma sqrt(d) scale)."""

    def __init__(self, num_embeddings: int, embedding_dim: int, scale: float = 1.0):
        super().__init__(num_embeddings, embedding_dim)
        self.scale = float(scale)

    def apply(self, x, ctx):
        out = super().apply(x, ctx)
        return out * jnp.asarray(self.scale, out.dtype)


class PositionEmbedding(Embedding):
    """Learned position embedding indexed from the dynamic context offset.

    The reference mutates a ``position_offset`` attribute during cached decode
    (neural_net_layers.py:98-118); here the offset is a traced scalar from the
    Ctx so one compiled program covers all positions.
    """

    def apply(self, x, ctx):
        num_positions = x.shape[-1]
        # Per-index clamping (jnp.take) — a dynamic slice would shift the
        # whole window on overflow, corrupting still-valid positions.  The
        # scatter in this VJP touches at most num_positions contiguous rows,
        # which XLA handles fine.  A (B,) offset (ragged batches) yields
        # per-sequence position rows (B, T) → (B, T, d).
        offset = jnp.asarray(ctx.offset())
        steps = jnp.arange(num_positions, dtype=jnp.int32)
        if offset.ndim == 2:
            # (B, T) explicit per-token absolute positions (ragged packed
            # batches) — already fully resolved, nothing to add.
            positions = offset
        elif offset.ndim >= 1:
            positions = offset[:, None] + steps
        else:
            positions = offset + steps
        return jnp.take(self._p(ctx, "weight"), positions, axis=0)


class Linear(Module):
    """Dense layer storing weight as (out, in) for state-dict parity."""

    def __init__(self, in_features: int, out_features: int, bias: bool = True):
        self.in_features = int(in_features)
        self.out_features = int(out_features)
        self.use_bias = bool(bias)

    def param_shapes(self):
        shapes = {"weight": (self.out_features, self.in_features)}
        if self.use_bias:
            shapes["bias"] = (self.out_features,)
        return shapes

    def init(self, rng):
        kw, kb = jax.random.split(rng)
        bound = 1.0 / math.sqrt(self.in_features)
        params = {self.key("weight"): _uniform(kw, (self.out_features, self.in_features), bound)}
        if self.use_bias:
            params[self.key("bias")] = _uniform(kb, (self.out_features,), bound)
        return params

    def apply(self, x, ctx):
        w = self._p(ctx, "weight")
        out = jnp.matmul(x, w.T)
        if self.use_bias:
            out = out + self._p(ctx, "bias")
        return self._maybe_lora(out, x, ctx)

    def _maybe_lora(self, out, x, ctx):
        """Low-rank adapter delta ``out += scale · (x Aᵀ) Bᵀ`` when adapter
        factors are bound for this projection (models/lora.py).

        Two bindings: flat ``<prefix>.lora_A/B/scale`` keys inside
        ``ctx.params`` apply ONE adapter to the whole batch (training, the
        legacy generate paths); ``ctx.lora[prefix]`` holds per-slot stacked
        factors and ``ctx.lora_idx`` routes each batch row to its slot —
        the BGMV-style gathered einsum that lets rows with different
        adapters (or none: the trailing all-zero slot) share one forward.
        """
        a = ctx.params.get(self.key("lora_A"))
        if a is not None:
            b = ctx.params[self.key("lora_B")]
            s = ctx.params[self.key("lora_scale")]
            t = jnp.matmul(x, a.astype(x.dtype).T)
            return out + jnp.matmul(t, b.astype(x.dtype).T) \
                * s.astype(out.dtype)
        ent = ctx.lora.get(self.prefix) if ctx.lora else None
        if ent is None:
            return out
        idx = ctx.lora_idx
        if idx is not None and jnp.ndim(idx) == 2:
            # (B, T) PER-TOKEN slots — the ragged packed batch, where
            # adjacent tokens belong to different rows with different
            # adapters.  Gathered factors grow a token axis; otherwise
            # identical to the per-row einsum below.
            asel = jnp.take(ent["a"], idx, axis=0).astype(x.dtype)
            bsel = jnp.take(ent["b"], idx, axis=0).astype(x.dtype)
            ssel = jnp.take(ent["scale"], idx, axis=0).astype(out.dtype)
            t = jnp.einsum("btd,btrd->btr", x, asel)
            return out + jnp.einsum("btr,btor->bto", t, bsel) \
                * ssel[:, :, None]
        asel = jnp.take(ent["a"], idx, axis=0).astype(x.dtype)  # (B, r, in)
        bsel = jnp.take(ent["b"], idx, axis=0).astype(x.dtype)  # (B, out, r)
        ssel = jnp.take(ent["scale"], idx, axis=0).astype(out.dtype)  # (B,)
        if x.ndim == 2:  # (B, d) stacks (MLP-style models)
            t = jnp.einsum("bd,brd->br", x, asel)
            return out + jnp.einsum("br,bor->bo", t, bsel) * ssel[:, None]
        t = jnp.einsum("btd,brd->btr", x, asel)
        return out + jnp.einsum("btr,bor->bto", t, bsel) \
            * ssel[:, None, None]


class Flatten(Module):
    def __init__(self, start_dim: int = 1, end_dim: int = -1):
        self.start_dim = start_dim
        self.end_dim = end_dim

    def apply(self, x, ctx):
        start = self.start_dim if self.start_dim >= 0 else x.ndim + self.start_dim
        end = self.end_dim if self.end_dim >= 0 else x.ndim + self.end_dim
        shape = x.shape[:start] + (-1,) + x.shape[end + 1:]
        return jnp.reshape(x, shape)


class BatchNorm1d(Module):
    def __init__(self, num_features: int, eps: float = 1e-5, momentum: float = 0.1):
        self.num_features = int(num_features)
        self.eps = float(eps)
        self.momentum = float(momentum)

    def param_shapes(self):
        return {"weight": (self.num_features,), "bias": (self.num_features,)}

    def init(self, rng):
        return {self.key("weight"): jnp.ones((self.num_features,), jnp.float32),
                self.key("bias"): jnp.zeros((self.num_features,), jnp.float32)}

    def init_buffers(self):
        return {self.key("running_mean"): jnp.zeros((self.num_features,), jnp.float32),
                self.key("running_var"): jnp.ones((self.num_features,), jnp.float32),
                self.key("num_batches_tracked"): jnp.zeros((), jnp.int64
                                                           if jax.config.jax_enable_x64 else jnp.int32)}

    def apply(self, x, ctx):
        w, b = self._p(ctx, "weight"), self._p(ctx, "bias")
        reduce_axes = tuple(i for i in range(x.ndim) if i != 1) if x.ndim > 2 else (0,)
        if ctx.training:
            mean = jnp.mean(x, axis=reduce_axes)
            var = jnp.var(x, axis=reduce_axes)
            n = x.size // x.shape[1]
            unbiased = var * (n / max(n - 1, 1))
            rm = ctx.buffers[self.key("running_mean")]
            rv = ctx.buffers[self.key("running_var")]
            nb = ctx.buffers[self.key("num_batches_tracked")]
            m = self.momentum
            ctx.buffer_updates[self.key("running_mean")] = (1 - m) * rm + m * mean
            ctx.buffer_updates[self.key("running_var")] = (1 - m) * rv + m * unbiased
            ctx.buffer_updates[self.key("num_batches_tracked")] = nb + 1
        else:
            mean = ctx.buffers[self.key("running_mean")]
            var = ctx.buffers[self.key("running_var")]
        shape = (1, -1) + (1,) * (x.ndim - 2)
        mean, var = mean.reshape(shape), var.reshape(shape)
        inv = jax.lax.rsqrt(var + self.eps)
        return (x - mean) * inv * w.reshape(shape) + b.reshape(shape)


class LayerNorm(Module):
    def __init__(self, normalized_shape, eps: float = 1e-5, bias: bool = True,
                 elementwise_affine: bool = True):
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self.normalized_shape = tuple(int(d) for d in normalized_shape)
        self.eps = float(eps)
        # Non-parametric mode (OLMo v1): normalize only, no learned scale
        # or shift (torch LayerNorm(elementwise_affine=False)).
        self.affine = bool(elementwise_affine)
        self.use_bias = bool(bias) and self.affine

    def param_shapes(self):
        if not self.affine:
            return {}
        shapes = {"weight": self.normalized_shape}
        if self.use_bias:
            shapes["bias"] = self.normalized_shape
        return shapes

    def init(self, rng):
        if not self.affine:
            return {}
        params = {self.key("weight"): jnp.ones(self.normalized_shape, jnp.float32)}
        if self.use_bias:
            params[self.key("bias")] = jnp.zeros(self.normalized_shape, jnp.float32)
        return params

    def apply(self, x, ctx):
        # fp32-internal normalization like torch F.layer_norm (and HF's
        # OlmoLayerNorm, which upcasts explicitly): bf16 mean/var over the
        # large pre-norm activations would drift imported-model numerics.
        axes = tuple(range(x.ndim - len(self.normalized_shape), x.ndim))
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=axes, keepdims=True)
        var = jnp.var(xf, axis=axes, keepdims=True)
        out = ((xf - mean) * jax.lax.rsqrt(var + self.eps)).astype(x.dtype)
        if self.affine:
            out = out * self._p(ctx, "weight")
        if self.use_bias:
            out = out + self._p(ctx, "bias")
        return out


class Softcap(Module):
    """Gemma-2 logit soft-capping: ``cap · tanh(x / cap)`` (HF applies it
    to the lm-head output via ``final_logit_softcapping``)."""

    def __init__(self, cap: float):
        if float(cap) <= 0.0:
            raise ValueError(f"softcap must be > 0, got {cap}")
        self.cap = float(cap)

    def apply(self, x, ctx):
        return (self.cap * jnp.tanh(x.astype(jnp.float32) / self.cap)
                ).astype(x.dtype)


class Clamp(Module):
    """Elementwise value clipping (OLMo v1 ``clip_qkv``: the fused QKV
    projection output is clamped to ±clip before attention)."""

    def __init__(self, min: Optional[float] = None,
                 max: Optional[float] = None):
        if min is None and max is None:
            raise ValueError("clamp needs at least one of min/max")
        self.min = float(min) if min is not None else None
        self.max = float(max) if max is not None else None

    def apply(self, x, ctx):
        return jnp.clip(x, self.min, self.max)


class RMSNorm(Module):
    """RMS normalization computed internally in float32 (reference:
    neural_net_layers.py:144-155)."""

    def __init__(self, normalized_shape: int, eps: float = 1e-6):
        self.normalized_shape = int(normalized_shape)
        self.eps = float(eps)

    def param_shapes(self):
        return {"weight": (self.normalized_shape,)}

    def init(self, rng):
        return {self.key("weight"): jnp.ones((self.normalized_shape,), jnp.float32)}

    def apply(self, x, ctx):
        dtype = x.dtype
        xf = x.astype(jnp.float32)
        norm = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + self.eps)
        return (xf * norm).astype(dtype) * self._p(ctx, "weight")


class ReLU(Module):
    def apply(self, x, ctx):
        return jax.nn.relu(x)


class GELU(Module):
    def __init__(self, approximate: str = "none"):
        self.approximate = approximate

    def apply(self, x, ctx):
        return jax.nn.gelu(x, approximate=(self.approximate == "tanh"))


class SiLU(Module):
    def apply(self, x, ctx):
        return jax.nn.silu(x)


class Sigmoid(Module):
    def apply(self, x, ctx):
        return jax.nn.sigmoid(x)


class Tanh(Module):
    def apply(self, x, ctx):
        return jnp.tanh(x)


class Softmax(Module):
    def __init__(self, dim: Optional[int] = None):
        self.dim = dim

    def apply(self, x, ctx):
        return jax.nn.softmax(x, axis=self.dim if self.dim is not None else -1)


class SoftmaxOnLast(Softmax):
    """Softmax over the vocabulary of only the final sequence position."""

    def apply(self, x, ctx):
        return jax.nn.softmax(x[:, -1, :], axis=self.dim if self.dim is not None else -1)


class Dropout(Module):
    def __init__(self, p: float = 0.5):
        self.p = float(p)

    def apply(self, x, ctx):
        if not ctx.training or self.p == 0.0:
            return x
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(ctx.next_rng(), keep, x.shape)
        return jnp.where(mask, x / keep, jnp.zeros((), x.dtype))


# ---------------------------------------------------------------------------
# Containers
# ---------------------------------------------------------------------------

class Sequential(Module):
    def __init__(self, *layers: Module):
        self.layers = list(layers)

    def children(self):
        return [(str(i), l) for i, l in enumerate(self.layers)]

    def apply(self, x, ctx):
        for layer in self.layers:
            x = layer.apply(x, ctx)
        return x


class Summation(Sequential):
    """Sum of each child applied to the same input (token+position embed)."""

    def apply(self, x, ctx):
        out = self.layers[0].apply(x, ctx)
        for layer in self.layers[1:]:
            out = out + layer.apply(x, ctx)
        return out


class ResidualConnection(Sequential):
    """x = x + child(x), applied for each child in order."""

    def apply(self, x, ctx):
        for layer in self.layers:
            x = x + layer.apply(x, ctx)
        return x


class ParallelResidual(Sequential):
    """x = x + Σ child(x): every child reads the SAME input.

    The GPT-NeoX/Pythia ``use_parallel_residual`` block — attention and MLP
    branches run on the same pre-block activations and their outputs are
    summed onto the residual stream (HF ``modeling_gpt_neox`` forward),
    unlike :class:`ResidualConnection` where each child sees the previous
    child's residual sum.

    Composable as ``residual([summation([...branches])])``, but the
    dedicated container keeps branch params one level flatter
    (``layers.i.{branch}.*``), which the NeoX HF key remap relies on.
    """

    def apply(self, x, ctx):
        out = x
        for layer in self.layers:
            out = out + layer.apply(x, ctx)
        return out


class TransformerBlock(Module):
    """Pre-norm decoder block with optional Gemma-style post-norms.

    ``post_norm_on_residual=True`` (Gemma 3+): ``h = post_norm(x + branch(x))``;
    ``False`` (Gemma 2): ``h = x + post_norm(branch(x))``.
    (reference: neural_net_layers.py:188-225)
    """

    def __init__(self, attn_block: Module, mlp_block: Module,
                 post_attn_norm: Module = None, post_mlp_norm: Module = None,
                 post_norm_on_residual: bool = True):
        self.attn_block = attn_block
        self.mlp_block = mlp_block
        self.post_attn_norm = post_attn_norm
        self.post_mlp_norm = post_mlp_norm
        self.post_norm_on_residual = bool(post_norm_on_residual)

    def children(self):
        out = [("attn_block", self.attn_block), ("mlp_block", self.mlp_block)]
        if self.post_attn_norm is not None:
            out.append(("post_attn_norm", self.post_attn_norm))
        if self.post_mlp_norm is not None:
            out.append(("post_mlp_norm", self.post_mlp_norm))
        return out

    def apply(self, x, ctx):
        attn_out = self.attn_block.apply(x, ctx)
        if self.post_attn_norm is not None and not self.post_norm_on_residual:
            attn_out = self.post_attn_norm.apply(attn_out, ctx)
        h = x + attn_out
        if self.post_attn_norm is not None and self.post_norm_on_residual:
            h = self.post_attn_norm.apply(h, ctx)

        mlp_out = self.mlp_block.apply(h, ctx)
        if self.post_mlp_norm is not None and not self.post_norm_on_residual:
            mlp_out = self.post_mlp_norm.apply(mlp_out, ctx)
        out = h + mlp_out
        if self.post_mlp_norm is not None and self.post_norm_on_residual:
            out = self.post_mlp_norm.apply(out, ctx)
        return out


class GatedMLP(Module):
    """SwiGLU/GeGLU gated MLP (Gemma/LLaMA style)."""

    def __init__(self, in_features: int, intermediate_size: int,
                 bias: bool = False, activation: str = "gelu_pytorch_tanh"):
        self.gate_proj = Linear(in_features, intermediate_size, bias=bias)
        self.up_proj = Linear(in_features, intermediate_size, bias=bias)
        self.down_proj = Linear(intermediate_size, in_features, bias=bias)
        self.activation = activation

    def children(self):
        return [("gate_proj", self.gate_proj), ("up_proj", self.up_proj),
                ("down_proj", self.down_proj)]

    def _act(self, x):
        return _gated_activation(self.activation, x)

    def apply(self, x, ctx):
        gated = self._act(self.gate_proj.apply(x, ctx)) * self.up_proj.apply(x, ctx)
        return self.down_proj.apply(gated, ctx)


def _gated_activation(name: str, x):
    """silu / gelu / gelu_pytorch_tanh dispatch shared by the gated MLPs."""
    if name in ("silu", "swish"):
        return jax.nn.silu(x)
    return jax.nn.gelu(x, approximate=(name == "gelu_pytorch_tanh"))


class MixtureOfExperts(Module):
    """Top-k routed mixture of gated-MLP experts (Mixtral/Switch style).

    TPU-first layout: expert weights are *stacked* on a leading expert
    dimension — ``experts.gate_proj.weight`` (E, H, D) etc. — so a single
    einsum drives the MXU for every expert at once, and the expert dimension
    shards over the mesh ``expert`` axis (expert parallelism: each device
    computes its expert shard for all tokens; the top-k-weighted combine is
    a contraction over E, which XLA turns into a psum over the axis).

    Two dispatch modes (``dispatch`` DSL arg):

    - ``"dense"`` (default): every expert processes every token and
      non-selected contributions are zeroed by the router weights.  No
      token dropping, exact top-k math, at the cost of E/top_k× extra MLP
      FLOPs — the right trade below ~16 experts.
    - ``"capacity"`` (Switch/Mesh-TF style): the flattened batch splits
      into fixed-size groups of ``DISPATCH_GROUP`` tokens (padded up with
      masked rows when not divisible) and each group packs its tokens
      into per-expert buffers of static capacity
      ``C = ceil(top_k · DISPATCH_GROUP / E · capacity_factor)`` via
      one-hot dispatch einsums; each expert computes only its (C, d)
      buffer per group and a combine einsum scatters results back.  MLP
      FLOPs drop by ~E/top_k× (the point of sparse MoE); tokens routed
      past their group's per-expert capacity lose that expert's
      contribution (Switch token dropping, applied per group — uneven
      routing across groups can drop tokens a single global buffer would
      have served).  All shapes stay static for XLA, and the buffers
      shard on the mesh ``expert`` axis like the stacked weights.

    No reference equivalent (the reference has no MoE; nearest is GatedMLP,
    neural_net_layers.py:158-174) — this is a capability extension wired
    into the same DSL registry.
    """

    def __init__(self, in_features: int, intermediate_size: int,
                 num_experts: int, top_k: int = 2, bias: bool = False,
                 activation: str = "silu", aux_loss_coef: float = 0.0,
                 dispatch: str = "dense", capacity_factor: float = 1.25,
                 norm_topk: bool = True, shared_expert_size: int = 0):
        if top_k < 1 or top_k > num_experts:
            raise ValueError(f"top_k={top_k} outside [1, {num_experts}]")
        if bias:
            raise ValueError("MixtureOfExperts does not support bias yet")
        if dispatch not in ("dense", "capacity"):
            raise ValueError(f"dispatch must be 'dense' or 'capacity', "
                             f"got {dispatch!r}")
        if float(capacity_factor) <= 0.0:
            raise ValueError(f"capacity_factor must be > 0, "
                             f"got {capacity_factor}")
        self.dispatch = dispatch
        self.capacity_factor = float(capacity_factor)
        # Qwen2-MoE options: ``norm_topk=False`` keeps the raw softmax
        # mass on the selected experts (HF ``norm_topk_prob`` default);
        # ``shared_expert_size`` adds an always-on gated-MLP expert whose
        # contribution is scaled by a sigmoid token gate and SUMMED with
        # the routed output (Qwen2MoeSparseMoeBlock.shared_expert).
        self.norm_topk = bool(norm_topk)
        self.shared_expert_size = int(shared_expert_size)
        self.in_features = int(in_features)
        self.intermediate_size = int(intermediate_size)
        self.num_experts = int(num_experts)
        self.top_k = int(top_k)
        self.activation = activation
        # Switch/Mixtral-style load-balance loss weight; 0 disables.  A
        # top-k router trained purely on task loss commonly collapses onto
        # few experts, and dense dispatch makes the collapse invisible (no
        # capacity-overflow signal) — the aux term and the router_fraction
        # buffer below are the countermeasure + the observability.
        self.aux_loss_coef = float(aux_loss_coef)

    def param_shapes(self):
        d, h, e = self.in_features, self.intermediate_size, self.num_experts
        shapes = {
            "router.weight": (e, d),
            "experts.gate_proj.weight": (e, h, d),
            "experts.up_proj.weight": (e, h, d),
            "experts.down_proj.weight": (e, d, h),
        }
        if self.shared_expert_size:
            hs = self.shared_expert_size
            shapes.update({
                "shared_expert.gate_proj.weight": (hs, d),
                "shared_expert.up_proj.weight": (hs, d),
                "shared_expert.down_proj.weight": (d, hs),
                "shared_expert_gate.weight": (1, d),
            })
        return shapes

    def init(self, rng):
        # torch-Linear-style U(-1/sqrt(fan_in), ·) per leaf; fan_in is the
        # trailing (contraction) dim for every weight in this module.
        shapes = self.param_shapes()
        keys = jax.random.split(rng, len(shapes))
        return {self.key(name): _uniform(k, shape,
                                         1.0 / math.sqrt(shape[-1]))
                for k, (name, shape) in zip(keys, shapes.items())}

    def _act(self, x):
        return _gated_activation(self.activation, x)

    def init_buffers(self):
        # Latest per-expert routing fraction (observability; updated each
        # training step like BatchNorm running stats).
        return {self.key("router_fraction"):
                jnp.zeros((self.num_experts,), jnp.float32)}

    def router_weights(self, x, ctx):
        """(B, T, E) combine weights: softmax → top-k → renormalize.

        Routing runs entirely in fp32 — logits einsum included: bf16
        rounding before the (monotonic) softmax still flips expert choices
        on near-tie tokens."""
        router = ctx.params[self.key("router.weight")]
        logits = jnp.einsum("btd,ed->bte", x.astype(jnp.float32),
                            router.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)
        top_vals, top_idx = jax.lax.top_k(probs, self.top_k)
        if self.norm_topk:
            top_vals = top_vals / jnp.sum(top_vals, axis=-1, keepdims=True)
        one_hot = jax.nn.one_hot(top_idx, self.num_experts,
                                 dtype=jnp.float32)  # (B, T, k, E)
        if ctx.training:
            # f_e: fraction of routing slots assigned to expert e;
            # P_e: mean router probability.  Switch aux = E · Σ f_e P_e is
            # minimized (=1) by uniform routing.
            fractions = jnp.mean(jnp.sum(one_hot, axis=2), axis=(0, 1))
            mean_probs = jnp.mean(probs, axis=(0, 1))
            ctx.buffer_updates[self.key("router_fraction")] = \
                fractions / self.top_k
            if self.aux_loss_coef > 0.0:
                aux = self.num_experts * jnp.sum(
                    (fractions / self.top_k) * mean_probs)
                ctx.aux_losses.append(self.aux_loss_coef * aux)
        return jnp.einsum("btk,btke->bte", top_vals, one_hot)

    def apply(self, x, ctx):
        w_gate = self._p(ctx, "experts.gate_proj.weight")
        w_up = self._p(ctx, "experts.up_proj.weight")
        w_down = self._p(ctx, "experts.down_proj.weight")
        weights = self.router_weights(x, ctx).astype(x.dtype)
        if self.dispatch == "capacity":
            from penroz_tpu.parallel.mesh import EXPERT_AXIS
            ep_mesh = getattr(ctx, "ep_mesh", None)
            routed = None
            if ep_mesh is not None:
                ep = ep_mesh.shape.get(EXPERT_AXIS, 1)
                if ep > 1 and self.num_experts % ep == 0:
                    routed = self._apply_capacity_ep(
                        x, weights, w_gate, w_up, w_down, ep_mesh)
            if routed is None:
                routed = self._apply_capacity(x, weights, w_gate, w_up,
                                              w_down)
        else:
            g = jnp.einsum("btd,ehd->bteh", x, w_gate)
            u = jnp.einsum("btd,ehd->bteh", x, w_up)
            hidden = self._act(g) * u
            y = jnp.einsum("bteh,edh->bted", hidden, w_down)
            routed = jnp.einsum("bted,bte->btd", y, weights)
        if self.shared_expert_size:
            # Always-on shared expert (Qwen2-MoE): ordinary gated MLP
            # scaled by a per-token sigmoid gate, summed with the routed
            # output.
            sg = jnp.einsum("btd,hd->bth", x,
                            self._p(ctx, "shared_expert.gate_proj.weight"))
            su = jnp.einsum("btd,hd->bth", x,
                            self._p(ctx, "shared_expert.up_proj.weight"))
            shared = jnp.einsum(
                "bth,dh->btd", self._act(sg) * su,
                self._p(ctx, "shared_expert.down_proj.weight"))
            gate = jax.nn.sigmoid(jnp.einsum(
                "btd,od->bto", x,
                self._p(ctx, "shared_expert_gate.weight")))
            routed = routed + gate * shared
        return routed

    # Tokens per dispatch group.  One-hot dispatch costs
    # O(group_size · E · C) with C ∝ group_size/E, i.e. quadratic in the
    # group size — fixed-size groups (Mesh-TF/Switch "G groups of S
    # tokens") keep dispatch linear in total tokens and a small fraction
    # of the expert-MLP FLOPs (ratio ≈ group/(3·intermediate)).
    DISPATCH_GROUP = 512

    def _apply_capacity(self, x, weights, w_gate, w_up, w_down):
        """Capacity-packed dispatch: one-hot buffer einsums, static shapes.

        ``weights``: (B, T, E) dense combine weights (zeros off the top-k).
        The flattened batch splits into fixed-size groups; within each
        group a selected token takes the next slot in its expert's queue
        (cumsum order) and tokens past the per-group capacity
        ``C = ceil(top_k · group / E · capacity_factor)`` get an all-zero
        dispatch row, silently losing that expert's contribution (Switch
        token dropping, applied per group).
        """
        B, T, d = x.shape
        E = self.num_experts
        tokens = B * T
        group = min(tokens, self.DISPATCH_GROUP)
        # Pad up to a group multiple with masked rows (weights 0 → never
        # selected, never dispatched) so group size stays fixed for any
        # B·T — a shrinking-divisor fallback would silently degrade to
        # dense-level dispatch FLOPs on awkward (e.g. prime) token counts.
        padded = -(-tokens // group) * group
        n_groups = padded // group
        cap = int(math.ceil(self.top_k * group / E * self.capacity_factor))
        cap = max(1, min(cap, group))
        flat_x = x.reshape(tokens, d)
        flat_w = weights.reshape(tokens, E)
        if padded != tokens:
            pad = padded - tokens
            flat_x = jnp.concatenate(
                [flat_x, jnp.zeros((pad, d), flat_x.dtype)])
            flat_w = jnp.concatenate(
                [flat_w, jnp.zeros((pad, E), flat_w.dtype)])
        gx = flat_x.reshape(n_groups, group, d)
        gw = flat_w.reshape(n_groups, group, E)
        disp, combine = self._dispatch_plan(gw, cap, x.dtype)
        expert_in = jnp.einsum("gsec,gsd->gecd", disp, gx)
        gate = jnp.einsum("gecd,ehd->gech", expert_in, w_gate)
        up = jnp.einsum("gecd,ehd->gech", expert_in, w_up)
        out_e = jnp.einsum("gech,edh->gecd", self._act(gate) * up, w_down)
        y = jnp.einsum("gsec,gecd->gsd", combine, out_e)
        return y.reshape(padded, d)[:tokens].reshape(B, T, d)

    @staticmethod
    def _dispatch_plan(gw, cap, dtype):
        """(dispatch, combine) one-hot tensors, both (G, S, E, C), for
        grouped capacity routing: a selected token takes the next slot in
        its expert's per-group queue (cumsum order); tokens past ``cap``
        one-hot an out-of-range class → all-zero row → dropped."""
        sel = gw > 0
        pos = jnp.cumsum(sel.astype(jnp.int32), axis=1) - 1  # slot in queue
        slot = jnp.where(sel & (pos < cap), pos, cap)
        disp = jax.nn.one_hot(slot, cap, dtype=dtype)
        return disp, disp * gw[..., None]

    def _apply_capacity_ep(self, x, weights, w_gate, w_up, w_down, mesh):
        """Expert-parallel capacity dispatch: ``lax.all_to_all`` token
        routing over the mesh ``expert`` axis (GShard-style).

        Dispatch groups shard over the expert axis; each device packs its
        local groups' tokens into per-expert buffers, one all_to_all sends
        each expert's (capacity-bounded) buffers to the device owning that
        expert shard, the expert MLP runs on the local expert slice for
        every group, and the reverse all_to_all returns outputs for a
        local combine.  Same routing math as :meth:`_apply_capacity`
        (shared ``_dispatch_plan``), but the cross-device traffic is two
        all_to_alls of the packed buffers instead of the full-activation
        psum the einsum formulation compiles to under GSPMD (r04 EP
        census: 34 all-reduces, zero all-to-all, 7x the DP step time).
        Only the expert axis goes manual — data/model/sequence stay
        GSPMD-automatic, so the path composes with DP/TP meshes.
        """
        from jax.sharding import PartitionSpec as P
        from penroz_tpu.parallel.mesh import EXPERT_AXIS
        ep = mesh.shape[EXPERT_AXIS]
        B, T, d = x.shape
        E = self.num_experts
        tokens = B * T
        group = min(tokens, self.DISPATCH_GROUP)
        n_groups = -(-tokens // group)
        # Round the group count up to an ep multiple with fully masked
        # groups (weights 0 → all-zero dispatch) so the group dim splits
        # evenly over the axis; the waste is < 1 group per device.
        n_groups += (-n_groups) % ep
        padded = n_groups * group
        cap = int(math.ceil(self.top_k * group / E * self.capacity_factor))
        cap = max(1, min(cap, group))
        flat_x = x.reshape(tokens, d)
        flat_w = weights.reshape(tokens, E)
        if padded != tokens:
            pad = padded - tokens
            flat_x = jnp.concatenate(
                [flat_x, jnp.zeros((pad, d), flat_x.dtype)])
            flat_w = jnp.concatenate(
                [flat_w, jnp.zeros((pad, E), flat_w.dtype)])
        # The expert-manual split gets its OWN leading dim (ep, G/ep, …):
        # Shardy rejects a dimension whose sharding mixes a free axis
        # before a manual one (e.g. the group dim co-sharded (data,
        # expert) inside the GPipe schedule), so no dim may carry both.
        gx = flat_x.reshape(ep, n_groups // ep, group, d)
        gw = flat_w.reshape(ep, n_groups // ep, group, E)

        def body(gx_l, gw_l, wg_l, wu_l, wd_l):
            # gx_l: (1, G/ep, S, d); gw_l: (1, G/ep, S, E) — local
            # groups, all experts.  wg_l/wu_l: (E/ep, h, d).
            disp, combine = self._dispatch_plan(gw_l[0], cap, gx_l.dtype)
            expert_in = jnp.einsum("gsec,gsd->gecd", disp, gx_l[0])
            # Send expert chunk p to device p; receive every device's
            # groups for the local experts: (G, E/ep, C, d).
            expert_in = jax.lax.all_to_all(expert_in, EXPERT_AXIS, 1, 0,
                                           tiled=True)
            gate = jnp.einsum("gecd,ehd->gech", expert_in, wg_l)
            up = jnp.einsum("gecd,ehd->gech", expert_in, wu_l)
            out_e = jnp.einsum("gech,edh->gecd", self._act(gate) * up, wd_l)
            # Return each group's outputs to its owner: (G/ep, E, C, d).
            out_e = jax.lax.all_to_all(out_e, EXPERT_AXIS, 0, 1, tiled=True)
            return jnp.einsum("gsec,gecd->gsd", combine, out_e)[None]

        spec4 = P(EXPERT_AXIS, None, None, None)
        spec3 = P(EXPERT_AXIS, None, None)
        y = jax.shard_map(body, mesh=mesh,
                          in_specs=(spec4, spec4, spec3, spec3, spec3),
                          out_specs=spec4,
                          axis_names=frozenset({EXPERT_AXIS}))(
            gx, gw, w_gate, w_up, w_down)
        return y.reshape(padded, d)[:tokens].reshape(B, T, d)


# ---------------------------------------------------------------------------
# Attention
# ---------------------------------------------------------------------------

class CausalSelfAttention(Module):
    """Causal self-attention over a fused QKV input with GQA + optional RoPE.

    Consumes a ``(B, T, q_dim + 2*kv_dim)`` projection (reference:
    neural_net_layers.py:59-95).  Head dim is derived from the input width.
    When a KV cache is present in the Ctx, new K/V are written at the current
    cache length (pre-GQA-expansion — unlike the reference, which expands KV
    heads before caching, we store only ``num_kv_heads`` heads in HBM).
    """

    def __init__(self, num_heads: int, dropout: float = 0.0,
                 num_kv_heads: Optional[int] = None,
                 rope_theta: Optional[float] = None,
                 head_dim: Optional[int] = None,
                 rope_scaling: Optional[dict] = None,
                 sliding_window: Optional[int] = None,
                 rope_pct: Optional[float] = None,
                 qk_norm: bool = False, qk_norm_eps: float = 1e-6,
                 qk_norm_scope: str = "head", rope_dim=None,
                 qk_norm_fp32_weight: bool = False, alibi: bool = False,
                 logit_softcap=None, attn_scale=None):
        if sliding_window is not None and int(sliding_window) < 1:
            raise ValueError(f"sliding_window must be >= 1, "
                             f"got {sliding_window}")
        # RMS normalization of q and k before RoPE.  scope="head" (Qwen3:
        # RMSNorm(head_dim) applied per head after the reshape, learned
        # (head_dim,) weights); scope="flat" (OLMo-2: RMSNorm over the
        # WHOLE projection before the head split, learned (H*hd,) /
        # (KV*hd,) weights).  Either way the module needs head_dim at
        # build time to size the weights.
        if qk_norm_scope not in ("head", "flat"):
            raise ValueError(f"qk_norm_scope must be 'head' or 'flat', "
                             f"got {qk_norm_scope!r}")
        self.qk_norm = bool(qk_norm)
        self.qk_norm_eps = float(qk_norm_eps)
        self.qk_norm_scope = qk_norm_scope
        # Weight-multiply precision order differs BY FAMILY in HF:
        # Qwen3RMSNorm (a LlamaRMSNorm copy) downcasts the normalized
        # activations to input dtype BEFORE multiplying the weight;
        # Olmo2RMSNorm multiplies the fp32 weight in fp32 and downcasts
        # once at the end.  A global choice skews bf16 imports of the
        # other family by one rounding step per element.
        self.qk_norm_fp32_weight = bool(qk_norm_fp32_weight)
        if self.qk_norm and head_dim is None:
            raise ValueError("qk_norm=True requires an explicit head_dim")
        self.sliding_window = (int(sliding_window)
                               if sliding_window is not None else None)
        self.num_heads = int(num_heads)
        self.num_kv_heads = int(num_kv_heads) if num_kv_heads is not None else int(num_heads)
        self.dropout = float(dropout)
        # ALiBi (Press et al. 2022, BLOOM/MPT): per-head linear position
        # bias on the attention logits instead of rotary/learned
        # positions; slopes are a pure function of the head count.
        self.alibi = bool(alibi)
        if self.alibi and rope_theta is not None:
            raise ValueError("alibi and rope_theta are mutually exclusive "
                             "position encodings")
        # Gemma-2: score soft-capping c·tanh(s/c) and the
        # query_pre_attn_scalar^-0.5 scale override.
        if logit_softcap is not None and float(logit_softcap) <= 0.0:
            raise ValueError(f"logit_softcap must be > 0, "
                             f"got {logit_softcap}")
        self.logit_softcap = (float(logit_softcap)
                              if logit_softcap is not None else None)
        self.attn_scale = (float(attn_scale)
                           if attn_scale is not None else None)
        self.rope_theta = float(rope_theta) if rope_theta is not None else None
        self.head_dim = int(head_dim) if head_dim is not None else None
        # Partial rotary (GPT-NeoX rotary_pct): rotate only the first
        # int(head_dim * rope_pct) feature dims (rounded to even).
        if rope_pct is not None and not 0.0 < float(rope_pct) <= 1.0:
            raise ValueError(f"rope_pct must be in (0, 1], got {rope_pct}")
        self.rope_pct = float(rope_pct) if rope_pct is not None else None
        # Exact integer rotary width (GPT-J rotary_dim): overrides the
        # pct-derived value, whose float round-trip can drop 2 dims for
        # awkward (head_dim, rotary_dim) pairs.
        if rope_dim is not None and (int(rope_dim) < 2 or int(rope_dim) % 2):
            raise ValueError(f"rope_dim must be even and >= 2, "
                             f"got {rope_dim}")
        self.rope_dim = int(rope_dim) if rope_dim is not None else None
        # llama3-type inverse-frequency rescaling (ops/attention.rope_cos_sin).
        # Validated HERE, at model build time (→ HTTP 400 on POST /model/):
        # the DSL reaches this module directly, so the HF importer's guard
        # alone would let a yarn dict silently run the llama3 formula or a
        # missing key crash opaquely at first jit trace.
        if rope_scaling and (rope_scaling.get("rope_type")
                             or rope_scaling.get("type")) == "linear":
            # HF linear scaling: positions divide by the factor (Gemma-3
            # global layers); no band parameters to validate.
            if float(rope_scaling.get("factor", 0.0)) < 1.0:
                raise ValueError("linear rope_scaling needs factor >= 1")
            self.rope_scaling = {"rope_type": "linear",
                                 "factor": float(rope_scaling["factor"])}
        elif rope_scaling:
            rope_type = (rope_scaling.get("rope_type")
                         or rope_scaling.get("type") or "default")
            if rope_type != "llama3":
                raise ValueError(f"rope_scaling type {rope_type!r} is not "
                                 "supported (only 'llama3' and 'linear')")
            missing = [k for k in ("factor",
                                   "original_max_position_embeddings")
                       if k not in rope_scaling]
            if missing:
                raise ValueError(f"rope_scaling missing keys: {missing}")
            low = float(rope_scaling.get("low_freq_factor", 1.0))
            high = float(rope_scaling.get("high_freq_factor", 4.0))
            if high <= low:
                # the band-smoothing divides by (high - low): equal factors
                # would NaN every logit at first forward (HF's
                # rope_config_validation rejects this too)
                raise ValueError(f"rope_scaling needs high_freq_factor > "
                                 f"low_freq_factor, got {low} >= {high}")
            if float(rope_scaling["factor"]) < 1.0:
                raise ValueError("rope_scaling factor must be >= 1")
            self.rope_scaling = {
                "rope_type": "llama3",
                "factor": float(rope_scaling["factor"]),
                "low_freq_factor":
                    float(rope_scaling.get("low_freq_factor", 1.0)),
                "high_freq_factor":
                    float(rope_scaling.get("high_freq_factor", 4.0)),
                "original_max_position_embeddings":
                    float(rope_scaling["original_max_position_embeddings"]),
            }
        else:
            self.rope_scaling = None
        self.layer_idx = 0  # assigned by the model builder

    def param_shapes(self):
        if not self.qk_norm:
            return {}
        if self.qk_norm_scope == "flat":
            return {"q_norm.weight": (self.num_heads * self.head_dim,),
                    "k_norm.weight": (self.num_kv_heads * self.head_dim,)}
        return {"q_norm.weight": (self.head_dim,),
                "k_norm.weight": (self.head_dim,)}

    def init(self, rng):
        return {self.key(name): jnp.ones(shape, jnp.float32)
                for name, shape in self.param_shapes().items()}

    def _head_rmsnorm(self, x, w):
        """fp32 RMS over the head dim, learned multiplicative weight."""
        xf = x.astype(jnp.float32)
        norm = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True)
                             + self.qk_norm_eps)
        if self.qk_norm_fp32_weight:
            # Olmo2RMSNorm order: (weight * fp32_normed).to(input_dtype).
            return ((xf * norm) * w.astype(jnp.float32)).astype(x.dtype)
        # Qwen3/LlamaRMSNorm order: weight * normed.to(input_dtype).
        return ((xf * norm).astype(x.dtype) * w).astype(x.dtype)

    def apply(self, qkv, ctx):
        B, T, total_dim = qkv.shape
        head_dim = total_dim // (self.num_heads + 2 * self.num_kv_heads)
        q_dim = self.num_heads * head_dim
        kv_dim = self.num_kv_heads * head_dim

        q_flat = qkv[..., :q_dim]
        k_flat = qkv[..., q_dim:q_dim + kv_dim]
        if self.qk_norm and self.qk_norm_scope == "flat":
            # OLMo-2: normalize the whole projection BEFORE the head split.
            q_flat = self._head_rmsnorm(q_flat, self._p(ctx, "q_norm.weight"))
            k_flat = self._head_rmsnorm(k_flat, self._p(ctx, "k_norm.weight"))
        q = q_flat.reshape(B, T, self.num_heads, head_dim)
        k = k_flat.reshape(B, T, self.num_kv_heads, head_dim)
        v = qkv[..., q_dim + kv_dim:].reshape(B, T, self.num_kv_heads, head_dim)
        # to (B, H, T, D)
        q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))

        if self.qk_norm and self.qk_norm_scope == "head":
            q = self._head_rmsnorm(q, self._p(ctx, "q_norm.weight"))
            k = self._head_rmsnorm(k, self._p(ctx, "k_norm.weight"))

        offset = ctx.offset()
        if self.rope_theta is not None:
            if ctx.sp_manual_axis is not None:
                # Manual sequence sharding (GPipe×Ulysses): this shard
                # holds rows r·T_local..(r+1)·T_local-1 of the global
                # sequence — rotate with GLOBAL positions, not 0..T_local.
                offset = offset + jax.lax.axis_index(ctx.sp_manual_axis) * T
            rotary_dim = None
            if self.rope_dim is not None:
                rotary_dim = None if self.rope_dim >= head_dim \
                    else self.rope_dim
            elif self.rope_pct is not None and self.rope_pct < 1.0:
                rotary_dim = int(head_dim * self.rope_pct) // 2 * 2
            q, k = attn_ops.apply_rope(q, k, self.rope_theta, offset,
                                       scaling=self.rope_scaling,
                                       rotary_dim=rotary_dim)

        dropout_rate = self.dropout if ctx.training else 0.0
        dropout_rng = ctx.next_rng() if (dropout_rate > 0.0 and ctx.training) else None

        alibi = attn_ops.alibi_slopes(self.num_heads) if self.alibi else None

        if ctx.kv is not None:
            from penroz_tpu.ops import kv_cache as KV
            paged = isinstance(ctx.kv, KV.PagedKVState)
            ragged = paged and ctx.ragged_descs is not None
            if ragged:
                store_k, store_v = ctx.kv.append_packed(
                    self.layer_idx, k, v, ctx.ragged_rows)
                length = None
            elif paged:
                store_k, store_v, length = ctx.kv.append_rows(self.layer_idx,
                                                              k, v)
            elif ctx.kv.quantized:
                # int8 cache: store + attend on the raw buffers — the
                # kernel dequantizes per VMEM tile, never materializing a
                # full-precision cache.
                store_k, store_v, length = ctx.kv.append_raw(self.layer_idx,
                                                             k, v)
            else:
                store_k, store_v, length = ctx.kv.append(self.layer_idx,
                                                         k, v)
            # int8 caches (paged pools and contiguous) carry per-token
            # scales; read AFTER the append so the new tokens' scales are in.
            scales = ({"k_scale": ctx.kv.k_scale[self.layer_idx],
                       "v_scale": ctx.kv.v_scale[self.layer_idx]}
                      if ctx.kv.quantized else {})
            if ragged:
                out = attn_ops.ragged_paged_cached_attention(
                    q, store_k, store_v, ctx.kv.block_table,
                    ctx.kv.page_size, ctx.ragged_descs,
                    platform=ctx.platform, window=self.sliding_window,
                    alibi=alibi, scale=self.attn_scale,
                    softcap=self.logit_softcap, **scales)
            elif paged:
                out = attn_ops.paged_cached_attention(
                    q, store_k, store_v, ctx.kv.block_table, ctx.kv.page_size,
                    offset, length, dropout_rate=dropout_rate,
                    dropout_rng=dropout_rng, platform=ctx.platform,
                    window=self.sliding_window, alibi=alibi,
                    scale=self.attn_scale, softcap=self.logit_softcap,
                    **scales)
            else:
                out = attn_ops.cached_attention(q, store_k, store_v, offset,
                                                length,
                                                dropout_rate=dropout_rate,
                                                dropout_rng=dropout_rng,
                                                platform=ctx.platform,
                                                window=self.sliding_window,
                                                alibi=alibi,
                                                scale=self.attn_scale,
                                                softcap=self.logit_softcap,
                                                **scales)
        elif ctx.sp_manual_axis is not None and dropout_rate == 0.0:
            # Inside the GPipe schedule with the sequence axis manual: the
            # SP bodies run on the ambient axis (a nested shard_map is
            # impossible).  Same mode dispatch + divisibility fallback as
            # the sp_mesh path below.
            from penroz_tpu.parallel import alltoall_attention as a2a
            from penroz_tpu.parallel import ring_attention as ring
            n_seq = jax.lax.axis_size(ctx.sp_manual_axis)
            if (ctx.sp_mode == "alltoall" and alibi is None
                    and self.logit_softcap is None
                    and a2a.alltoall_supported(
                        q.shape[1], k.shape[1], n=n_seq)):
                out = a2a.alltoall_attention_manual(
                    q, k, v, axis_name=ctx.sp_manual_axis,
                    window=self.sliding_window, platform=ctx.platform,
                    scale=self.attn_scale)
            else:
                if ctx.sp_mode == "alltoall":
                    # Trace-time (shapes are static), so the operator gets
                    # a signal — mirrors the sp_mesh path's warning.
                    # (ALiBi also lands here: the Ulysses body re-shards
                    # HEADS, whose slopes would become device-dynamic.)
                    logging.getLogger(__name__).warning(
                        "alltoall SP unavailable (heads Hq=%d/Hkv=%d vs "
                        "axis %d, or alibi bias); falling back to ring "
                        "attention", q.shape[1], k.shape[1], n_seq)
                out = ring.ring_attention_manual(
                    q, k, v, axis_name=ctx.sp_manual_axis,
                    window=self.sliding_window, alibi=alibi,
                    scale=self.attn_scale, softcap=self.logit_softcap)
        elif ctx.sp_mesh is not None and dropout_rate == 0.0:
            # Sequence-parallel training over ICI (windowed when the model
            # slides — long-context SP is exactly where windows matter).
            # Two modes: 'ring' rotates K/V via ppermute; 'alltoall'
            # (Ulysses) re-partitions seq→head sharding so each device runs
            # the ordinary fused kernel on the full sequence for its heads
            # (falls back to ring when heads don't divide the axis).
            from penroz_tpu.parallel import alltoall_attention as a2a
            from penroz_tpu.parallel.ring_attention import ring_attention
            if (ctx.sp_mode == "alltoall" and alibi is None
                    and self.logit_softcap is None
                    and a2a.alltoall_supported(q.shape[1], k.shape[1],
                                               ctx.sp_mesh)):
                out = a2a.alltoall_attention(q, k, v, ctx.sp_mesh,
                                             causal=True,
                                             window=self.sliding_window,
                                             platform=ctx.platform,
                                             scale=self.attn_scale)
            else:
                if ctx.sp_mode == "alltoall":
                    # every fallback cause gets a trace-time signal, like
                    # the manual-axis branch
                    logging.getLogger(__name__).warning(
                        "alltoall SP unavailable (indivisible heads, "
                        "alibi, or logit softcap); falling back to ring "
                        "attention")
                out = ring_attention(q, k, v, ctx.sp_mesh, causal=True,
                                     window=self.sliding_window,
                                     alibi=alibi, scale=self.attn_scale,
                                     softcap=self.logit_softcap)
        else:
            out = attn_ops.causal_attention(q, k, v, dropout_rate=dropout_rate,
                                            dropout_rng=dropout_rng,
                                            platform=ctx.platform,
                                            window=self.sliding_window,
                                            alibi=alibi,
                                            scale=self.attn_scale,
                                            softcap=self.logit_softcap)

        return out.transpose(0, 2, 1, 3).reshape(B, T, q_dim)


class GatedSSM(Module):
    """Gated linear-attention / SSD token mixer with O(1) per-row state.

    Consumes a fused projection laid out ``[q (H·dk) | k (H·dk) | v (H·dv)
    | gate (H)]`` — the SSM analogue of attention's fused qkv Linear — and
    runs the recurrence ``S_t = σ(gate_t)·S_{t-1} + k_t ⊗ v_t,
    y_t = q_t·S_t`` (ops/ssm.py).  No positional encoding: the recurrence
    itself is the position signal, so the layer needs no RoPE/offset.

    Cached serving rides ``ctx.kv.ssm`` (the fixed-size
    :class:`~penroz_tpu.ops.ssm.SSMState` child of any KV variant) through
    the same dense / packed-ragged dispatch as attention; without a cache
    the full-sequence chunked form runs (Pallas kernel on TPU, scan oracle
    elsewhere).  ``layer_idx`` indexes the model's *ssm* layers, assigned
    by the model builder like attention's (models/model.py).
    """

    def __init__(self, num_heads: int, head_dim: int,
                 value_dim: int | None = None):
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.value_dim = int(value_dim) if value_dim is not None \
            else int(head_dim)
        self.layer_idx = 0  # assigned by the model builder

    @property
    def fused_dim(self) -> int:
        """Input width the preceding fused Linear must produce."""
        return self.num_heads * (2 * self.head_dim + self.value_dim + 1)

    def apply(self, x, ctx):
        from penroz_tpu.ops import ssm as ssm_ops
        B, T, total = x.shape
        H, dk, dv = self.num_heads, self.head_dim, self.value_dim
        if total != self.fused_dim:
            raise ValueError(f"ssm fused input width {total} != expected "
                             f"{self.fused_dim} (H={H}, dk={dk}, dv={dv})")
        q = x[..., :H * dk].reshape(B, T, H, dk) * (dk ** -0.5)
        k = x[..., H * dk:2 * H * dk].reshape(B, T, H, dk)
        v = x[..., 2 * H * dk:2 * H * dk + H * dv].reshape(B, T, H, dv)
        # fp32 gate: σ saturates in bf16 after ~8 tokens of decay product
        g = jax.nn.sigmoid(
            x[..., 2 * H * dk + H * dv:].astype(jnp.float32)).reshape(B, T, H)

        ssm = getattr(ctx.kv, "ssm", None) if ctx.kv is not None else None
        if ssm is not None:
            if ctx.ragged_descs is not None:
                # packed slots per block = Tp // NB (build_descriptors
                # emits NB equal blocks of block_q slots)
                nb = ctx.ragged_descs.shape[0]
                y = ssm.update_packed(self.layer_idx, q, k, v, g,
                                      ctx.ragged_descs, T // nb)
            else:
                y = ssm.update_dense(self.layer_idx, q, k, v, g,
                                     ctx.offset())
        else:
            y = ssm_ops.gla_full(q, k, v, g, platform=ctx.platform,
                                 training=ctx.training)
        return y.reshape(B, T, H * dv).astype(x.dtype)
