"""Fused chunked softmax cross-entropy for large vocabularies.

The reference computes CE through torch's fused ``F.cross_entropy`` path
(reference: neural_net_model.py:264-268); the naive JAX equivalent
(``logits.astype(f32)`` + optax) materializes a full fp32 ``(B, T, V)`` copy
of the logits and saves fp32 residuals for the backward — ~1.6 GB at B=8,
T=1024, V=50304, almost all of it HBM traffic rather than MXU work.

``fused_cross_entropy_mean`` is a ``custom_vjp`` whose forward saves only the
original (bf16) logits, the integer targets, and the per-row fp32 ``lse``:

- On TPU it dispatches to streaming Pallas kernels
  (ops/pallas/cross_entropy.py) that read the logits exactly once per pass.
- Elsewhere it streams row-chunks through a ``lax.scan`` (fp32 math in
  chunk-sized pieces) — this path is also the kernels' correctness oracle.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# Rows per jnp scan step: big enough to keep the VPU busy, small enough that
# the fp32 temporaries stay cache-sized.
_CHUNK_ROWS = 512


def _use_pallas(x2d, platform) -> bool:
    from penroz_tpu.ops.attention import _tpu_platform
    return (x2d.shape[-1] >= 1024
            and jnp.issubdtype(x2d.dtype, jnp.floating)
            and _tpu_platform(x2d, platform))


def pad_rows(x2d, t1d, chunk: int):
    """Pad rows to a multiple of ``chunk``; padded targets get the -1
    sentinel that every consumer (jnp scan masks, Pallas backward kernel)
    treats as 'no loss / zero gradient'.  Shared with
    ops/pallas/cross_entropy.py — keep the sentinel in sync."""
    n = x2d.shape[0]
    num_chunks = max(1, -(-n // chunk))
    pad = num_chunks * chunk - n
    if pad:
        x2d = jnp.pad(x2d, ((0, pad), (0, 0)))
        t1d = jnp.pad(t1d, (0, pad), constant_values=-1)
    return x2d, t1d, num_chunks


def _jnp_forward(x2d, t1d, chunk_rows: int):
    """Per-row (lse, label_logit) via a row-chunked scan; fp32 (N, 1) each."""
    xp, tp, num_chunks = pad_rows(x2d, t1d, chunk_rows)
    v = xp.shape[-1]
    xc = xp.reshape(num_chunks, chunk_rows, v)
    tc = tp.reshape(num_chunks, chunk_rows)

    def step(_, chunk):
        cx, ct = chunk
        x = cx.astype(jnp.float32)
        m = jnp.max(x, axis=-1)
        lse = m + jnp.log(jnp.sum(jnp.exp(x - m[:, None]), axis=-1))
        safe_t = jnp.maximum(ct, 0)
        ll = jnp.take_along_axis(x, safe_t[:, None], axis=-1)[:, 0]
        return None, (lse, ll)

    _, (lse, ll) = jax.lax.scan(step, None, (xc, tc))
    n = x2d.shape[0]
    return (lse.reshape(-1, 1)[:n], ll.reshape(-1, 1)[:n])


def _jnp_backward(x2d, t1d, lse, scale, chunk_rows: int):
    """(softmax - onehot) · scale from saved lse, row-chunked."""
    xp, tp, num_chunks = pad_rows(x2d, t1d, chunk_rows)
    v = xp.shape[-1]
    pad = xp.shape[0] - x2d.shape[0]
    lp = jnp.pad(lse, ((0, pad), (0, 0))) if pad else lse
    xc = xp.reshape(num_chunks, chunk_rows, v)
    tc = tp.reshape(num_chunks, chunk_rows)
    lc = lp.reshape(num_chunks, chunk_rows, 1)

    def step(_, chunk):
        cx, ct, cl = chunk
        x = cx.astype(jnp.float32)
        p = jnp.exp(x - cl)
        safe_t = jnp.maximum(ct, 0)
        onehot = (jnp.arange(v, dtype=jnp.int32)[None, :] == safe_t[:, None])
        valid = (ct >= 0)[:, None]
        return None, jnp.where(valid, (p - onehot) * scale, 0.0).astype(cx.dtype)

    _, grads = jax.lax.scan(step, None, (xc, tc, lc))
    return grads.reshape(-1, v)[: x2d.shape[0]]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def fused_cross_entropy_mean(logits, targets, chunk_rows: int = _CHUNK_ROWS,
                             platform=None):
    """Mean integer-label CE over all leading dims without fp32 blowup.

    ``logits``: ``(..., V)`` float (bf16 stays bf16 in HBM); ``targets``:
    ``(...,)`` int.  Numerically equivalent (fp32 accumulation) to
    ``optax.softmax_cross_entropy_with_integer_labels(f32(logits), t).mean()``.
    ``platform`` is the execution-placement hint forwarded to the Pallas gate
    (see ops/attention.py:_tpu_platform).
    """
    loss, _ = _fce_fwd(logits, targets, chunk_rows, platform)
    return loss


def _fce_fwd(logits, targets, chunk_rows: int, platform):
    v = logits.shape[-1]
    n = int(np.prod(targets.shape)) if targets.shape else 1
    x2d = logits.reshape(-1, v)
    t1d = targets.reshape(-1).astype(jnp.int32)
    if _use_pallas(x2d, platform):
        from penroz_tpu.ops.pallas import cross_entropy as ce
        lse, ll = ce.ce_forward(x2d, t1d)
    else:
        lse, ll = _jnp_forward(x2d, t1d, chunk_rows)
    loss = jnp.sum(lse - ll) / n
    return loss, (logits, targets, lse)


def _fce_bwd(chunk_rows: int, platform, residuals, gbar):
    logits, targets, lse = residuals
    v = logits.shape[-1]
    n = int(np.prod(targets.shape)) if targets.shape else 1
    x2d = logits.reshape(-1, v)
    t1d = targets.reshape(-1).astype(jnp.int32)
    scale = gbar.astype(jnp.float32) / n
    if _use_pallas(x2d, platform):
        from penroz_tpu.ops.pallas import cross_entropy as ce
        grad = ce.ce_backward(x2d, t1d, lse, scale)
    else:
        grad = _jnp_backward(x2d, t1d, lse, scale, chunk_rows)
    t_tangent = np.zeros(targets.shape, dtype=jax.dtypes.float0)
    return grad.reshape(logits.shape), t_tangent


fused_cross_entropy_mean.defvjp(_fce_fwd, _fce_bwd)
