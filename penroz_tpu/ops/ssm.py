"""Fixed-size recurrent sequence state: gated linear-attention / SSD scan.

The second ``SequenceState`` backend (see ops/kv_cache.py for the protocol):
where the KV variants grow O(T) per row, the SSM state is a constant-size
per-row tensor ``(H, dk, dv)`` per layer, so rollback, preempt-resume,
disagg hand-off and hibernation all become fixed-size copies.

Recurrence (per head, per row; all math fp32):

    S_t = g_t * S_{t-1} + k_t ⊗ v_t          S in R^{dk×dv},  g_t = σ(gate_t)
    y_t = q_t · S_t                           q pre-scaled by dk^-0.5

Three execution forms, all bit-identical in greedy decoding because every
*cached* path uses the same sequential ``lax.scan`` token order:

- ``update_dense``  — cached prefill / batched decode: scan over T with a
  scalar-or-(B,) position offset (row views, batch generate, supersteps).
- ``update_packed`` — the unified ragged path: scan over the Tp packed slots
  of a ``build_descriptors`` block layout, read-modify-write per valid slot
  (mirrors ``PagedKVState.append_packed`` addressing).
- ``gla_full``      — no-cache training/eval: jnp sequential oracle on CPU,
  chunked Pallas kernel (ops/pallas/ssm_scan.py) on TPU inference.

Checkpoint ring (exact spec-decode rollback): every token write also stores
the post-token state in a ring of ``ckpt_slots`` slots keyed by the *length
after the token* (``ckpt_pos``; −1 = empty).  ``rollback_row(row, L)``
restores the state checkpointed at length L (zeros for L == 0) and
invalidates slots from the discarded future — no replay, no page moves.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np


def ckpt_slots_default() -> int:
    """Ring size: enough for a spec-decode verify block plus slack."""
    slots = int(os.environ.get("PENROZ_SSM_CKPT", "8"))
    spec = int(os.environ.get("PENROZ_SPEC_DECODE", "0") or 0)
    return max(slots, spec + 2, 2)


def _outer(k_t, v_t):
    """k ⊗ v over trailing dims: (..., dk) x (..., dv) -> (..., dk, dv)."""
    return k_t[..., :, None] * v_t[..., None, :]


@jax.tree_util.register_pytree_node_class
class SSMState:
    """Per-row recurrent state for every ``ssm`` block of a model.

    Children: per-layer ``state`` (B, H, dk, dv) fp32, per-layer ``ckpt``
    (B, C, H, dk, dv) fp32 and ONE shared ``ckpt_pos`` (B, C) int32 (every
    layer checkpoints at the same positions, so the slot map is common).
    """

    def __init__(self, state, ckpt, ckpt_pos, specs, ckpt_slots):
        self.state = list(state)
        self.ckpt = list(ckpt)
        self.ckpt_pos = ckpt_pos
        self.specs = tuple(tuple(int(x) for x in s) for s in specs)
        self.ckpt_slots = int(ckpt_slots)

    # -- pytree -------------------------------------------------------------
    def tree_flatten(self):
        return ((tuple(self.state), tuple(self.ckpt), self.ckpt_pos),
                (self.specs, self.ckpt_slots))

    @classmethod
    def tree_unflatten(cls, aux, children):
        state, ckpt, ckpt_pos = children
        return cls(state, ckpt, ckpt_pos, aux[0], aux[1])

    # -- construction -------------------------------------------------------
    @classmethod
    def create(cls, specs, batch, ckpt_slots=None):
        """Zero state for ``specs = [(num_heads, head_dim, value_dim), ...]``."""
        C = int(ckpt_slots) if ckpt_slots else ckpt_slots_default()
        B = int(batch)
        state = [jnp.zeros((B, h, dk, dv), jnp.float32)
                 for (h, dk, dv) in specs]
        ckpt = [jnp.zeros((B, C, h, dk, dv), jnp.float32)
                for (h, dk, dv) in specs]
        ckpt_pos = jnp.full((B, C), -1, jnp.int32)
        return cls(state, ckpt, ckpt_pos, specs, C)

    @property
    def batch(self) -> int:
        return int(self.ckpt_pos.shape[0])

    def nbytes(self) -> int:
        n = self.ckpt_pos.size * self.ckpt_pos.dtype.itemsize
        for arr in (*self.state, *self.ckpt):
            n += arr.size * arr.dtype.itemsize
        return int(n)

    # -- SequenceState contract --------------------------------------------
    def reset(self):
        return SSMState([jnp.zeros_like(s) for s in self.state],
                        [jnp.zeros_like(c) for c in self.ckpt],
                        jnp.full_like(self.ckpt_pos, -1),
                        self.specs, self.ckpt_slots)

    def reset_row(self, row):
        state = [jax.lax.dynamic_update_slice_in_dim(
                     s, jnp.zeros_like(s[:1]), row, 0) for s in self.state]
        ckpt = [jax.lax.dynamic_update_slice_in_dim(
                    c, jnp.zeros_like(c[:1]), row, 0) for c in self.ckpt]
        ckpt_pos = jax.lax.dynamic_update_slice_in_dim(
            self.ckpt_pos, jnp.full_like(self.ckpt_pos[:1], -1), row, 0)
        return SSMState(state, ckpt, ckpt_pos, self.specs, self.ckpt_slots)

    def insert_row(self, row, src):
        """Copy a freshly prefilled batch-1 ``SSMState`` into row ``row``
        (the KV ``insert_row`` contract — admission of a newcomer)."""
        if src.specs != self.specs:
            raise ValueError(f"insert_row source specs {src.specs} != "
                             f"destination specs {self.specs}")
        return self.merge_row(row, src)

    def import_row(self, row, blob):
        """Install per-layer states for one row (hand-off / resume import).

        ``blob`` maps ``"state"`` to a list of (H, dk, dv) arrays (host numpy
        or device).  Checkpoints for the row start empty — the next decoded
        tokens repopulate the ring before any rollback can need them.
        """
        out = self.reset_row(row)
        state = [jax.lax.dynamic_update_slice_in_dim(
                     s, jnp.asarray(b, jnp.float32)[None], row, 0)
                 for s, b in zip(out.state, blob["state"])]
        return SSMState(state, out.ckpt, out.ckpt_pos,
                        self.specs, self.ckpt_slots)

    def rollback_row(self, row, new_length):
        """Exact rewind of one row to ``new_length`` via the checkpoint ring.

        Length 0 restores zeros.  A missing checkpoint keeps the current
        state (spec-decode writes every verified token into the ring, so
        the target length is always present there).
        """
        L = jnp.asarray(new_length, jnp.int32)
        pos_row = jax.lax.dynamic_slice_in_dim(self.ckpt_pos, row, 1, 0)[0]
        hit = pos_row == L  # at most one: slot v%C only ever stores value v
        any_hit = jnp.any(hit)
        state = []
        for l, s in enumerate(self.state):
            cur = jax.lax.dynamic_slice_in_dim(s, row, 1, 0)[0]
            ck = jax.lax.dynamic_slice_in_dim(self.ckpt[l], row, 1, 0)[0]
            restored = jnp.einsum("c,c...->...", hit.astype(ck.dtype), ck)
            sel = jnp.where(L == 0, jnp.zeros_like(cur),
                            jnp.where(any_hit, restored, cur))
            state.append(jax.lax.dynamic_update_slice_in_dim(
                s, sel[None], row, 0))
        # drop checkpoints from the discarded future (all of them at L == 0)
        inval = (pos_row > L) | (L == 0)
        pos_new = jnp.where(inval, jnp.int32(-1), pos_row)
        ckpt_pos = jax.lax.dynamic_update_slice_in_dim(
            self.ckpt_pos, pos_new[None], row, 0)
        return SSMState(state, self.ckpt, ckpt_pos,
                        self.specs, self.ckpt_slots)

    def row_view(self, row, length=None):
        """Batch-1 view of one row (rides KV ``row_view`` into jit bodies).
        ``length`` is accepted for contract uniformity and ignored — the
        recurrent state has no positional extent to re-clock."""
        state = [jax.lax.dynamic_slice_in_dim(s, row, 1, 0)
                 for s in self.state]
        ckpt = [jax.lax.dynamic_slice_in_dim(c, row, 1, 0)
                for c in self.ckpt]
        ckpt_pos = jax.lax.dynamic_slice_in_dim(self.ckpt_pos, row, 1, 0)
        return SSMState(state, ckpt, ckpt_pos, self.specs, self.ckpt_slots)

    def merge_row(self, row, view):
        state = [jax.lax.dynamic_update_slice_in_dim(s, vs, row, 0)
                 for s, vs in zip(self.state, view.state)]
        ckpt = [jax.lax.dynamic_update_slice_in_dim(c, vc, row, 0)
                for c, vc in zip(self.ckpt, view.ckpt)]
        ckpt_pos = jax.lax.dynamic_update_slice_in_dim(
            self.ckpt_pos, view.ckpt_pos, row, 0)
        return SSMState(state, ckpt, ckpt_pos, self.specs, self.ckpt_slots)

    def export_row(self, row, device: bool = False):
        """Constant-size blob for hand-off/hibernation: live state only."""
        arrs = [s[row] for s in self.state]
        if not device:
            arrs = [np.asarray(a) for a in arrs]
        return {"state": arrs, "specs": [list(s) for s in self.specs]}

    def export_row_pages(self, row, length, device: bool = False):
        """Contract alias for :meth:`export_row` — the "pages" of a
        recurrent row are its constant-size state blob; ``length`` is
        irrelevant to the export size (that is the whole point)."""
        return self.export_row(int(row), device=device)

    def import_row_pages(self, row, blob):
        """Contract alias for :meth:`import_row`."""
        return self.import_row(int(row), blob)

    def export_all(self, device: bool = False):
        """Whole-batch blob (full-cache hibernation path)."""
        state = self.state if device else [np.asarray(s) for s in self.state]
        return {"state": state, "specs": [list(s) for s in self.specs]}

    def import_all(self, blob):
        state = [jnp.asarray(b, jnp.float32) for b in blob["state"]]
        return SSMState(state, [jnp.zeros_like(c) for c in self.ckpt],
                        jnp.full_like(self.ckpt_pos, -1),
                        self.specs, self.ckpt_slots)

    # -- cached scan updates (mutating, like KV append_*) -------------------
    def update_dense(self, layer_idx, q, k, v, g, start):
        """Sequential scan over T for B rows at offset ``start`` (scalar or
        (B,)); mutates this layer's state + checkpoints, returns y
        (B, T, H, dv) fp32."""
        B, T = q.shape[0], q.shape[1]
        C = self.ckpt_slots
        start = jnp.broadcast_to(jnp.asarray(start, jnp.int32), (B,))
        pos_after = start[None, :] + jnp.arange(T, dtype=jnp.int32)[:, None] + 1
        rows = jnp.arange(B)
        xs = (q.swapaxes(0, 1).astype(jnp.float32),
              k.swapaxes(0, 1).astype(jnp.float32),
              v.swapaxes(0, 1).astype(jnp.float32),
              g.swapaxes(0, 1).astype(jnp.float32),
              pos_after)

        def step(carry, xt):
            s, ck, cp = carry
            q_t, k_t, v_t, g_t, pa = xt
            s = g_t[..., None, None] * s + _outer(k_t, v_t)
            y = jnp.einsum("bhk,bhkv->bhv", q_t, s)
            slot = pa % C
            ck = ck.at[rows, slot].set(s)
            cp = cp.at[rows, slot].set(pa)
            return (s, ck, cp), y

        carry = (self.state[layer_idx], self.ckpt[layer_idx], self.ckpt_pos)
        (s, ck, cp), ys = jax.lax.scan(step, carry, xs)
        self.state[layer_idx] = s
        self.ckpt[layer_idx] = ck
        self.ckpt_pos = cp
        return ys.swapaxes(0, 1)

    def update_packed(self, layer_idx, q, k, v, g, descs, block_q):
        """Sequential scan over the Tp packed slots of the unified ragged
        layout (descs: (NB, 4) [row, start, count, _]); q/k/v/g are
        (1, Tp, ...).  Invalid tail slots of each block are dropped via
        out-of-bounds scatter.  Returns y (1, Tp, H, dv) fp32."""
        B = self.ckpt_pos.shape[0]
        C = self.ckpt_slots
        Tp = q.shape[1]
        xs = (q[0].astype(jnp.float32), k[0].astype(jnp.float32),
              v[0].astype(jnp.float32), g[0].astype(jnp.float32),
              jnp.arange(Tp, dtype=jnp.int32))

        def step(carry, xt):
            st, ck, cp = carry
            q_p, k_p, v_p, g_p, p = xt
            blk = p // block_q
            t = p - blk * block_q
            row = descs[blk, 0]
            valid = t < descs[blk, 2]
            pa = descs[blk, 1] + t + 1
            s = jnp.take(st, row, axis=0)
            s_new = g_p[..., None, None] * s + _outer(k_p, v_p)
            y = jnp.einsum("hk,hkv->hv", q_p, s_new)
            srow = jnp.where(valid, row, B)  # B is out of bounds -> drop
            st = st.at[srow].set(s_new, mode="drop")
            slot = pa % C
            ck = ck.at[srow, slot].set(s_new, mode="drop")
            cp = cp.at[srow, slot].set(pa, mode="drop")
            return (st, ck, cp), y

        carry = (self.state[layer_idx], self.ckpt[layer_idx], self.ckpt_pos)
        (st, ck, cp), ys = jax.lax.scan(step, carry, xs)
        self.state[layer_idx] = st
        self.ckpt[layer_idx] = ck
        self.ckpt_pos = cp
        return ys[None]


# ---------------------------------------------------------------------------
# No-cache full-sequence form (training / uncached eval)
# ---------------------------------------------------------------------------

def gla_full_reference(q, k, v, g):
    """Sequential-scan oracle: exact recurrence, (B, T, H, ·) -> fp32."""
    B = q.shape[0]
    H, dk = q.shape[2], q.shape[3]
    dv = v.shape[-1]

    def step(s, xt):
        q_t, k_t, v_t, g_t = xt
        s = g_t[..., None, None] * s + _outer(k_t, v_t)
        return s, jnp.einsum("bhk,bhkv->bhv", q_t, s)

    s0 = jnp.zeros((B, H, dk, dv), jnp.float32)
    xs = tuple(t.swapaxes(0, 1).astype(jnp.float32) for t in (q, k, v, g))
    _, ys = jax.lax.scan(step, s0, xs)
    return ys.swapaxes(0, 1)


def gla_full(q, k, v, g, platform=None, training: bool = False):
    """Full causal gated linear attention, no cache.  TPU inference runs the
    chunked Pallas kernel; training and CPU run the differentiable scan
    oracle (the kernel defines no VJP)."""
    from penroz_tpu.ops import attention as attn_ops
    if not training and attn_ops._tpu_platform(q, platform):
        from penroz_tpu.ops.pallas import ssm_scan
        return ssm_scan.gla_chunked(q, k, v, g)
    return gla_full_reference(q, k, v, g)
