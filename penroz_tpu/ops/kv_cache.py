"""KV cache for autoregressive decoding.

Two layers of abstraction:

- ``KVState`` / ``QuantKVState`` — functional, *preallocated* per-layer HBM
  buffers threaded through the jitted decode step.  Appends are
  ``lax.dynamic_update_slice`` writes at the current length; a single scalar
  ``length`` is shared by all layers and advanced once per model step.  This
  replaces the reference's grow-by-concat mutable cache (kv_cache.py:41-68)
  with a static-shape design XLA can compile once.

- ``KVCache`` / ``TurboQuantKVCache`` — small Python wrappers carrying
  ``KVCacheMetrics`` and the reference's append/get/clear/seq_len surface
  (kv_cache.py:25-206) for API/test parity and observability.  The int8
  "TurboQuant" variant stores values with per-token scales and dequantizes on
  read (kv_cache.py:101-195); the same env flag ``TURBO_QUANT_KV_CACHE=1``
  selects it.

Every cache variant here — and the fixed-size recurrent backend in
ops/ssm.py — implements the :class:`SequenceState` protocol: the per-row
slot-management contract the continuous-batching scheduler drives
(insert/reset/rollback/row_view/merge plus the export/import hand-off pair).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass
from typing import Protocol, runtime_checkable

import jax
import jax.numpy as jnp
import numpy as np

log = logging.getLogger(__name__)

TURBO_QUANT_ENV = "TURBO_QUANT_KV_CACHE"
PAGED_ENV = "PAGED_KV_CACHE"
PAGE_SIZE_ENV = "PENROZ_KV_PAGE_SIZE"
PREFIX_CACHE_ENV = "PENROZ_PREFIX_CACHE"
PREFIX_CACHE_PAGES_ENV = "PENROZ_PREFIX_CACHE_PAGES"

# -- pool-capacity drop accounting ------------------------------------------
# ``PagedKVState._allocate`` clamps page assignment at pool capacity and the
# row lookups clip, so an overflowing append silently overwrites the final
# page instead of raising (append_rows docstring).  The clamp itself runs
# inside jit where it cannot be observed; the host-side callers that CAN see
# an overflow coming (the eager oracle paths here, the continuous-batching
# scheduler's capacity retirements) record it through this process-wide
# counter so /serving_stats/ can surface silent truncation.
_POOL_DROP_LOCK = threading.Lock()
_POOL_DROPS = 0
_POOL_DROP_WARNED = False


def record_pool_drop(tokens: int = 1, context: str = ""):
    """Count ``tokens`` KV writes dropped/overwritten at pool capacity.
    Logs a warning on the first occurrence (per process)."""
    global _POOL_DROPS, _POOL_DROP_WARNED
    with _POOL_DROP_LOCK:
        _POOL_DROPS += int(tokens)
        first = not _POOL_DROP_WARNED
        _POOL_DROP_WARNED = True
    if first:
        log.warning(
            "KV pool capacity exceeded for the first time (%d token(s) "
            "dropped%s) — sequences hitting this are truncated; grow the "
            "pool (block_size / pool_pages) or admit fewer rows",
            tokens, f"; {context}" if context else "")


def pool_drop_count() -> int:
    return _POOL_DROPS


def reset_pool_drop_count():
    """Test hook: zero the counter and re-arm the first-occurrence warning."""
    global _POOL_DROPS, _POOL_DROP_WARNED
    with _POOL_DROP_LOCK:
        _POOL_DROPS = 0
        _POOL_DROP_WARNED = False


# A RadixPrefixCache.unpin without a matching pin means refcount accounting
# broke somewhere upstream — clamping silently (the old behaviour) hides the
# bug until a pinned page gets evicted under a live row.  Same shape as the
# pool-drop counter: process-wide count surfaced through /metrics, warn once
# per node key so a hot retirement path cannot flood the log.
_UNPIN_UNDERFLOW_LOCK = threading.Lock()
_UNPIN_UNDERFLOWS = 0
_UNPIN_UNDERFLOW_WARNED: set = set()


def record_unpin_underflow(key):
    """Count one negative-refcount unpin on the radix node labelled ``key``;
    warn the first time each distinct key underflows."""
    global _UNPIN_UNDERFLOWS
    with _UNPIN_UNDERFLOW_LOCK:
        _UNPIN_UNDERFLOWS += 1
        first = key not in _UNPIN_UNDERFLOW_WARNED
        _UNPIN_UNDERFLOW_WARNED.add(key)
    if first:
        log.warning(
            "RadixPrefixCache.unpin underflow on node key %r: refcount went "
            "negative (unpaired unpin) — clamped to 0; check pin/unpin "
            "pairing on the retirement / preempt-resume paths "
            "(prefix_cache_unpin_underflow counts every occurrence)", key)


def unpin_underflow_count() -> int:
    return _UNPIN_UNDERFLOWS


def reset_unpin_underflow_count():
    """Test hook: zero the counter and re-arm the per-key warnings."""
    global _UNPIN_UNDERFLOWS
    with _UNPIN_UNDERFLOW_LOCK:
        _UNPIN_UNDERFLOWS = 0
        _UNPIN_UNDERFLOW_WARNED.clear()


def turbo_quant_enabled() -> bool:
    return os.environ.get(TURBO_QUANT_ENV, "0") == "1"


def paged_enabled() -> bool:
    return os.environ.get(PAGED_ENV, "0") == "1"


def prefix_cache_enabled() -> bool:
    """``PENROZ_PREFIX_CACHE=1`` opts into radix prefix-KV sharing over the
    paged pool (requires ``PAGED_KV_CACHE=1`` — page granularity is the
    sharing unit; the continuous-batching scheduler checks both)."""
    return os.environ.get(PREFIX_CACHE_ENV, "0") == "1"


def prefix_cache_pages() -> int:
    """Pool pages reserved for the radix prefix cache
    (``PENROZ_PREFIX_CACHE_PAGES``, default 64)."""
    raw = os.environ.get(PREFIX_CACHE_PAGES_ENV, "64")
    try:
        pages = int(raw)
        if pages < 0:
            raise ValueError
    except ValueError:
        log.warning("Ignoring invalid %s=%r; using 64",
                    PREFIX_CACHE_PAGES_ENV, raw)
        return 64
    return pages


def default_page_size() -> int:
    raw = os.environ.get(PAGE_SIZE_ENV, "128")
    try:
        size = int(raw)
        if size <= 0:
            raise ValueError
    except ValueError:
        log.warning("Ignoring invalid %s=%r; using 128", PAGE_SIZE_ENV, raw)
        return 128
    return size


# ---------------------------------------------------------------------------
# Functional state (hot path)
# ---------------------------------------------------------------------------

def _quantize_int8(t):
    """Per-token int8 quantization: scale = amax over head dim / 127."""
    abs_max = jnp.max(jnp.abs(t), axis=-1, keepdims=True)
    scale = abs_max / 127.0
    scale = jnp.where(scale == 0, jnp.ones_like(scale), scale)
    q = jnp.clip(jnp.round(t / scale), -128, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def _dequantize_int8(q, scale, dtype):
    return (q.astype(jnp.float32) * scale).astype(dtype)


def array_device_bytes(a) -> int:
    """Per-device HBM bytes for one array — the shard size, not the
    logical global size.

    Under a serving mesh a head-sharded page pool occupies ``1/tp`` of
    its logical size on each device; the capacity ledger
    (serve/memledger.py) and every ``memory_bytes`` below account what a
    device actually holds, so HBM headroom math stays honest when the
    engine shards.  Replicated arrays, committed single-device arrays
    (``SingleDeviceSharding.shard_shape`` is the identity) and plain
    numpy all report the global size — every unmeshed byte count is
    bit-for-bit what it was before this helper existed.
    """
    shape = tuple(getattr(a, "shape", ()))
    sharding = getattr(a, "sharding", None)
    if sharding is not None:
        try:
            shape = tuple(sharding.shard_shape(shape))
        except (TypeError, ValueError, AttributeError):
            pass  # exotic shardings: report the logical size
    size = int(np.prod(shape, dtype=np.int64)) if shape else 1
    return size * np.dtype(a.dtype).itemsize


@runtime_checkable
class SequenceState(Protocol):
    """Per-row sequence-state contract of the continuous-batching scheduler.

    What was an implicit convention duplicated across the four KV variants
    is the explicit interface any backend must implement to ride the
    unified scheduler — the O(T) paged/contiguous KV caches here and the
    O(1) recurrent state in ops/ssm.py both conform:

    - ``insert_row(row, src)``     — admit a prefilled batch-1 state
    - ``reset_row(row)``           — recycle a slot for the next sequence
    - ``rollback_row(row, L)``     — exact rewind (spec-decode rejection)
    - ``row_view(row, length)``    — batch-1 view for chunked prefill/verify
    - ``merge_row(row, view)``     — fold an advanced view back in
    - ``export_row_pages(row, length, device=False)`` /
      ``import_row_pages(row, blob)`` — the disagg hand-off pair (O(T)
      page moves for KV, a constant-size blob for recurrent state)
    - ``reset()`` and ``hbm_components()`` — lifecycle + byte attribution

    All implementations are registered pytrees whose row ops may take
    traced scalars, so one compiled program serves every slot.
    """

    def insert_row(self, row, src): ...

    def reset_row(self, row): ...

    def rollback_row(self, row, new_length): ...

    def row_view(self, row, length): ...

    def merge_row(self, row, view): ...

    def reset(self): ...


@jax.tree_util.register_pytree_node_class
class KVState:
    """Preallocated functional KV buffers: per-layer (B, Hkv, S_max, D).

    RAGGED batches carry a separate ``ragged_lengths`` (B,) child next to
    the scalar ``_length`` slot rather than replacing it — the scalar leaf
    must survive into the ragged state so a donated input cache's scalar
    buffer has a matching output to alias (otherwise every batched prefill
    emits "donated buffers were not usable: int32[]").  The stale scalar is
    poisoned to -1 so a direct read fails loudly; ``length`` masks it.

    **Scan-carry contract** (compiled multi-step decode,
    ``NeuralNetworkModel.decode_superstep``): every state variant is a
    registered pytree whose children keep a fixed structure under
    ``with_lengths`` → append → ``advanced`` cycles, so a ``lax.scan``
    can thread the cache through N fused decode steps with the input
    donated — each iteration re-installs the carry's (B,) lengths via
    ``with_lengths`` (the in-scan analogue of the scheduler's
    host-authoritative per-dispatch install), appends at trace-static
    shapes, and the buffers alias in place across steps with zero host
    copies.  Holds for all four variants: this class (fp contiguous),
    :class:`QuantKVState` (int8 quantize-on-append), and the paged pair,
    whose appends walk a STATIC block-table partition
    (``with_static_table`` pins ``assigned_pages``, so the in-jit bump
    allocator is a no-op inside the scan and the carried counters stay
    constant).
    """

    quantized = False

    def __init__(self, k, v, length, ragged_lengths=None, ssm=None):
        self.k = list(k)
        self.v = list(v)
        self._length = length
        self.ragged_lengths = ragged_lengths
        # Optional fixed-size recurrent child (ops/ssm.py::SSMState) for
        # hybrid attention+SSM models; ``None`` (pure-attention) is a
        # zero-leaf pytree, so attention-only models see no new leaves,
        # donation aliasing is unchanged and the row ops below stay
        # no-ops for it.
        self.ssm = ssm

    @property
    def length(self):
        if self.ragged_lengths is not None:
            return self.ragged_lengths
        return self._length

    def tree_flatten(self):
        return (tuple(self.k), tuple(self.v), self._length,
                self.ragged_lengths, self.ssm), len(self.k)

    @classmethod
    def tree_unflatten(cls, aux, children):
        k, v, length, ragged, ssm = children
        return cls(list(k), list(v), length, ragged_lengths=ragged, ssm=ssm)

    @classmethod
    def create(cls, specs, batch: int, max_len: int, dtype=jnp.float32):
        """``specs``: per-attention-layer (num_kv_heads, head_dim)."""
        k = [jnp.zeros((batch, h, max_len, d), dtype) for h, d in specs]
        v = [jnp.zeros((batch, h, max_len, d), dtype) for h, d in specs]
        return cls(k, v, jnp.zeros((), jnp.int32))

    @property
    def max_len(self) -> int:
        return self.k[0].shape[2] if self.k else 0

    def append(self, layer_idx: int, k_new, v_new):
        """Write new K/V at the current length; returns full buffers.

        Does NOT advance ``length`` — the model runtime advances it once per
        step via ``advanced(T)`` after all layers have appended.

        With RAGGED (B,) lengths (``with_lengths``) each sequence's T new
        rows are written at its own positions ``length[b] + [0, T)`` —
        T = 1 is the batched decode hot loop; T > 1 is the multi-token
        speculative verify step (every row advances by the same candidate
        count; ragged *acceptance* is a post-step length rewind, see
        :meth:`rollback_row`).
        """
        ragged = jnp.ndim(self.length) >= 1
        if ragged:
            pos, b_idx = self._ragged_positions(k_new.shape)
            self.k[layer_idx] = self.k[layer_idx].at[b_idx, :, pos].set(
                k_new.transpose(0, 2, 1, 3).astype(self.k[layer_idx].dtype))
            self.v[layer_idx] = self.v[layer_idx].at[b_idx, :, pos].set(
                v_new.transpose(0, 2, 1, 3).astype(self.v[layer_idx].dtype))
        else:
            start = (0, 0, self.length, 0)
            self.k[layer_idx] = jax.lax.dynamic_update_slice(
                self.k[layer_idx], k_new.astype(self.k[layer_idx].dtype),
                start)
            self.v[layer_idx] = jax.lax.dynamic_update_slice(
                self.v[layer_idx], v_new.astype(self.v[layer_idx].dtype),
                start)
        new_length = self.length + k_new.shape[2]
        return self.k[layer_idx], self.v[layer_idx], new_length

    def _ragged_positions(self, new_shape):
        """(B, T) per-row write positions + (B, 1) batch indices for a
        ragged append of ``new_shape`` = (B, H, T, D) rows.  The advanced-
        index pair ``buf.at[b_idx, :, pos]`` addresses a (B, T, H, D) view,
        so callers scatter ``new.transpose(0, 2, 1, 3)``."""
        B, _, T, _ = new_shape
        b_idx = jnp.arange(B, dtype=jnp.int32)[:, None]
        pos = (jnp.asarray(self.length, jnp.int32)[:, None]
               + jnp.arange(T, dtype=jnp.int32)[None, :])
        return pos, b_idx

    def advanced(self, num_tokens: int):
        """State with length advanced by ``num_tokens`` (post-step)."""
        return self._with_length(self.length + num_tokens)

    def reset(self):
        out = self._with_length(jnp.zeros((), jnp.int32))
        if self.ssm is not None:
            out.ssm = self.ssm.reset()
        return out

    def with_lengths(self, lengths):
        """State with RAGGED per-sequence (B,) valid lengths — installed
        after a right-padded batched prefill (rows past a sequence's
        length hold garbage that the per-sequence masks never attend);
        subsequent appends write each row at its own position.  Supported
        by every cache variant (fp/int8 × contiguous/paged)."""
        return self._with_length(jnp.asarray(lengths, jnp.int32))

    def _with_length(self, length):
        if jnp.ndim(length) >= 1:
            return KVState(list(self.k), list(self.v),
                           jnp.full_like(self._length, -1),
                           ragged_lengths=jnp.asarray(length, jnp.int32),
                           ssm=self.ssm)
        return KVState(list(self.k), list(self.v), length, ssm=self.ssm)

    # -- per-row slot management (continuous-batching scheduler) ------------

    def _row_lengths(self):
        """(B,) per-row valid lengths, broadcasting the scalar if needed."""
        if self.ragged_lengths is not None:
            return self.ragged_lengths
        batch = self.k[0].shape[0] if self.k else 1
        return jnp.broadcast_to(jnp.asarray(self._length, jnp.int32),
                                (batch,))

    @staticmethod
    def _scalar_length(length):
        """Collapse a source state's length (scalar or (1,) ragged) to a
        scalar for the destination row."""
        arr = jnp.asarray(length, jnp.int32)
        return arr.reshape(-1)[0] if arr.ndim else arr

    def insert_row(self, row, src):
        """Copy a freshly prefilled batch-1 state ``src`` into row ``row``.

        The continuous-batching scheduler's admission path: a newcomer is
        prefilled into its own batch-1 cache (the exact single-sequence
        prefill program), then dropped into a free row of the persistent
        multi-row decode cache.  ``row`` may be a traced scalar, so one
        compiled program serves every slot.  The result carries RAGGED
        per-row lengths with row ``row`` set to ``src.length``.
        """
        if type(src) is not type(self):
            raise ValueError(f"insert_row source must be a {type(self).__name__}"
                             f" (got {type(src).__name__})")
        if src.max_len != self.max_len:
            raise ValueError(f"insert_row source max_len {src.max_len} != "
                             f"destination max_len {self.max_len}")
        row = jnp.asarray(row, jnp.int32)
        out = self._with_length(
            self._row_lengths().at[row].set(self._scalar_length(src.length)))
        out.k = [jax.lax.dynamic_update_slice(d, s.astype(d.dtype),
                                              (row, 0, 0, 0))
                 for d, s in zip(self.k, src.k)]
        out.v = [jax.lax.dynamic_update_slice(d, s.astype(d.dtype),
                                              (row, 0, 0, 0))
                 for d, s in zip(self.v, src.v)]
        if self.ssm is not None:
            out.ssm = self.ssm.insert_row(row, src.ssm)
        return out

    def reset_row(self, row):
        """Zero row ``row``'s valid length, recycling the slot for the next
        sequence (ragged states only).  The stale K/V rows stay in place as
        dead weight the per-row masks never attend; a recurrent child has
        no masking to hide behind, so its row is zeroed for real."""
        if self.ragged_lengths is None:
            raise ValueError("reset_row requires ragged per-row lengths "
                             "(call with_lengths first)")
        out = self._with_length(
            self.ragged_lengths.at[jnp.asarray(row, jnp.int32)].set(0))
        if self.ssm is not None:
            out.ssm = self.ssm.reset_row(jnp.asarray(row, jnp.int32))
        return out

    def rollback_row(self, row, new_length):
        """Rewind row ``row``'s valid length to ``new_length`` — the
        speculative-decoding rejection path: a verify step appends K+1
        candidate positions, then the rejected suffix is rolled back so
        the next append overwrites it.

        Purely a per-row length rewind (ragged states only): the rejected
        K/V stays in place as garbage the per-row masks never attend, and
        nothing is freed or zeroed.  On the paged variants this means a
        rewind across a page boundary simply moves the next write position
        back into an earlier (still-assigned) page of the row's table, and
        pages the table merely *aliases* — refcount-pinned prefix-cache
        pages — are never freed or written by the rollback itself.
        Callers must not rewind below a row's aliased-prefix length: the
        shared pages are read-only, and a subsequent append would write
        into them.
        """
        if self.ragged_lengths is None:
            raise ValueError("rollback_row requires ragged per-row lengths "
                             "(call with_lengths first)")
        out = self._with_length(
            self.ragged_lengths.at[jnp.asarray(row, jnp.int32)].set(
                jnp.asarray(new_length, jnp.int32)))
        if self.ssm is not None:
            # recurrent state cannot be length-masked — restore the exact
            # checkpointed state for the target length (ops/ssm.py ring)
            out.ssm = self.ssm.rollback_row(jnp.asarray(row, jnp.int32),
                                            new_length)
        return out

    def row_view(self, row, length):
        """Batch-1 view of row ``row`` with scalar valid ``length`` — the
        chunked-prefill substrate: the scheduler feeds prompt chunks through
        the model against this view (appending at ``length``), then writes
        the result back with :meth:`merge_row`.  ``row`` and ``length`` may
        be traced scalars, so one compiled chunk program serves every slot.
        """
        row = jnp.asarray(row, jnp.int32)
        slc = lambda a: jax.lax.dynamic_slice(
            a, (row,) + (0,) * (a.ndim - 1), (1,) + a.shape[1:])
        return KVState([slc(a) for a in self.k], [slc(a) for a in self.v],
                       jnp.asarray(length, jnp.int32),
                       ssm=(self.ssm.row_view(row)
                            if self.ssm is not None else None))

    def merge_row(self, row, view):
        """Multi-row state with row ``row``'s buffers replaced by ``view``'s
        (a :meth:`row_view` after chunk appends).  Lengths are untouched —
        the scheduler's host-side array stays authoritative, so a decode
        step never attends a row whose prefill is still in flight."""
        row = jnp.asarray(row, jnp.int32)
        upd = lambda d, s: jax.lax.dynamic_update_slice(
            d, s.astype(d.dtype), (row,) + (0,) * (d.ndim - 1))
        out = self._with_length(self.length)
        out.k = [upd(d, s) for d, s in zip(self.k, view.k)]
        out.v = [upd(d, s) for d, s in zip(self.v, view.v)]
        if self.ssm is not None:
            out.ssm = self.ssm.merge_row(row, view.ssm)
        return out

    def with_static_table(self):
        """No-op for contiguous layouts (rows already own fixed buffers);
        the paged variants override this with a fixed page partition."""
        return self

    # Observability: per-device bytes resident in HBM for this cache
    # (shard bytes under a serving mesh — see array_device_bytes).
    def memory_bytes(self) -> int:
        return sum(array_device_bytes(a) for a in (*self.k, *self.v))

    def logical_bytes(self) -> int:
        """Bytes an unquantized fp cache of the same shape would occupy."""
        return self.memory_bytes()

    def _ssm_bytes(self) -> int:
        return self.ssm.nbytes() if self.ssm is not None else 0

    def hbm_components(self) -> dict:
        """Byte attribution for the capacity ledger (serve/memledger.py):
        KV values vs quantization scales vs block-table/counter metadata
        vs recurrent state.  Components sum to everything this cache holds
        resident."""
        return {"kv_values": self.memory_bytes(),
                "kv_scales": 0,
                "kv_block_table": 0,
                "ssm_state": self._ssm_bytes()}


@jax.tree_util.register_pytree_node_class
class QuantKVState(KVState):
    """Int8 KV buffers with per-token scales (TurboQuant)."""

    quantized = True

    def __init__(self, k, v, length, k_scale, v_scale, out_dtype=jnp.float32,
                 ragged_lengths=None, ssm=None):
        super().__init__(k, v, length, ragged_lengths=ragged_lengths,
                         ssm=ssm)
        self.k_scale = list(k_scale)
        self.v_scale = list(v_scale)
        self.out_dtype = out_dtype

    def tree_flatten(self):
        children = (tuple(self.k), tuple(self.v), self._length,
                    tuple(self.k_scale), tuple(self.v_scale),
                    self.ragged_lengths, self.ssm)
        return children, (len(self.k), self.out_dtype)

    @classmethod
    def tree_unflatten(cls, aux, children):
        k, v, length, k_scale, v_scale, ragged, ssm = children
        return cls(list(k), list(v), length, list(k_scale), list(v_scale),
                   out_dtype=aux[1], ragged_lengths=ragged, ssm=ssm)

    @classmethod
    def create(cls, specs, batch: int, max_len: int, dtype=jnp.float32):
        k = [jnp.zeros((batch, h, max_len, d), jnp.int8) for h, d in specs]
        v = [jnp.zeros((batch, h, max_len, d), jnp.int8) for h, d in specs]
        ks = [jnp.zeros((batch, h, max_len, 1), jnp.float32) for h, _ in specs]
        vs = [jnp.zeros((batch, h, max_len, 1), jnp.float32) for h, _ in specs]
        return cls(k, v, jnp.zeros((), jnp.int32), ks, vs, out_dtype=dtype)

    def append_raw(self, layer_idx: int, k_new, v_new):
        """Quantize + store; return the RAW int8 buffers and new length.

        The attention consumer passes the per-token scales alongside
        (``cached_attention(k_scale=..., v_scale=...)``) so dequantization
        happens per VMEM tile inside the decode kernel — materializing a
        full-precision copy of the whole cache every decode step (what
        :meth:`append` does) costs 3× the HBM traffic int8 storage saves.
        """
        qk, sk = _quantize_int8(k_new)
        qv, sv = _quantize_int8(v_new)
        if jnp.ndim(self.length) >= 1:  # ragged: per-sequence positions
            # Quantize-and-store multi-token writes: T = 1 is the batched
            # decode hot loop, T > 1 the speculative verify step (and any
            # future ragged chunked prefill) — scales scatter to the same
            # (B, T) positions as the int8 values.
            pos, b_idx = self._ragged_positions(k_new.shape)
            for buf, new in ((self.k, qk), (self.v, qv),
                             (self.k_scale, sk), (self.v_scale, sv)):
                buf[layer_idx] = buf[layer_idx].at[b_idx, :, pos].set(
                    new.transpose(0, 2, 1, 3))
        else:
            start = (0, 0, self.length, 0)
            for buf, new in ((self.k, qk), (self.v, qv),
                             (self.k_scale, sk), (self.v_scale, sv)):
                buf[layer_idx] = jax.lax.dynamic_update_slice(
                    buf[layer_idx], new, start)
        return (self.k[layer_idx], self.v[layer_idx],
                self.length + k_new.shape[2])

    def append(self, layer_idx: int, k_new, v_new):
        """Store + return the dequantized full cache (correctness oracle
        for :meth:`append_raw`; the hot decode path uses the raw variant)."""
        qk_full, qv_full, new_length = self.append_raw(layer_idx, k_new,
                                                       v_new)
        k_full = _dequantize_int8(qk_full, self.k_scale[layer_idx], self.out_dtype)
        v_full = _dequantize_int8(qv_full, self.v_scale[layer_idx], self.out_dtype)
        return k_full, v_full, new_length

    def _with_length(self, length):
        if jnp.ndim(length) >= 1:
            return QuantKVState(list(self.k), list(self.v),
                                jnp.full_like(self._length, -1),
                                list(self.k_scale), list(self.v_scale),
                                out_dtype=self.out_dtype,
                                ragged_lengths=jnp.asarray(length, jnp.int32),
                                ssm=self.ssm)
        return QuantKVState(list(self.k), list(self.v), length,
                            list(self.k_scale), list(self.v_scale),
                            out_dtype=self.out_dtype, ssm=self.ssm)

    def insert_row(self, row, src):
        out = super().insert_row(row, src)
        row = jnp.asarray(row, jnp.int32)
        out.k_scale = [jax.lax.dynamic_update_slice(d, s, (row, 0, 0, 0))
                       for d, s in zip(self.k_scale, src.k_scale)]
        out.v_scale = [jax.lax.dynamic_update_slice(d, s, (row, 0, 0, 0))
                       for d, s in zip(self.v_scale, src.v_scale)]
        return out

    def row_view(self, row, length):
        row = jnp.asarray(row, jnp.int32)
        slc = lambda a: jax.lax.dynamic_slice(
            a, (row,) + (0,) * (a.ndim - 1), (1,) + a.shape[1:])
        return QuantKVState([slc(a) for a in self.k],
                            [slc(a) for a in self.v],
                            jnp.asarray(length, jnp.int32),
                            [slc(a) for a in self.k_scale],
                            [slc(a) for a in self.v_scale],
                            out_dtype=self.out_dtype,
                            ssm=(self.ssm.row_view(row)
                                 if self.ssm is not None else None))

    def merge_row(self, row, view):
        out = super().merge_row(row, view)
        row = jnp.asarray(row, jnp.int32)
        upd = lambda d, s: jax.lax.dynamic_update_slice(
            d, s, (row,) + (0,) * (d.ndim - 1))
        out.k_scale = [upd(d, s) for d, s in zip(self.k_scale, view.k_scale)]
        out.v_scale = [upd(d, s) for d, s in zip(self.v_scale, view.v_scale)]
        return out

    def logical_bytes(self) -> int:
        itemsize = jnp.dtype(self.out_dtype).itemsize
        return sum(int(a.size) * itemsize for a in (*self.k, *self.v))

    def hbm_components(self) -> dict:
        return {"kv_values": self.memory_bytes(),
                "kv_scales": sum(array_device_bytes(a)
                                 for a in (*self.k_scale, *self.v_scale)),
                "kv_block_table": 0,
                "ssm_state": self._ssm_bytes()}


def build_descriptors(spans, block_q: int, num_blocks: int):
    """Host-side descriptor builder for the ragged unified dispatch.

    ``spans``: an ordered list of ``(row, q_start, q_len)`` work items —
    a decode step is ``q_len = 1``, a prefill chunk ``q_len = chunk``, a
    spec-verify span ``q_len = K+1``.  Each span is cut into
    ``ceil(q_len / block_q)`` consecutive ``block_q``-token descriptor
    blocks ``(row, q_pos0, q_valid, kv_len)`` with ``kv_len = q_start +
    q_len`` (the row's valid length after the append), padded with
    ``(-1, 0, 0, 0)`` rows up to ``num_blocks`` (the shape bucket — see
    utils/bucketing.py::bucket_count).  Returns ``(descs, offsets)``:
    the ``(num_blocks, 4)`` int32 numpy array plus each span's first
    block index, so callers can locate span token ``i`` at packed slot
    ``(offsets[s] + i // block_q) * block_q + i % block_q``.
    """
    descs = np.zeros((num_blocks, 4), np.int32)
    descs[:, 0] = -1
    offsets = []
    nb = 0
    for row, q_start, q_len in spans:
        offsets.append(nb)
        done = 0
        while done < q_len:
            take = min(block_q, q_len - done)
            if nb >= num_blocks:
                raise ValueError(
                    f"spans need more than num_blocks={num_blocks} "
                    f"descriptor blocks of block_q={block_q}")
            descs[nb] = (row, q_start + done, take, q_start + q_len)
            nb += 1
            done += take
    return descs, offsets


def packed_slots(offset: int, q_len: int, block_q: int) -> np.ndarray:
    """Packed-array slot index of each of a span's ``q_len`` tokens,
    given the span's first descriptor block ``offset``
    (:func:`build_descriptors` returns those offsets)."""
    i = np.arange(int(q_len))
    return (int(offset) + i // int(block_q)) * int(block_q) + i % int(block_q)


@jax.tree_util.register_pytree_node_class
class PagedKVState(KVState):
    """Paged KV cache: fixed-size pages in a shared HBM pool + block table.

    The contiguous per-sequence buffers of :class:`KVState` become per-layer
    *page pools* — flat ``(Hkv, num_pages * page_size, D)`` arrays whose row
    axis is grouped into pages of ``page_size`` tokens (head-major so one
    page of one head is a well-tiled ``(page_size, D)`` VMEM block for the
    paged Pallas kernel) — plus one block table
    ``(B, pages_per_seq)`` mapping each sequence's logical page to a physical
    page.  Pages are assigned on demand by an in-jit bump allocator
    (vLLM-style paged attention; BASELINE.json config "gpt2-medium /generate/
    with paged KV on TPU HBM").

    The pool itself is preallocated (XLA needs static shapes), so single-
    sequence decode holds the same HBM as the contiguous cache; the paged
    layout is the substrate for pool sharing across sequences, which needs a
    freeing allocator (the current bump allocator only frees on ``reset``, so
    ``create`` rejects undersized pools rather than aliasing live pages).
    ``assigned_bytes()`` tracks actual per-sequence growth.
    The attention-facing ``append`` currently materializes dense gathered
    views (a paged Pallas decode kernel that walks the block table directly
    is the planned replacement for that copy).

    The surface is identical to :class:`KVState` (``append`` returns gathered
    full ``(B, Hkv, S_max, D)`` views; ``advanced``/``reset`` thread state), so
    it is a drop-in for the jitted decode path.  ``-1`` block-table entries
    mark unassigned pages; their gathered rows are garbage but always sit at
    positions ≥ the valid length, which the attention mask ignores
    (ops/attention.py:91-108).
    """

    quantized = False

    # ``counters`` packs (length, next_free, assigned_pages) into one int32
    # array: a single buffer cannot alias itself when the state is donated.
    # RAGGED batches carry a separate ``ragged_lengths`` (B,) child (the
    # packed scalar slot cannot hold a vector); when present it supersedes
    # ``counters[0]``.

    def __init__(self, k, v, counters, block_table,
                 page_size: int, pages_per_seq: int, ragged_lengths=None,
                 ssm=None):
        self.k = list(k)
        self.v = list(v)
        self.counters = counters
        self.block_table = block_table
        self.page_size = int(page_size)
        self.pages_per_seq = int(pages_per_seq)
        self.ragged_lengths = ragged_lengths
        self.ssm = ssm  # optional recurrent child (see KVState.__init__)

    @property
    def length(self):
        if self.ragged_lengths is not None:
            return self.ragged_lengths
        return self.counters[0]

    @property
    def next_free(self):
        return self.counters[1]

    @property
    def assigned_pages(self):
        """Per-sequence logical pages handed out so far this step."""
        return self.counters[2]

    def tree_flatten(self):
        children = (tuple(self.k), tuple(self.v), self.counters,
                    self.block_table, self.ragged_lengths, self.ssm)
        return children, (self.page_size, self.pages_per_seq)

    @classmethod
    def tree_unflatten(cls, aux, children):
        k, v, counters, block_table, ragged, ssm = children
        return cls(list(k), list(v), counters, block_table,
                   page_size=aux[0], pages_per_seq=aux[1],
                   ragged_lengths=ragged, ssm=ssm)

    @classmethod
    def create(cls, specs, batch: int, max_len: int, dtype=jnp.float32,
               page_size: int | None = None, pool_pages: int | None = None):
        page = page_size or default_page_size()
        pages_per_seq = -(-max_len // page)
        num_pages = pool_pages or batch * pages_per_seq
        if num_pages < batch * pages_per_seq:
            raise ValueError(
                f"pool_pages={num_pages} cannot back {batch} sequence(s) of "
                f"{pages_per_seq} pages: the bump allocator frees only on "
                "reset, so an undersized pool would alias live pages")
        k = [jnp.zeros((h, num_pages * page, d), dtype) for h, d in specs]
        v = [jnp.zeros((h, num_pages * page, d), dtype) for h, d in specs]
        table = jnp.full((batch, pages_per_seq), -1, jnp.int32)
        return cls(k, v, jnp.zeros((3,), jnp.int32), table,
                   page, pages_per_seq)

    @property
    def max_len(self) -> int:
        return self.pages_per_seq * self.page_size

    @property
    def num_pool_pages(self) -> int:
        if self.k:
            return self.k[0].shape[1] // self.page_size
        # Pure-SSM shell: no attention layers, so no pools — the logical
        # zero-byte static partition (one "page" slot per table entry)
        # keeps with_static_table and the memledger partition audit sound.
        return int(self.block_table.size)

    def _allocate(self, new_length):
        """Bump-allocate physical pages covering ``[0, new_length)``.

        Idempotent within a step: every layer's ``append`` calls this with
        the same ``new_length``; ``assigned_pages`` (not ``length``, which
        only advances post-step) tracks what the first call handed out, so
        subsequent calls see ``delta == 0``.

        RAGGED (B,) lengths allocate uniformly to the longest sequence —
        a shorter sequence's write position is always below the longest's,
        so its page is covered; the over-assignment is bounded by one page
        per sequence ahead of need.
        """
        P, S = self.page_size, self.pages_per_seq
        B = self.block_table.shape[0]
        if jnp.ndim(new_length) >= 1:
            new_length = jnp.max(new_length)
        assigned = self.assigned_pages
        needed = jnp.minimum((new_length + P - 1) // P, S)
        # Monotone: a recycled row shrinking max(lengths) below the pages
        # already handed out (continuous-batching slot reuse), or a
        # statically partitioned table (with_static_table), must not walk
        # the counters backwards — that would re-assign live pages.
        needed = jnp.maximum(needed, assigned)
        delta = needed - assigned
        slots = jnp.arange(S, dtype=jnp.int32)
        fresh = (slots >= assigned) & (slots < needed)
        b_idx = jnp.arange(B, dtype=jnp.int32)[:, None]
        entries = self.next_free + b_idx * delta + (slots[None, :] - assigned)
        self.block_table = jnp.where(fresh[None, :], entries.astype(jnp.int32),
                                     self.block_table)
        self.counters = jnp.stack([self.counters[0],
                                   self.next_free + B * delta, needed])

    def _rows(self, pos):
        """Physical row indices for logical positions ``pos`` (n,) → (B, n)."""
        P = self.page_size
        phys_page = self.block_table[:, pos // P]  # (B, n)
        return phys_page * P + pos % P

    def _allocate_rows(self, T: int):
        """Bump-allocate pages for ``T`` new tokens; returns the flat pool
        row index per (batch, token) plus the new valid length.

        RAGGED (B,) lengths (``with_lengths``): each sequence's T rows
        land at its own positions ``length[b] + [0, T)``, walking the
        block table per position so a write may span a page boundary —
        T = 1 is the batched decode hot loop, T > 1 the multi-token
        speculative verify step (same contract as the contiguous ragged
        append)."""
        new_length = self.length + T
        self._allocate(new_length)
        if jnp.ndim(self.length) >= 1:
            P = self.page_size
            pos = (jnp.asarray(self.length, jnp.int32)[:, None]
                   + jnp.arange(T, dtype=jnp.int32)[None, :])   # (B, T)
            page = jnp.clip(pos // P, 0, self.pages_per_seq - 1)
            phys = jnp.take_along_axis(self.block_table, page, axis=1)
            rows = phys * P + pos % P
            return rows.reshape(-1), new_length     # rows: (B*T,), b-major
        pos = self.length + jnp.arange(T, dtype=jnp.int32)
        return self._rows(pos).reshape(-1), new_length  # rows: (B*T,)

    @staticmethod
    def _to_rows(t):
        """(B, H, T, d) → head-major flat rows (H, B*T, d)."""
        B, H, T, d = t.shape
        return t.transpose(1, 0, 2, 3).reshape(H, B * T, d)

    def _note_overflow(self, T: int):
        """Host-visible half of the silent-truncation contract: when this
        append runs EAGERLY (oracle/test paths) and the write provably
        lands past ``max_len``, count the dropped tokens.  Inside jit the
        lengths are tracers and the clamp stays silent — the scheduler
        covers that case from its host-side bookkeeping."""
        lengths = self.length
        if isinstance(lengths, jax.core.Tracer):
            return
        try:
            over = int(np.max(np.asarray(lengths))) + int(T) - self.max_len
        except Exception:  # noqa: BLE001 — accounting must never break appends
            return
        if over > 0:
            record_pool_drop(over, context=f"paged pool max_len={self.max_len}")

    def append_rows(self, layer_idx: int, k_new, v_new):
        """Scatter new K/V into the page pools; returns the *flat* pools
        (no dense gather — the paged Pallas kernel walks the block table
        directly, ops/pallas/paged_attention.py).

        Precondition: ``length + T <= max_len``.  ``_allocate`` clamps the
        page count and ``_rows`` clamps the logical-page lookup, so an
        overflowing append silently overwrites the final page's rows
        instead of raising — callers must reset/re-prefill at capacity the
        way the generate loop does (models/model.py overflow path).  Eager
        overflows are counted via :func:`record_pool_drop`.
        """
        self._note_overflow(k_new.shape[2])
        rows, new_length = self._allocate_rows(k_new.shape[2])
        self.k[layer_idx] = self.k[layer_idx].at[:, rows].set(
            self._to_rows(k_new).astype(self.k[layer_idx].dtype))
        self.v[layer_idx] = self.v[layer_idx].at[:, rows].set(
            self._to_rows(v_new).astype(self.v[layer_idx].dtype))
        return self.k[layer_idx], self.v[layer_idx], new_length

    def append(self, layer_idx: int, k_new, v_new):
        """Scatter + dense gathered views (the jnp fallback/oracle path)."""
        _, _, new_length = self.append_rows(layer_idx, k_new, v_new)
        return (self._gather(self.k[layer_idx]),
                self._gather(self.v[layer_idx]), new_length)

    # -- ragged packed-batch path (unified mixed dispatch) ------------------

    def packed_rows(self, descs, block_q: int):
        """Flat pool row per PACKED token for a ``(NB, 4)`` descriptor
        array (``build_descriptors``) — the scatter targets of
        :meth:`append_packed`.  Padding slots (row = -1 or t ≥ q_valid)
        map past the pool so the scatter drops them.  Requires the row
        tables to be fully assigned (the scheduler's static partition /
        prefix aliases) — the bump allocator is never consulted, which is
        what lets prefill chunks, decode steps and verify spans share one
        scatter."""
        P = self.page_size
        descs = jnp.asarray(descs, jnp.int32)
        t = jnp.arange(int(block_q), dtype=jnp.int32)[None, :]
        row = descs[:, 0:1]
        pos = descs[:, 1:2] + t                            # (NB, BQ)
        valid = (t < descs[:, 2:3]) & (row >= 0) & (pos < self.max_len)
        page = jnp.clip(pos // P, 0, self.pages_per_seq - 1)
        phys = self.block_table[jnp.clip(row, 0), page]    # (NB, BQ)
        rows = phys * P + pos % P
        oob = self.k[0].shape[1] if self.k else 0
        return jnp.where(valid & (phys >= 0), rows, oob).reshape(-1)

    def append_packed(self, layer_idx: int, k_new, v_new, rows):
        """Scatter a PACKED mixed batch into the pools.

        ``k_new``/``v_new``: (1, Hkv, Tp, D) packed new tokens;
        ``rows``: (Tp,) flat pool rows from :meth:`packed_rows` (shared
        across layers — compute once per step).  Out-of-pool rows (the
        padding slots) are dropped by the scatter.  Lengths are NOT
        advanced here — descriptors carry the post-append lengths and
        :meth:`lengths_after_packed` reconciles the state."""
        self.k[layer_idx] = self.k[layer_idx].at[:, rows].set(
            self._to_rows(k_new).astype(self.k[layer_idx].dtype),
            mode="drop")
        self.v[layer_idx] = self.v[layer_idx].at[:, rows].set(
            self._to_rows(v_new).astype(self.v[layer_idx].dtype),
            mode="drop")
        return self.k[layer_idx], self.v[layer_idx]

    def lengths_after_packed(self, descs):
        """Per-row (B,) valid lengths after a packed append: each live
        descriptor raises its row to its ``kv_len``; untouched rows keep
        their current length."""
        descs = jnp.asarray(descs, jnp.int32)
        lens = self._row_lengths()
        row = jnp.where(descs[:, 0] >= 0, descs[:, 0], lens.shape[0])
        return lens.at[row].max(descs[:, 3], mode="drop")

    def _gather(self, flat):
        """Assemble the (B, Hkv, S_max, D) view the attention mask expects."""
        all_pos = jnp.arange(self.max_len, dtype=jnp.int32)
        rows = jnp.clip(self._rows(all_pos), 0)  # unassigned → row 0 (masked)
        # flat: (Hkv, pool_rows, D); rows: (B, S_max)
        return jnp.take(flat, rows, axis=1,
                        mode="clip").transpose(1, 0, 2, 3)

    def _with_length(self, length):
        if jnp.ndim(length) >= 1:
            # counters[0] would go stale behind ragged_lengths; poison it
            # so any future direct read fails loudly instead of returning
            # the prefill-time scalar.
            counters = self.counters.at[0].set(-1)
            return PagedKVState(list(self.k), list(self.v), counters,
                                self.block_table, self.page_size,
                                self.pages_per_seq,
                                ragged_lengths=jnp.asarray(length,
                                                           jnp.int32),
                                ssm=self.ssm)
        counters = self.counters.at[0].set(length)
        return PagedKVState(list(self.k), list(self.v), counters,
                            self.block_table,
                            self.page_size, self.pages_per_seq,
                            ssm=self.ssm)

    def reset(self):
        table = jnp.full_like(self.block_table, -1)
        return PagedKVState(list(self.k), list(self.v),
                            jnp.zeros((3,), jnp.int32), table,
                            self.page_size, self.pages_per_seq,
                            ssm=(self.ssm.reset()
                                 if self.ssm is not None else None))

    # -- per-row slot management (continuous-batching scheduler) ------------

    def _row_lengths(self):
        if self.ragged_lengths is not None:
            return self.ragged_lengths
        batch = self.block_table.shape[0]
        return jnp.broadcast_to(jnp.asarray(self.counters[0], jnp.int32),
                                (batch,))

    def with_static_table(self):
        """Partition the pool statically: row ``i`` owns physical pages
        ``[i*S, (i+1)*S)``.  The bump allocator never frees, so per-row
        recycling cannot go through it; with the full table pre-assigned
        (``assigned_pages = S``) and the monotone ``_allocate`` clamp,
        appends become pure scatters into each row's own page range and a
        recycled row simply overwrites its own stale pages.  Requires the
        pool to back every row (the ``create`` default)."""
        B, S = self.block_table.shape
        if self.num_pool_pages < B * S:
            raise ValueError(
                f"static page table needs pool_pages >= batch*pages_per_seq "
                f"({B}*{S}); pool has {self.num_pool_pages}")
        out = self._with_length(self.length)
        out.block_table = (jnp.arange(B, dtype=jnp.int32)[:, None] * S
                           + jnp.arange(S, dtype=jnp.int32)[None, :])
        out.counters = out.counters.at[1].set(B * S).at[2].set(S)
        return out

    def insert_row(self, row, src):
        """Copy a prefilled batch-1 paged state into row ``row``.

        A batch-1 pool's bump allocator assigns physical pages in logical
        order (page j ↦ pool page j), so the source pool rows are already
        position-ordered: the copy is one dynamic-slice write into the
        destination row's own page range.  Installs the static per-row
        table (see :meth:`with_static_table`) as a side effect — per-row
        admission and the dynamic bump allocator cannot coexist.
        """
        if type(src) is not type(self):
            raise ValueError(f"insert_row source must be a {type(self).__name__}"
                             f" (got {type(src).__name__})")
        if (src.page_size != self.page_size
                or src.pages_per_seq != self.pages_per_seq):
            raise ValueError(
                f"insert_row source page layout ({src.page_size}, "
                f"{src.pages_per_seq}) != destination ({self.page_size}, "
                f"{self.pages_per_seq})")
        base = self.with_static_table()
        S, P = self.pages_per_seq, self.page_size
        span = S * P
        row = jnp.asarray(row, jnp.int32)
        out = base._with_length(
            base._row_lengths().at[row].set(self._scalar_length(src.length)))
        start = row * span
        out.k = [jax.lax.dynamic_update_slice(
                     d, s[:, :span].astype(d.dtype), (0, start, 0))
                 for d, s in zip(base.k, src.k)]
        out.v = [jax.lax.dynamic_update_slice(
                     d, s[:, :span].astype(d.dtype), (0, start, 0))
                 for d, s in zip(base.v, src.v)]
        if self.ssm is not None:
            out.ssm = self.ssm.insert_row(row, src.ssm)
        return out

    def row_view(self, row, length):
        """Batch-1 view of row ``row`` sharing this state's flat pools —
        appends through the view scatter straight into the parent pool's
        pages via the sliced block-table row, so :meth:`merge_row` is just
        a pool swap (no data copy).  This is what makes chunked prefill
        write a row's suffix in place while its leading table entries may
        alias prefix-cache pages owned by other sequences.

        Precondition: the row's table is fully assigned (the scheduler's
        static partition / :meth:`with_static_table`); the view parks the
        bump allocator (``assigned_pages = pages_per_seq``) so appends are
        pure scatters and never walk the shared counters."""
        row = jnp.asarray(row, jnp.int32)
        table = jax.lax.dynamic_slice(self.block_table, (row, 0),
                                      (1, self.pages_per_seq))
        counters = jnp.stack([jnp.asarray(length, jnp.int32),
                              self.counters[1],
                              jnp.asarray(self.pages_per_seq, jnp.int32)])
        return PagedKVState(list(self.k), list(self.v), counters, table,
                            self.page_size, self.pages_per_seq,
                            ssm=(self.ssm.row_view(row)
                                 if self.ssm is not None else None))

    def merge_row(self, row, view):
        """Adopt the view's (already scattered-into) pools; table, counters
        and per-row lengths are untouched — the scheduler's host array
        stays authoritative.  A recurrent child has no shared pool, so its
        batch-1 state is written back into the row explicitly."""
        out = self._with_length(self.length)
        out.k = list(view.k)
        out.v = list(view.v)
        if self.ssm is not None:
            out.ssm = self.ssm.merge_row(jnp.asarray(row, jnp.int32),
                                         view.ssm)
        return out

    def with_row_prefix(self, row, prefix_pages):
        """Row ``row``'s block-table entries rebuilt as ``prefix_pages``
        aliased over the leading logical pages, the row's own
        static-partition pages for the rest (radix prefix-KV sharing).
        Suffix appends land at positions ≥ ``len(prefix_pages) *
        page_size``, so the shared pages are only ever read.  Eager
        admission-path op; ``row`` is a host int.  Requires the static
        partition (:meth:`with_static_table`)."""
        S = self.pages_per_seq
        n = len(prefix_pages)
        if n > S:
            raise ValueError(f"prefix of {n} pages exceeds pages_per_seq={S}")
        entries = np.arange(int(row) * S, int(row) * S + S, dtype=np.int32)
        entries[:n] = np.asarray(list(prefix_pages), np.int32)
        out = self._with_length(self.length)
        out.block_table = self.block_table.at[int(row)].set(
            jnp.asarray(entries))
        return out

    def restore_row_table(self, row):
        """Drop row ``row``'s prefix aliases, restoring its own static
        partition (retirement path — the next occupant must not write
        through stale shared entries)."""
        return self.with_row_prefix(row, ())

    def copy_pages(self, src_pages, dst_pages):
        """Copy whole physical pages ``src_pages[i] → dst_pages[i]`` in
        every layer's K and V pool — prefix-cache registration: a finished
        prompt's row-private pages are copied into the reserved cache
        region so slot recycling cannot clobber them.  Eager op."""
        if len(src_pages) != len(dst_pages):
            raise ValueError("copy_pages needs equal-length page lists")
        if not len(src_pages):
            return self
        rows = lambda pages: (
            np.asarray(list(pages), np.int64)[:, None] * self.page_size
            + np.arange(self.page_size)).reshape(-1)
        src_rows, dst_rows = rows(src_pages), rows(dst_pages)
        out = self._with_length(self.length)
        out.k = [a.at[:, dst_rows].set(a[:, src_rows]) for a in self.k]
        out.v = [a.at[:, dst_rows].set(a[:, src_rows]) for a in self.v]
        return out

    def _export_pool_rows(self, row: int, n_pages: int):
        """Flat pool-row indices of row ``row``'s first ``n_pages`` logical
        pages, resolved through its block table (host op)."""
        phys = np.asarray(self.block_table)[int(row), :n_pages].astype(
            np.int64)
        return (phys[:, None] * self.page_size
                + np.arange(self.page_size)).reshape(-1)

    def export_row_pages(self, row, length, device: bool = False) -> dict:
        """Gather row ``row``'s first ``ceil(length/page_size)`` logical
        pages through its block table — the disaggregated prefill export.
        The gather follows the table, so prefix-aliased leading pages come
        out position-ordered exactly like row-private ones.  With
        ``device=False`` (the host-staged / crash-safe transport) the
        planes come back as host arrays ready for the CRC blob codec;
        ``device=True`` (d2d transport) keeps them as device arrays so the
        hand-off never round-trips through host memory.  Eager op;
        ``row``/``length`` are host ints."""
        P = self.page_size
        n = -(-int(length) // P)
        if n > self.pages_per_seq:
            raise ValueError(f"export of {n} pages exceeds "
                             f"pages_per_seq={self.pages_per_seq}")
        pool_rows = self._export_pool_rows(row, n)
        gather = ((lambda a: a[:, pool_rows]) if device
                  else (lambda a: np.asarray(a[:, pool_rows])))
        blob = {"page_size": P, "pages": n, "length": int(length),
                "quantized": bool(getattr(self, "quantized", False)),
                "k": [gather(a) for a in self.k],
                "v": [gather(a) for a in self.v]}
        if self.ssm is not None:
            # constant-size recurrent state rides the same blob — for a
            # pure-SSM row this is the ENTIRE hand-off payload
            blob["ssm"] = self.ssm.export_row(int(row), device=device)
        return blob

    @staticmethod
    def _import_operand(s, a):
        """Hand-off update operand for one pool leaf: host-blob planes
        convert on device as before; device planes (d2d transport)
        re-shard onto the destination pool's own layout first so the
        scatter stays one XLA program with co-sharded operands."""
        if isinstance(s, jax.Array):
            from penroz_tpu.parallel import sharding as sharding_mod
            if s.dtype != a.dtype:
                s = s.astype(a.dtype)
            return sharding_mod.place_update(s, a)
        return jnp.asarray(s, a.dtype)

    def import_row_pages(self, row, blob: dict):
        """Scatter an :meth:`export_row_pages` blob into row ``row``'s own
        static-partition pages (table entries restored to static first, so
        a stale prefix alias can never be written through).  The inverse
        hand-off op on the decode replica; eager, ``row`` is a host int."""
        P, S = self.page_size, self.pages_per_seq
        if int(blob["page_size"]) != P:
            raise ValueError(f"page blob page_size {blob['page_size']} != "
                             f"pool page_size {P}")
        if bool(blob["quantized"]) != bool(getattr(self, "quantized", False)):
            raise ValueError("page blob quantization does not match pool")
        n = int(blob["pages"])
        if n > S:
            raise ValueError(f"import of {n} pages exceeds pages_per_seq={S}")
        # dynamic start: the scatter's compiled program is keyed on the
        # update SHAPE only, so every destination row shares one program
        # instead of paying an XLA compile per (row, pages) combination
        start = jnp.int32(int(row) * S * P)
        zero = jnp.int32(0)
        out = self.with_row_prefix(row, ())
        out.k = [jax.lax.dynamic_update_slice(
                     a, self._import_operand(s, a), (zero, start, zero))
                 for a, s in zip(out.k, blob["k"])]
        out.v = [jax.lax.dynamic_update_slice(
                     a, self._import_operand(s, a), (zero, start, zero))
                 for a, s in zip(out.v, blob["v"])]
        if self.ssm is not None and blob.get("ssm") is not None:
            out.ssm = self.ssm.import_row(int(row), blob["ssm"])
        return out

    def _page_pool_rows(self, pages):
        """Flat pool-row indices of an explicit physical page list (host
        op) — the page-granular sibling of :meth:`_export_pool_rows`, for
        pages that have no row block table (radix-cache pages)."""
        return (np.asarray(list(pages), np.int64)[:, None] * self.page_size
                + np.arange(self.page_size)).reshape(-1)

    def export_pages(self, pages, length, device: bool = False) -> dict:
        """Gather an explicit physical page list into an
        :meth:`export_row_pages`-shaped blob — the hibernation export:
        radix-cache pages are pool-resident but belong to no row, so there
        is no block table to resolve through.  ``pages`` must be
        position-ordered (root → leaf) for the blob to replay as a prefix.
        Eager op; ``length`` is the token count the pages cover."""
        pool_rows = self._page_pool_rows(pages)
        gather = ((lambda a: a[:, pool_rows]) if device
                  else (lambda a: np.asarray(a[:, pool_rows])))
        return {"page_size": self.page_size, "pages": len(pages),
                "length": int(length),
                "quantized": bool(getattr(self, "quantized", False)),
                "k": [gather(a) for a in self.k],
                "v": [gather(a) for a in self.v]}

    def import_pages(self, pages, blob: dict, blob_offset: int = 0):
        """Scatter an :meth:`export_pages`/:meth:`export_row_pages` blob
        into an explicit physical page list — the promotion import: the
        destination pages are freshly ``insert()``-created radix slots, so
        unlike :meth:`import_row_pages` there is no row whose table needs
        restoring.  ``blob_offset`` skips leading blob pages (a partially
        radix-resident session only promotes the tail blocks ``insert``
        newly created); a blob longer than ``blob_offset + len(pages)``
        is fine — the surplus just stays hibernated.  Eager op."""
        P = self.page_size
        if int(blob["page_size"]) != P:
            raise ValueError(f"page blob page_size {blob['page_size']} != "
                             f"pool page_size {P}")
        if bool(blob["quantized"]) != bool(getattr(self, "quantized", False)):
            raise ValueError("page blob quantization does not match pool")
        n = len(pages)
        off = int(blob_offset)
        if off + n > int(blob["pages"]):
            raise ValueError(f"import of pages [{off}, {off + n}) exceeds "
                             f"blob pages={blob['pages']}")
        lo, hi = off * P, (off + n) * P
        pool_rows = self._page_pool_rows(pages)
        out = self._with_length(self.length)
        out.k = [a.at[:, pool_rows].set(self._import_operand(s[:, lo:hi], a))
                 for a, s in zip(self.k, blob["k"])]
        out.v = [a.at[:, pool_rows].set(self._import_operand(s[:, lo:hi], a))
                 for a, s in zip(self.v, blob["v"])]
        return out

    def _row_bytes(self) -> int:
        """Bytes per token row summed over every layer's K and V pool."""
        return sum(a.shape[0] * a.shape[2] * a.dtype.itemsize
                   for a in (*self.k, *self.v))

    # ``memory_bytes`` is inherited: the preallocated pool is what actually
    # sits in HBM, so the reported compression ratio is an honest 1.0.

    def assigned_bytes(self) -> int:
        """Bytes of *assigned* pages (what live sequences actually hold).

        ``next_free`` counts pages per pool; every layer's pool assigns the
        same pages, so live bytes = pages × page_size × summed row bytes."""
        import numpy as np
        live_pages = min(int(np.asarray(self.next_free)), self.num_pool_pages)
        return live_pages * self.page_size * self._row_bytes()

    def logical_bytes(self) -> int:
        """Bytes a contiguous per-sequence cache of max_len would occupy."""
        B = self.block_table.shape[0]
        return B * self.max_len * self._row_bytes()

    def _table_bytes(self) -> int:
        return (int(self.block_table.size) * self.block_table.dtype.itemsize
                + int(self.counters.size) * self.counters.dtype.itemsize)

    def hbm_components(self) -> dict:
        return {"kv_values": self.memory_bytes(),
                "kv_scales": 0,
                "kv_block_table": self._table_bytes(),
                "ssm_state": self._ssm_bytes()}


@jax.tree_util.register_pytree_node_class
class QuantPagedKVState(PagedKVState):
    """Int8 paged pool: TurboQuant storage + paged layout combined.

    The page pools hold int8 values; parallel ``(Hkv, rows, 1)`` fp32 pools
    hold the per-token scales (TurboQuant layout, kv_cache.py:101-195 in the
    reference).  The paged Pallas kernel dequantizes one page at a time in
    VMEM (ops/pallas/paged_attention.py), so HBM holds ~¼ the bytes of the
    fp32 paged pool while context stays HBM-bounded.
    """

    quantized = True

    def __init__(self, k, v, counters, block_table, page_size: int,
                 pages_per_seq: int, k_scale, v_scale,
                 out_dtype=jnp.float32, ragged_lengths=None, ssm=None):
        super().__init__(k, v, counters, block_table, page_size,
                         pages_per_seq, ragged_lengths=ragged_lengths,
                         ssm=ssm)
        self.k_scale = list(k_scale)
        self.v_scale = list(v_scale)
        self.out_dtype = out_dtype

    def tree_flatten(self):
        children = (tuple(self.k), tuple(self.v), self.counters,
                    self.block_table, tuple(self.k_scale),
                    tuple(self.v_scale), self.ragged_lengths, self.ssm)
        return children, (self.page_size, self.pages_per_seq, self.out_dtype)

    @classmethod
    def tree_unflatten(cls, aux, children):
        k, v, counters, block_table, k_scale, v_scale, ragged, ssm = children
        return cls(list(k), list(v), counters, block_table,
                   page_size=aux[0], pages_per_seq=aux[1],
                   k_scale=list(k_scale), v_scale=list(v_scale),
                   out_dtype=aux[2], ragged_lengths=ragged, ssm=ssm)

    @classmethod
    def create(cls, specs, batch: int, max_len: int, dtype=jnp.float32,
               page_size: int | None = None, pool_pages: int | None = None):
        base = PagedKVState.create(specs, batch, max_len, jnp.int8,
                                   page_size=page_size,
                                   pool_pages=pool_pages)
        rows = base.k[0].shape[1] if base.k else 0
        ks = [jnp.zeros((h, rows, 1), jnp.float32) for h, _ in specs]
        vs = [jnp.zeros((h, rows, 1), jnp.float32) for h, _ in specs]
        return cls(base.k, base.v, base.counters, base.block_table,
                   base.page_size, base.pages_per_seq, ks, vs,
                   out_dtype=dtype)

    def append_rows(self, layer_idx: int, k_new, v_new):
        """Quantize then scatter values *and* scales into the pools (same
        allocator/scatter path and overflow precondition as the parent)."""
        self._note_overflow(k_new.shape[2])
        qk, sk = _quantize_int8(k_new)
        qv, sv = _quantize_int8(v_new)
        rows, new_length = self._allocate_rows(k_new.shape[2])
        self.k[layer_idx] = self.k[layer_idx].at[:, rows].set(
            self._to_rows(qk))
        self.v[layer_idx] = self.v[layer_idx].at[:, rows].set(
            self._to_rows(qv))
        self.k_scale[layer_idx] = self.k_scale[layer_idx].at[:, rows].set(
            self._to_rows(sk))
        self.v_scale[layer_idx] = self.v_scale[layer_idx].at[:, rows].set(
            self._to_rows(sv))
        return self.k[layer_idx], self.v[layer_idx], new_length

    def append_packed(self, layer_idx: int, k_new, v_new, rows):
        """Quantize then scatter a packed mixed batch — values and
        per-token scales land at the same pool rows (padding dropped)."""
        qk, sk = _quantize_int8(k_new)
        qv, sv = _quantize_int8(v_new)
        self.k[layer_idx] = self.k[layer_idx].at[:, rows].set(
            self._to_rows(qk), mode="drop")
        self.v[layer_idx] = self.v[layer_idx].at[:, rows].set(
            self._to_rows(qv), mode="drop")
        self.k_scale[layer_idx] = self.k_scale[layer_idx].at[:, rows].set(
            self._to_rows(sk), mode="drop")
        self.v_scale[layer_idx] = self.v_scale[layer_idx].at[:, rows].set(
            self._to_rows(sv), mode="drop")
        return self.k[layer_idx], self.v[layer_idx]

    def append(self, layer_idx: int, k_new, v_new):
        """Scatter + dense dequantized views (jnp fallback/oracle path)."""
        _, _, new_length = self.append_rows(layer_idx, k_new, v_new)
        k_full = _dequantize_int8(self._gather(self.k[layer_idx]),
                                  self._gather(self.k_scale[layer_idx]),
                                  self.out_dtype)
        v_full = _dequantize_int8(self._gather(self.v[layer_idx]),
                                  self._gather(self.v_scale[layer_idx]),
                                  self.out_dtype)
        return k_full, v_full, new_length

    def _with_length(self, length):
        if jnp.ndim(length) >= 1:
            counters = self.counters.at[0].set(-1)  # poisoned; see base class
            return QuantPagedKVState(
                list(self.k), list(self.v), counters, self.block_table,
                self.page_size, self.pages_per_seq, list(self.k_scale),
                list(self.v_scale), out_dtype=self.out_dtype,
                ragged_lengths=jnp.asarray(length, jnp.int32), ssm=self.ssm)
        counters = self.counters.at[0].set(length)
        return QuantPagedKVState(list(self.k), list(self.v), counters,
                                 self.block_table, self.page_size,
                                 self.pages_per_seq, list(self.k_scale),
                                 list(self.v_scale),
                                 out_dtype=self.out_dtype, ssm=self.ssm)

    def reset(self):
        table = jnp.full_like(self.block_table, -1)
        return QuantPagedKVState(list(self.k), list(self.v),
                                 jnp.zeros((3,), jnp.int32), table,
                                 self.page_size, self.pages_per_seq,
                                 list(self.k_scale), list(self.v_scale),
                                 out_dtype=self.out_dtype,
                                 ssm=(self.ssm.reset()
                                      if self.ssm is not None else None))

    def insert_row(self, row, src):
        out = super().insert_row(row, src)
        span = self.pages_per_seq * self.page_size
        row = jnp.asarray(row, jnp.int32)
        start = row * span
        out.k_scale = [jax.lax.dynamic_update_slice(d, s[:, :span],
                                                    (0, start, 0))
                       for d, s in zip(self.k_scale, src.k_scale)]
        out.v_scale = [jax.lax.dynamic_update_slice(d, s[:, :span],
                                                    (0, start, 0))
                       for d, s in zip(self.v_scale, src.v_scale)]
        return out

    def row_view(self, row, length):
        base = super().row_view(row, length)
        return QuantPagedKVState(base.k, base.v, base.counters,
                                 base.block_table, base.page_size,
                                 base.pages_per_seq, list(self.k_scale),
                                 list(self.v_scale),
                                 out_dtype=self.out_dtype, ssm=base.ssm)

    def merge_row(self, row, view):
        out = super().merge_row(row, view)
        out.k_scale = list(view.k_scale)
        out.v_scale = list(view.v_scale)
        return out

    def copy_pages(self, src_pages, dst_pages):
        out = super().copy_pages(src_pages, dst_pages)
        if not len(src_pages):
            return out
        rows = lambda pages: (
            np.asarray(list(pages), np.int64)[:, None] * self.page_size
            + np.arange(self.page_size)).reshape(-1)
        src_rows, dst_rows = rows(src_pages), rows(dst_pages)
        out.k_scale = [a.at[:, dst_rows].set(a[:, src_rows])
                       for a in self.k_scale]
        out.v_scale = [a.at[:, dst_rows].set(a[:, src_rows])
                       for a in self.v_scale]
        return out

    def export_row_pages(self, row, length, device: bool = False) -> dict:
        out = super().export_row_pages(row, length, device=device)
        pool_rows = self._export_pool_rows(row, out["pages"])
        gather = ((lambda a: a[:, pool_rows]) if device
                  else (lambda a: np.asarray(a[:, pool_rows])))
        out["k_scale"] = [gather(a) for a in self.k_scale]
        out["v_scale"] = [gather(a) for a in self.v_scale]
        return out

    def import_row_pages(self, row, blob: dict):
        out = super().import_row_pages(row, blob)
        P, S = self.page_size, self.pages_per_seq
        start = jnp.int32(int(row) * S * P)
        zero = jnp.int32(0)
        out.k_scale = [jax.lax.dynamic_update_slice(
                           a, self._import_operand(s, a), (zero, start, zero))
                       for a, s in zip(out.k_scale, blob["k_scale"])]
        out.v_scale = [jax.lax.dynamic_update_slice(
                           a, self._import_operand(s, a), (zero, start, zero))
                       for a, s in zip(out.v_scale, blob["v_scale"])]
        return out

    def export_pages(self, pages, length, device: bool = False) -> dict:
        out = super().export_pages(pages, length, device=device)
        pool_rows = self._page_pool_rows(pages)
        gather = ((lambda a: a[:, pool_rows]) if device
                  else (lambda a: np.asarray(a[:, pool_rows])))
        out["k_scale"] = [gather(a) for a in self.k_scale]
        out["v_scale"] = [gather(a) for a in self.v_scale]
        return out

    def import_pages(self, pages, blob: dict, blob_offset: int = 0):
        out = super().import_pages(pages, blob, blob_offset=blob_offset)
        P = self.page_size
        lo, hi = int(blob_offset) * P, (int(blob_offset) + len(pages)) * P
        pool_rows = self._page_pool_rows(pages)
        out.k_scale = [a.at[:, pool_rows].set(
                           self._import_operand(s[:, lo:hi], a))
                       for a, s in zip(self.k_scale, blob["k_scale"])]
        out.v_scale = [a.at[:, pool_rows].set(
                           self._import_operand(s[:, lo:hi], a))
                       for a, s in zip(self.v_scale, blob["v_scale"])]
        return out

    def _row_bytes(self) -> int:
        """int8 value rows + fp32 scale rows per token, over every layer."""
        values = super()._row_bytes()
        scales = sum(a.shape[0] * a.shape[2] * a.dtype.itemsize
                     for a in (*self.k_scale, *self.v_scale))
        return values + scales

    def memory_bytes(self) -> int:
        return sum(array_device_bytes(a)
                   for a in (*self.k, *self.v, *self.k_scale, *self.v_scale))

    def logical_bytes(self) -> int:
        """Bytes a contiguous out_dtype cache of max_len would occupy."""
        B = self.block_table.shape[0]
        itemsize = jnp.dtype(self.out_dtype).itemsize
        per_row = sum(a.shape[0] * a.shape[2] * itemsize
                      for a in (*self.k, *self.v))
        return B * self.max_len * per_row

    def hbm_components(self) -> dict:
        return {"kv_values": sum(array_device_bytes(a)
                                 for a in (*self.k, *self.v)),
                "kv_scales": sum(array_device_bytes(a)
                                 for a in (*self.k_scale, *self.v_scale)),
                "kv_block_table": self._table_bytes(),
                "ssm_state": self._ssm_bytes()}


def stage_kv_view(kv: PagedKVState, lo: int, hi: int) -> PagedKVState:
    """A pipeline stage's slice of a paged cache: pools restricted to
    attention layers ``[lo, hi)``, everything else SHARED with the full
    state (same counters, block table, ragged lengths, page geometry).

    Safe because the ragged serving path never consults the bump
    allocator: ``packed_rows`` walks the (static) block table and the
    scheduler authors lengths host-side, so S stage views over disjoint
    layer ranges can each run their own forward against the same tables
    and merge back without coordination (serve/decode_scheduler.py
    pipeline dispatch).  The slices alias the full state's pool arrays —
    a view costs no HBM until a stage's forward replaces its pools.
    """
    if isinstance(kv, QuantPagedKVState):
        return QuantPagedKVState(
            kv.k[lo:hi], kv.v[lo:hi], kv.counters, kv.block_table,
            kv.page_size, kv.pages_per_seq, kv.k_scale[lo:hi],
            kv.v_scale[lo:hi], out_dtype=kv.out_dtype,
            ragged_lengths=kv.ragged_lengths)
    return PagedKVState(kv.k[lo:hi], kv.v[lo:hi], kv.counters,
                        kv.block_table, kv.page_size, kv.pages_per_seq,
                        ragged_lengths=kv.ragged_lengths)


def restage_shared(kv: PagedKVState, sharding) -> PagedKVState:
    """Move a stage view's SHARED metadata (counters, block table, ragged
    lengths) onto the stage's own placement — the small-int32 re-staging
    each MPMD stage dispatch performs so its jit never mixes committed
    devices (the pools already live on the stage mesh; metadata follows
    whichever stage merged last).  Device-to-device: no host round trip.
    """
    import jax
    counters, table = jax.device_put((kv.counters, kv.block_table),
                                     sharding)
    lengths = (jax.device_put(kv.ragged_lengths, sharding)
               if kv.ragged_lengths is not None else None)
    if isinstance(kv, QuantPagedKVState):
        return QuantPagedKVState(
            kv.k, kv.v, counters, table, kv.page_size, kv.pages_per_seq,
            kv.k_scale, kv.v_scale, out_dtype=kv.out_dtype,
            ragged_lengths=lengths)
    return PagedKVState(kv.k, kv.v, counters, table, kv.page_size,
                        kv.pages_per_seq, ragged_lengths=lengths)


def merge_stage_kv(kv: PagedKVState, lo: int, hi: int,
                   stage_kv: PagedKVState) -> PagedKVState:
    """Fold a stage's advanced view back into the full cache: the stage's
    pools replace layers ``[lo, hi)`` and its counters/lengths become the
    whole cache's (every stage advances them identically — same descs,
    same block table — so taking the last merged stage's copy is exact).
    Returns a new full-state instance; the input is not mutated."""
    k = list(kv.k)
    v = list(kv.v)
    k[lo:hi] = stage_kv.k
    v[lo:hi] = stage_kv.v
    if isinstance(kv, QuantPagedKVState):
        ks = list(kv.k_scale)
        vs = list(kv.v_scale)
        ks[lo:hi] = stage_kv.k_scale
        vs[lo:hi] = stage_kv.v_scale
        return QuantPagedKVState(
            k, v, stage_kv.counters, stage_kv.block_table, kv.page_size,
            kv.pages_per_seq, ks, vs, out_dtype=kv.out_dtype,
            ragged_lengths=stage_kv.ragged_lengths)
    return PagedKVState(k, v, stage_kv.counters, stage_kv.block_table,
                        kv.page_size, kv.pages_per_seq,
                        ragged_lengths=stage_kv.ragged_lengths)


def stage_pool_bytes(kv: PagedKVState, lo: int, hi: int) -> int:
    """Device bytes held by the pool slices of attention layers
    ``[lo, hi)`` — the per-stage HBM attribution memledger reports
    (values + int8 scales; the shared block table is whole-cache)."""
    arrays = [*kv.k[lo:hi], *kv.v[lo:hi]]
    if isinstance(kv, QuantPagedKVState):
        arrays += [*kv.k_scale[lo:hi], *kv.v_scale[lo:hi]]
    return sum(array_device_bytes(a) for a in arrays)


def create_kv_state(specs, batch: int, max_len: int, dtype=jnp.float32,
                    quantized: bool | None = None,
                    paged: bool | None = None,
                    extra_pool_pages: int = 0,
                    ssm_specs=None) -> KVState:
    """Factory honoring ``TURBO_QUANT_KV_CACHE=1`` and ``PAGED_KV_CACHE=1``
    (both together → the int8 paged pool).  ``extra_pool_pages`` grows the
    paged pool beyond the per-row partition — the reserved prefix-cache
    region (ignored by contiguous layouts, which have no shared pool).

    ``ssm_specs`` — per-``ssm``-layer ``(num_heads, head_dim, value_dim)``
    triples (models/model.py::CompiledArch.ssm_specs) — attaches a
    fixed-size recurrent child (ops/ssm.py) that rides every variant's
    pytree and row ops; pure-SSM models get an empty-pool paged/contiguous
    shell whose only state bytes are the recurrent tensors."""
    if quantized is None:
        quantized = turbo_quant_enabled()
    if paged is None:
        paged = paged_enabled()
    page = default_page_size()
    pool_pages = None
    if paged and extra_pool_pages:
        pool_pages = batch * (-(-max_len // page)) + int(extra_pool_pages)
    if quantized and paged:
        log.info("Int8 paged KV cache enabled (%s=1 + %s=1, page_size=%d)",
                 TURBO_QUANT_ENV, PAGED_ENV, page)
        state = QuantPagedKVState.create(specs, batch, max_len, dtype,
                                         pool_pages=pool_pages)
    elif quantized:
        log.info("TurboQuant KV cache enabled (%s=1)", TURBO_QUANT_ENV)
        state = QuantKVState.create(specs, batch, max_len, dtype)
    elif paged:
        log.info("Paged KV cache enabled (%s=1, page_size=%d)", PAGED_ENV,
                 page)
        state = PagedKVState.create(specs, batch, max_len, dtype,
                                    pool_pages=pool_pages)
    else:
        state = KVState.create(specs, batch, max_len, dtype)
    if ssm_specs:
        from penroz_tpu.ops.ssm import SSMState
        state.ssm = SSMState.create(ssm_specs, batch)
    return state


# ---------------------------------------------------------------------------
# Radix prefix-KV cache (host-side bookkeeping over the paged pool)
# ---------------------------------------------------------------------------

class _RadixNode:
    __slots__ = ("key", "page", "children", "parent", "refs", "last_use")

    def __init__(self, key, page, parent, last_use):
        self.key = key          # page_size-token tuple (edge label)
        self.page = page        # physical pool page holding this block's KV
        self.children = {}
        self.parent = parent
        self.refs = 0           # live rows aliasing this page
        self.last_use = last_use


class RadixPrefixCache:
    """Radix tree over page-granularity prompt blocks → pages of a reserved
    region of the paged KV pool (the SGLang/RadixAttention shape adapted to
    this pool: PAPERS.md "Ragged Paged Attention" line of work).

    Pure host-side bookkeeping: the device-side work — aliasing matched
    pages into a row's block table, copying a finished prompt's pages into
    the cache region — is the caller's job via
    :meth:`PagedKVState.with_row_prefix` / :meth:`PagedKVState.copy_pages`.
    This class decides WHICH pages, with:

    - whole-page sharing only (a partially filled page is never cached —
      suffix appends into it would corrupt other readers);
    - refcounted pinning: a page aliased into a live row's table cannot be
      evicted (eviction recycles the page for the next insert, which would
      overwrite KV another row still attends);
    - LRU eviction of unpinned *leaves* only (an interior page is a prefix
      of its children's chains — evicting it would orphan them).

    Greedy outputs with a cache hit are token-identical to a miss: the
    aliased pages hold exactly the K/V the suffix prefill would recompute,
    written at the same absolute positions (RoPE/ALiBi are position-
    absolute, so a shared prefix's KV is request-invariant).
    """

    def __init__(self, pages, page_size: int):
        self.page_size = int(page_size)
        self._pages = list(pages)
        self._free = list(reversed(self._pages))
        # Namespaced roots: cached prefix KV depends on the WEIGHTS that
        # produced it, so rows bound to different LoRA adapters must never
        # alias each other's pages (a base-model prefix hit on an adapter
        # row would silently serve the wrong model).  Each namespace (None
        # = base, an adapter load-generation uid otherwise) gets its own
        # radix root; the page pool and LRU eviction stay shared.
        self._roots: dict = {None: _RadixNode(None, -1, None, 0)}
        self._clock = 0
        self.hits = 0
        self.misses = 0
        self.hit_tokens = 0
        self.inserted_pages = 0
        self.evicted_pages = 0
        # Instance-scoped mirror of record_unpin_underflow: the module
        # global can't say WHICH engine's cache underflowed, and crash
        # recovery swaps cache instances (serve/memledger.py carries the
        # retired instance's count forward).
        self.unpin_underflows = 0

    # -- introspection ------------------------------------------------------

    @property
    def capacity_pages(self) -> int:
        return len(self._pages)

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def cached_pages(self) -> int:
        return len(self._pages) - len(self._free)

    def stats(self) -> dict:
        total = self.hits + self.misses
        return {
            "capacity_pages": self.capacity_pages,
            "cached_pages": self.cached_pages,
            "hits": self.hits,
            "misses": self.misses,
            "hit_rate": (self.hits / total) if total else None,
            "hit_tokens": self.hit_tokens,
            "inserted_pages": self.inserted_pages,
            "evicted_pages": self.evicted_pages,
        }

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def _blocks(self, tokens, limit=None):
        P = self.page_size
        n = len(tokens) if limit is None else min(int(limit), len(tokens))
        for b in range(n // P):
            yield tuple(int(t) for t in tokens[b * P:(b + 1) * P])

    # -- lookup / registration ----------------------------------------------

    def _ns_root(self, namespace):
        root = self._roots.get(namespace)
        if root is None:
            root = self._roots[namespace] = _RadixNode(None, -1, None, 0)
        return root

    def chain(self, tokens, limit=None, namespace=None) -> list:
        """The cached node chain for ``tokens``' longest whole-page prefix,
        WITHOUT hit/miss accounting — bookkeeping walks (the preemption path
        re-pinning a chain it just inserted) must not skew the hit-rate
        stats that describe admission lookups.  Touches LRU recency like
        :meth:`match` (the chain is demonstrably live)."""
        nodes = []
        node = self._ns_root(namespace)
        for key in self._blocks(tokens, limit):
            child = node.children.get(key)
            if child is None:
                break
            nodes.append(child)
            node = child
        t = self._tick()
        for nd in nodes:
            nd.last_use = t
        return nodes

    def match(self, tokens, limit=None, namespace=None) -> list:
        """Longest cached prefix of ``tokens`` in whole pages; returns the
        matched node chain (``[n.page for n in nodes]`` are the pages to
        alias, in logical order).  ``limit`` caps the usable token count —
        admission passes ``len(prompt) - 1`` so at least one real token is
        always left to produce the first-sample logits.  Counts a hit iff
        at least one page matched.  ``namespace`` isolates adapter-bound
        rows: a lookup only ever matches pages inserted under the SAME
        namespace."""
        nodes = self.chain(tokens, limit, namespace)
        if nodes:
            self.hits += 1
            self.hit_tokens += len(nodes) * self.page_size
        else:
            self.misses += 1
        return nodes

    def pin(self, nodes):
        """Hold ``nodes``' pages against eviction while a live row aliases
        them (admission → :meth:`unpin` at retirement)."""
        for nd in nodes:
            nd.refs += 1

    def unpin(self, nodes):
        for nd in nodes:
            nd.refs -= 1
            if nd.refs < 0:  # defensive: never let an unpaired unpin
                nd.refs = 0  # turn into a negative permanent pin
                self.unpin_underflows += 1
                record_unpin_underflow(nd.key)

    def iter_nodes(self):
        """Every cached node across all namespaces (roots excluded — they
        own no page).  DFS order; callers must not mutate while iterating."""
        stack = [nd for root in self._roots.values()
                 for nd in root.children.values()]
        while stack:
            nd = stack.pop()
            stack.extend(nd.children.values())
            yield nd

    def page_audit(self) -> list[str]:
        """Structural invariants of the page bookkeeping, as violation
        strings (empty = sound).  Checks that cached + free is a PARTITION
        of the reserved region: no page on both sides, no page on neither
        (leaked), no page outside the region (foreign), no page under two
        nodes.  The capacity ledger's strict mode (serve/memledger.py)
        runs this after every retirement and crash recovery."""
        problems: list[str] = []
        region = set(self._pages)
        free = list(self._free)
        free_set = set(free)
        if len(free) != len(free_set):
            problems.append("duplicate pages on the free list")
        cached: dict = {}
        for nd in self.iter_nodes():
            if nd.page in cached:
                problems.append(
                    f"page {nd.page} owned by two nodes")
            cached[nd.page] = nd
            if nd.page not in region:
                problems.append(f"cached page {nd.page} outside the "
                                f"reserved region")
            if nd.refs < 0:
                problems.append(f"page {nd.page}: negative refs {nd.refs}")
        overlap = free_set & set(cached)
        if overlap:
            problems.append(f"pages both free and cached: {sorted(overlap)}")
        leaked = region - free_set - set(cached)
        if leaked:
            problems.append(f"pages neither free nor cached (leaked): "
                            f"{sorted(leaked)}")
        foreign = free_set - region
        if foreign:
            problems.append(f"free-list pages outside the reserved region: "
                            f"{sorted(foreign)}")
        return problems

    def insert(self, tokens, limit=None,
               namespace=None) -> list[tuple[int, int]]:
        """Ensure nodes exist for every full page block of ``tokens``;
        returns ``(block_index, page)`` pairs NEWLY allocated — the caller
        must ``copy_pages`` the corresponding KV into them.  Allocation
        evicts unpinned LRU leaves on demand and stops early (no error)
        when everything left is pinned; partial chains are valid prefixes.
        ``namespace`` must match the weights (base / adapter generation)
        that computed the pages being registered.
        """
        created = []
        chain = []
        node = self._ns_root(namespace)
        t = self._tick()
        try:
            for b, key in enumerate(self._blocks(tokens, limit)):
                child = node.children.get(key)
                if child is None:
                    page = self._alloc()
                    if page is None:
                        break
                    child = _RadixNode(key, page, node, t)
                    node.children[key] = child
                    created.append((b, page))
                    self.inserted_pages += 1
                child.last_use = t
                # pin the chain while building it: a tiny pool must not
                # evict a node we created two blocks ago (its page would be
                # recycled for a later block of this very chain, and the
                # caller's copy would clobber it).
                child.refs += 1
                chain.append(child)
                node = child
        finally:
            for nd in chain:
                nd.refs -= 1
        return created

    def _alloc(self):
        if self._free:
            return self._free.pop()
        victim = self._lru_leaf()
        if victim is None:
            return None
        self._evict(victim)
        return self._free.pop()

    def _lru_leaf(self):
        best = None
        stack = [nd for root in self._roots.values()
                 for nd in root.children.values()]
        while stack:
            nd = stack.pop()
            if nd.children:
                stack.extend(nd.children.values())
            elif nd.refs == 0 and (best is None
                                   or nd.last_use < best.last_use):
                best = nd
        return best

    def _evict(self, node):
        del node.parent.children[node.key]
        self._free.append(node.page)
        self.evicted_pages += 1

    def clear(self):
        """Drop every cached prefix and reclaim all pages (model reload:
        cached K/V from the old weights must never serve the new ones).
        Callers only reload with zero rows in flight, so nothing is pinned.
        Counters survive — they are lifetime observability."""
        self._roots = {None: _RadixNode(None, -1, None, 0)}
        self._free = list(reversed(self._pages))


# ---------------------------------------------------------------------------
# Metrics + API-parity wrappers
# ---------------------------------------------------------------------------

@dataclass
class KVCacheMetrics:
    """Lightweight metrics for KV cache usage (parity: kv_cache.py:14-22)."""
    num_appends: int = 0
    total_entries: int = 0
    memory_bytes: int = 0
    compressed_memory_bytes: int = 0
    compression_ratio: float = 1.0
    last_append_latency_ms: float = 0.0
    # Process-wide KV writes dropped at pool capacity, snapshotted per step
    # (see record_pool_drop) — surfaces silent paged-pool truncation.
    pool_capacity_drops: int = 0


class KVCache:
    """Dynamically growing per-layer KV store with metrics.

    Used for observability and standalone (non-jit) decode; the jitted decode
    path uses ``KVState``.  Float inputs are stored as-is.
    """

    def __init__(self, num_layers: int = 0):
        self._num_layers = num_layers
        self._keys = [None] * num_layers
        self._values = [None] * num_layers
        self._metrics = KVCacheMetrics()

    @property
    def metrics(self) -> KVCacheMetrics:
        return self._metrics

    def _store(self, layer_idx, key, value):
        if self._keys[layer_idx] is not None:
            key = jnp.concatenate([self._keys[layer_idx], key], axis=2)
            value = jnp.concatenate([self._values[layer_idx], value], axis=2)
        self._keys[layer_idx] = key
        self._values[layer_idx] = value
        return key, value

    def append(self, layer_idx: int, key, value):
        """Append (B, H, S_new, D) K/V; returns accumulated full tensors."""
        t0 = time.monotonic()
        key, value = jnp.asarray(key), jnp.asarray(value)
        new_bytes = key.size * key.dtype.itemsize + value.size * value.dtype.itemsize
        full_key, full_value = self._store(layer_idx, key, value)
        m = self._metrics
        m.num_appends += 1
        m.total_entries += key.shape[2]
        m.memory_bytes += int(new_bytes)
        m.compressed_memory_bytes = m.memory_bytes
        m.compression_ratio = 1.0
        m.last_append_latency_ms = (time.monotonic() - t0) * 1000
        return full_key, full_value

    def get(self, layer_idx: int):
        return self._keys[layer_idx], self._values[layer_idx]

    def clear(self):
        self._keys = [None] * self._num_layers
        self._values = [None] * self._num_layers
        self._metrics = KVCacheMetrics()

    def seq_len(self, layer_idx: int = 0) -> int:
        k = self._keys[layer_idx]
        return int(k.shape[2]) if k is not None else 0

    def record_step(self, num_tokens: int, logical_bytes: int,
                    stored_bytes: int, latency_ms: float = 0.0):
        """Metrics update from the jitted decode path (one call per step)."""
        m = self._metrics
        m.num_appends += 1
        m.total_entries += num_tokens
        m.memory_bytes = int(logical_bytes)
        m.compressed_memory_bytes = int(stored_bytes)
        m.compression_ratio = (m.memory_bytes / m.compressed_memory_bytes
                               if m.compressed_memory_bytes else 1.0)
        m.last_append_latency_ms = latency_ms
        m.pool_capacity_drops = pool_drop_count()

    def log_metrics(self):
        m = self._metrics
        log.info(
            "KVCache metrics: entries=%d, memory=%.1fKB, "
            "compression_ratio=%.2f, last_append=%.3fms, pool_drops=%d",
            m.total_entries, m.memory_bytes / 1024, m.compression_ratio,
            m.last_append_latency_ms, m.pool_capacity_drops)


class TurboQuantKVCache(KVCache):
    """Int8 + per-token-scale variant of :class:`KVCache`."""

    def __init__(self, num_layers: int = 0):
        super().__init__(num_layers)
        self._scales_k = [None] * num_layers
        self._scales_v = [None] * num_layers

    @staticmethod
    def _quantize(tensor):
        return _quantize_int8(jnp.asarray(tensor))

    @staticmethod
    def _dequantize(quantized, scale):
        return quantized.astype(jnp.float32) * scale

    def append(self, layer_idx: int, key, value):
        t0 = time.monotonic()
        key, value = jnp.asarray(key), jnp.asarray(value)
        q_key, s_key = self._quantize(key)
        q_value, s_value = self._quantize(value)
        compressed_new = sum(int(t.size) * t.dtype.itemsize
                             for t in (q_key, q_value, s_key, s_value))

        if self._keys[layer_idx] is not None:
            q_key = jnp.concatenate([self._keys[layer_idx], q_key], axis=2)
            q_value = jnp.concatenate([self._values[layer_idx], q_value], axis=2)
            s_key = jnp.concatenate([self._scales_k[layer_idx], s_key], axis=2)
            s_value = jnp.concatenate([self._scales_v[layer_idx], s_value], axis=2)
        self._keys[layer_idx] = q_key
        self._values[layer_idx] = q_value
        self._scales_k[layer_idx] = s_key
        self._scales_v[layer_idx] = s_value

        full_key = self._dequantize(q_key, s_key)
        full_value = self._dequantize(q_value, s_value)

        m = self._metrics
        m.num_appends += 1
        m.total_entries += key.shape[2]
        uncompressed_new = (key.size * key.dtype.itemsize
                            + value.size * value.dtype.itemsize)
        m.compressed_memory_bytes += int(compressed_new)
        m.memory_bytes += int(uncompressed_new)
        m.compression_ratio = (m.memory_bytes / m.compressed_memory_bytes
                               if m.compressed_memory_bytes > 0 else 1.0)
        m.last_append_latency_ms = (time.monotonic() - t0) * 1000
        return full_key, full_value

    def clear(self):
        super().clear()
        self._scales_k = [None] * self._num_layers
        self._scales_v = [None] * self._num_layers


def create_kv_cache(num_layers: int) -> KVCache:
    """Factory: TurboQuant or plain cache based on the env flag."""
    if turbo_quant_enabled():
        log.info("TurboQuant KV cache enabled (%s=1)", TURBO_QUANT_ENV)
        return TurboQuantKVCache(num_layers)
    return KVCache(num_layers)
