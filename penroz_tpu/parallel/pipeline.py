"""Pipeline parallelism: GPipe schedule over a ``pipe`` mesh axis.

The repeated transformer blocks of a DSL stack are *stacked* on a leading
layer dimension and sharded over the ``pipe`` axis — each stage (device
group) holds ``L / P`` consecutive blocks.  Microbatches stream through the
stages inside one ``shard_map``-compiled program: every schedule tick, each
stage applies its blocks (a ``lax.scan`` over its stacked shard) and hands
its activation to the next stage with ``lax.ppermute`` over ICI.  The
pipeline bubble is the standard GPipe ``(P-1)/(M+P-1)`` and invalid
in-flight activations are masked at the output buffer, never observed.

The whole schedule is differentiable (``ppermute`` has a transpose), so the
same function sits under ``jax.grad`` for pipeline-parallel training.

On 1F1B: a hand-scheduled one-forward-one-backward interleave bounds
in-flight activations to ``P`` microbatches; this implementation reaches
the same memory class compositionally instead.  ``remat="block"`` bounds
per-tick residency to block *inputs* (backward replays internals in
reverse schedule order — itself a pipelined schedule), and the grad-accum
scan above this function already chunks a step into micro-steps whose
activations are released between chunks: ``PENROZ_PIPE_MICROBATCHES``
trades bubble fraction ``(P-1)/(M+P-1)`` against per-chunk activation
memory exactly the way 1F1B's schedule depth does, with the compiler
owning the interleave.  A literal 1F1B would additionally need the loss
fused per-microbatch inside the schedule (cotangents before the last
microbatch finishes) — a restructuring whose win over remat+chunking is
a constant factor, not a complexity class.

No reference equivalent (the reference's only strategy is single-node DDP,
SURVEY.md §2.4) — this is capability extension shaped by the mesh design:
PP is a sharding of the *depth* dimension the way TP shards width.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from penroz_tpu.parallel.mesh import DATA_AXIS, PIPE_AXIS, SEQ_AXIS


def pipeline_block_range(layers_dsl: list[dict]) -> tuple[int, int]:
    """Longest contiguous run of *identical* top-level DSL entries — the
    repeated transformer blocks a GPipe schedule can stack and shard over
    the ``pipe`` axis.  Returns ``(start, count)``; ``count`` is 1 when no
    entry repeats (then PP has nothing to pipeline).

    Identity is full-config equality: heterogeneous stacks (e.g. Gemma
    sliding/full alternating dims) only pipeline their equal sub-runs.
    """
    import json
    keys = [json.dumps(entry, sort_keys=True, default=str)
            for entry in layers_dsl]
    best_start, best_count = 0, 1
    i = 0
    while i < len(keys):
        j = i
        while j + 1 < len(keys) and keys[j + 1] == keys[i]:
            j += 1
        if j - i + 1 > best_count:
            best_start, best_count = i, j - i + 1
        i = j + 1
    return best_start, best_count


def serve_stage_bounds(layers_dsl: list[dict], stages: int) -> list[tuple]:
    """Contiguous top-level DSL entry ranges for ``stages`` serving
    pipeline stages — the MPMD stage partition of the decode path
    (PENROZ_SERVE_PIPE_STAGES).

    The repeated transformer blocks (:func:`pipeline_block_range`) are
    split into ``stages`` near-equal contiguous runs; stage 0 prepends
    everything before the run (embedding/position), the last stage
    appends everything after it (final norm / head / softmax), so the
    stage DSLs concatenate back to the full stack and each mid-stage
    consumes hidden states directly (CompiledArch._apply iterates its
    module list over whatever ``x`` it is given).  Returns
    ``[(lo, hi), ...]`` half-open entry ranges covering the whole list.

    Raises ``ValueError`` when the model has fewer repeated blocks than
    stages — a stage without a block would hold no attention layer and
    no KV pool slice, which the per-stage ledger attribution rejects.
    """
    stages = int(stages)
    if stages < 1:
        raise ValueError(f"stages must be >= 1, got {stages}")
    start, count = pipeline_block_range(layers_dsl)
    if count < stages:
        raise ValueError(
            f"cannot partition {count} repeated block(s) over {stages} "
            f"pipeline stages (need at least one block per stage)")
    sizes = [count // stages + (1 if i < count % stages else 0)
             for i in range(stages)]
    bounds = []
    lo = 0
    hi = start
    for i, size in enumerate(sizes):
        hi += size
        bounds.append((lo, len(layers_dsl) if i == stages - 1 else hi))
        lo = hi
    return bounds


def stack_block_params(params: dict, block_indices, prefix="layers") -> dict:
    """Stack per-block params ``layers.{i}.<suffix>`` into ``(L, ...)`` leaves.

    ``block_indices`` must name structurally identical DSL entries (same
    suffix set and shapes) — the usual repeated transformer blocks.
    Returns ``{suffix: stacked}``.
    """
    first = f"{prefix}.{block_indices[0]}."
    suffixes = [k[len(first):] for k in params if k.startswith(first)]
    if not suffixes:
        raise ValueError(f"no params under {first}")
    stacked = {}
    for suffix in suffixes:
        leaves = [params[f"{prefix}.{i}.{suffix}"] for i in block_indices]
        stacked[suffix] = jnp.stack(leaves)
    return stacked


def unstack_block_params(stacked: dict, block_indices, prefix="layers") -> dict:
    """Inverse of :func:`stack_block_params`."""
    out = {}
    for suffix, leaf in stacked.items():
        for j, i in enumerate(block_indices):
            out[f"{prefix}.{i}.{suffix}"] = leaf[j]
    return out


def gpipe_spec(mesh, seq_shard: bool = False):
    """(stacked-params spec, microbatch spec, output spec) for gpipe_apply.

    ``seq_shard=True`` additionally shards the microbatch T dim over the
    ``sequence`` axis (Ulysses SP inside the stages)."""
    param_spec = P(PIPE_AXIS)
    # (M, B_mb, T, D): batch over data (+ T over sequence when SP)
    mb_spec = (P(None, DATA_AXIS, SEQ_AXIS) if seq_shard
               else P(None, DATA_AXIS))
    return param_spec, mb_spec, mb_spec


def gpipe_apply(block_fn, stacked_params: dict, x, mesh,
                num_microbatches: int, rng=None, remat: str = "none",
                with_aux: bool = False, seq_shard: bool = False,
                aux_probe_fn=None):
    """Apply ``L`` stacked blocks to ``x`` with a ``P``-stage GPipe schedule.

    ``block_fn(block_params: dict, h) -> h`` applies ONE block given its
    un-stacked param dict.  ``stacked_params`` leaves carry a leading ``L``
    dim with ``L % P == 0``; ``x`` is ``(B, T, D)`` with
    ``B % num_microbatches == 0``.  Output equals applying the ``L`` blocks
    sequentially (same math, pipelined schedule).

    With ``rng`` set, ``block_fn`` is instead called as
    ``block_fn(block_params, h, key)`` where ``key`` is folded from the
    global layer index and the schedule tick — every (layer, microbatch)
    application gets a distinct dropout stream, like the sequential path's
    per-call ``Ctx.next_rng`` folding.

    ``remat="block"`` wraps each block application in ``jax.checkpoint``:
    the backward pass saves only the per-(layer, tick) block *inputs* and
    recomputes block internals tick-by-tick in reverse schedule order —
    the reverse of a GPipe schedule is itself a pipelined schedule, so the
    recomputation stays distributed over the stages.  This bounds the
    activation residency the way a hand-scheduled 1F1B does (O(live
    microbatch activations) instead of O(all block internals)) while
    keeping exact numerics; the schedule/memory trade is the compiler's,
    which is the TPU-idiomatic split.  ``remat="none"`` keeps everything.

    ``with_aux=True``: ``block_fn`` returns ``(h, aux)`` where ``aux`` is a
    flat dict — key ``"loss"`` is a per-block scalar (e.g. the MoE balance
    loss) and every other key a per-block statistic (e.g.
    ``"buf.<suffix>"`` router fractions).  The schedule masks the pipeline
    bubble (warmup/drain ticks process garbage activations that must not
    pollute the sums) and SUMS every key over real (layer, microbatch)
    applications; the caller divides by ``num_microbatches``.  Because
    microbatches partition the batch rows equally, that mean is EXACTLY
    the whole-batch value for row-mean statistics like router fractions —
    identical to the sequential path computing them on the full batch.
    Returns ``(out, sums)`` with ``sums`` ``{key: (L, ...)}`` leaves,
    pmean'd over the data axis (again exact for row-mean statistics; the
    nonlinear balance loss becomes the mean of per-shard losses — the
    standard per-group/local Switch formulation).

    ``seq_shard=True``: the ``sequence`` axis joins the manual set and the
    microbatch T dim shards over it — ``block_fn`` must then handle its
    own sequence-parallel attention on the ambient axis (the Ctx's
    ``sp_manual_axis``, Ulysses all-to-alls inside the stage).  The aux
    channel folds the sequence axis into its pmean alongside data, so
    row-mean statistics stay exact over the full (rows × positions) set.
    """
    if remat not in ("none", "block"):
        raise ValueError(f"remat={remat!r}: expected 'none' or 'block'")
    if remat == "block":
        # prevent_cse=False: the checkpointed block only ever runs inside
        # lax.scan, where the CSE hazard checkpoint guards against cannot
        # occur — skipping the optimization_barrier keeps XLA free to fuse
        # across the block boundary in the forward ticks.
        block_fn = jax.checkpoint(block_fn, prevent_cse=False)
    pipe = mesh.shape[PIPE_AXIS]
    num_layers = next(iter(stacked_params.values())).shape[0]
    if num_layers % pipe:
        raise ValueError(f"{num_layers} blocks not divisible by pipe={pipe}")
    batch = x.shape[0]
    if batch % num_microbatches:
        raise ValueError(f"batch {batch} not divisible by "
                         f"microbatches={num_microbatches}")
    mbs = x.reshape(num_microbatches, batch // num_microbatches, *x.shape[1:])
    m = num_microbatches

    param_spec, mb_spec, out_spec = gpipe_spec(mesh, seq_shard=seq_shard)
    in_specs = (jax.tree.map(lambda _: param_spec, stacked_params), mb_spec)
    manual_axes = ({PIPE_AXIS, DATA_AXIS, SEQ_AXIS} if seq_shard
                   else {PIPE_AXIS, DATA_AXIS})

    aux_struct = None
    if with_aux:
        # Aux key set / shapes, needed to build the scan carry and the
        # shard_map out_specs before tracing the schedule.  Row counts
        # never reach aux shapes (scalars / per-expert vectors), so the
        # global microbatch shape stands in for the per-shard one.
        # ``aux_probe_fn``: shape-probe variant of block_fn for callers
        # whose real block_fn references manual axes (sequence-parallel
        # attention) that are unbound outside the shard_map — aux shapes
        # do not depend on the sharding, so a non-SP twin serves.
        probe = aux_probe_fn if aux_probe_fn is not None else block_fn
        p0 = {k: jax.ShapeDtypeStruct(v.shape[1:], v.dtype)
              for k, v in stacked_params.items()}
        h0 = jax.ShapeDtypeStruct(mbs.shape[1:], x.dtype)
        args = (p0, h0) if rng is None else (p0, h0, rng)
        _, aux_struct = jax.eval_shape(probe, *args)
        if "loss" not in aux_struct:
            raise ValueError("with_aux block_fn must return a 'loss' key")

    def stage_fn(params_stage, mbs_local):
        stage = jax.lax.axis_index(PIPE_AXIS)
        layers_per_stage = num_layers // pipe

        def apply_blocks(h, t):
            def body(hh, idx_and_params):
                idx, pl = idx_and_params
                if rng is None:
                    res = block_fn(pl, hh)
                else:
                    key = jax.random.fold_in(
                        jax.random.fold_in(
                            rng, stage * layers_per_stage + idx), t)
                    # Distinct dropout streams per manual shard — without
                    # the folds, every data (and sequence) shard would
                    # reuse one mask pattern across DIFFERENT rows or T
                    # positions, correlating the regularization.
                    key = jax.random.fold_in(
                        key, jax.lax.axis_index(DATA_AXIS))
                    if seq_shard:
                        key = jax.random.fold_in(
                            key, jax.lax.axis_index(SEQ_AXIS))
                    res = block_fn(pl, hh, key)
                if with_aux:
                    return res
                return res, None

            h, auxs = jax.lax.scan(body, h,
                                   (jnp.arange(layers_per_stage),
                                    params_stage))
            return h, auxs

        def tick(carry, t):
            state, buf, aux_acc = carry
            # Stage 0 ingests a fresh microbatch; others consume the
            # activation handed over by the previous stage last tick.
            feed = mbs_local[jnp.clip(t, 0, m - 1)]
            h, auxs = apply_blocks(jnp.where(stage == 0, feed, state), t)
            # Stage s works on microbatch t - s; the last stage commits it.
            out_mb = t - stage
            computing = (out_mb >= 0) & (out_mb < m)
            valid = computing & (stage == pipe - 1)
            committed = buf.at[jnp.clip(out_mb, 0, m - 1)].set(h)
            buf = jnp.where(valid, committed, buf)
            if with_aux:
                # Bubble ticks process garbage — mask them out of the sums.
                aux_acc = {k: acc + jnp.where(computing, auxs[k], 0.0)
                           for k, acc in aux_acc.items()}
            state = jax.lax.ppermute(
                h, PIPE_AXIS, [(i, (i + 1) % pipe) for i in range(pipe)])
            return (state, buf, aux_acc), None

        # The carry is device-varying over both `data` (inherited from the
        # sharded microbatches via zeros_like) and `pipe` (each stage's state
        # diverges after the first ppermute); the zero init must match.
        zero_buf = jax.lax.pcast(jnp.zeros_like(mbs_local), (PIPE_AXIS,),
                                 to="varying")
        zero_state = zero_buf[0]
        aux0 = None
        if with_aux:
            vary_axes = ((PIPE_AXIS, DATA_AXIS, SEQ_AXIS) if seq_shard
                         else (PIPE_AXIS, DATA_AXIS))

            def zinit(sd):
                # Fresh zeros are axis-invariant; the accumulated values
                # derive from activations varying over every manual axis.
                return jax.lax.pcast(
                    jnp.zeros((layers_per_stage,) + tuple(sd.shape),
                              jnp.float32),
                    vary_axes, to="varying")
            aux0 = {k: zinit(v) for k, v in aux_struct.items()}
        (_, buf, aux_final), _ = jax.lax.scan(
            tick, (zero_state, zero_buf, aux0), jnp.arange(m + pipe - 1))
        # Only the last stage holds real outputs; broadcast them to all.
        mine = jnp.where(stage == pipe - 1, buf, jnp.zeros_like(buf))
        out = jax.lax.psum(mine, PIPE_AXIS)
        if not with_aux:
            return out
        # Row-mean statistics (router fractions) are exact under the
        # data(+sequence) pmean; the balance loss becomes the mean of
        # per-shard losses.
        aux_axes = ((DATA_AXIS, SEQ_AXIS) if seq_shard else DATA_AXIS)
        return out, {k: jax.lax.pmean(v, aux_axes)
                     for k, v in aux_final.items()}

    # Partial-manual shard_map: only the pipe and data axes are manual
    # (the schedule's ppermute/psum/axis_index live on them); the model/
    # sequence/expert axes stay GSPMD-automatic, so stacked leaves carrying
    # a tensor-parallel sharding on their trailing dims (P(pipe, model, …)
    # from _enter_pipe_layout) get their TP collectives inserted by XLA
    # inside each stage — that is what lets pipe×model meshes train.
    if with_aux:
        out_specs = (out_spec, {k: P(PIPE_AXIS) for k in aux_struct})
    else:
        out_specs = out_spec
    res = jax.shard_map(stage_fn, mesh=mesh, in_specs=in_specs,
                        out_specs=out_specs,
                        axis_names=manual_axes)(stacked_params, mbs)
    if not with_aux:
        return res.reshape(batch, *x.shape[1:])
    out, sums = res
    return out.reshape(batch, *x.shape[1:]), sums


def block_fn_from_arch(arch, block_index: int, *, training=False,
                       compute_dtype=None, platform=None,
                       with_aux: bool = False, sp_manual: bool = False,
                       sp_mode: str = "ring"):
    """``block_fn`` for :func:`gpipe_apply` from one bound DSL block module.

    Uses the module tree of block ``block_index`` with params rebound from
    the un-stacked leaf dict (all stacked blocks are structurally identical,
    so one module tree serves every layer).  The optional ``key`` third
    argument carries the per-(layer, tick) dropout stream gpipe_apply folds
    when given an ``rng``.

    ``with_aux=True`` returns ``(h, aux)`` in the gpipe_apply aux protocol:
    ``aux["loss"]`` sums the block's auxiliary losses (MoE balance) and
    ``aux["buf.<suffix>"]`` carries its buffer updates (router fractions),
    suffixes relative to the block prefix so the caller can re-key them per
    unstacked layer.
    """
    from penroz_tpu.ops import modules as M
    mod = arch.mods[block_index]
    prefix = f"layers.{block_index}."

    def block_fn(block_params: dict, h, key=None):
        ctx = M.Ctx({prefix + suffix: leaf
                     for suffix, leaf in block_params.items()},
                    training=training, rng=key,
                    compute_dtype=compute_dtype, platform=platform,
                    sp_manual_axis=SEQ_AXIS if sp_manual else None,
                    sp_mode=sp_mode)
        out = mod.apply(h, ctx)
        if not with_aux:
            return out
        loss = (sum(ctx.aux_losses) if ctx.aux_losses
                else jnp.zeros((), jnp.float32))
        aux = {"loss": jnp.asarray(loss, jnp.float32)}
        for k, v in ctx.buffer_updates.items():
            aux["buf." + k[len(prefix):]] = v
        return out, aux

    return block_fn
