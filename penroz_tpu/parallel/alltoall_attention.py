"""Ulysses-style all-to-all sequence parallelism.

The second SP mode next to ring attention (``PENROZ_SP_MODE=alltoall``,
DeepSpeed-Ulysses, arXiv:2309.14509 pattern): instead of rotating K/V blocks
around the ring, one ``lax.all_to_all`` re-partitions the activations from
sequence-sharded to **head**-sharded — each device then holds the FULL
sequence for ``H/n`` heads, runs ordinary causal attention locally (the
Pallas flash kernel on TPU), and a second all-to-all restores sequence
sharding.  Communication volume is two all-to-alls of the activations,
independent of the number of ring steps, which favors meshes whose
sequence axis is large relative to the per-step compute; ring attention
keeps peak activation memory at O(T/n) and wins when T/n·T/n scores
dominate, so both modes stay available.

The reference has no long-context support at all (SURVEY.md §5); like ring
attention this is an extension point, not a parity item.

Constraint: the head dims must split evenly — ``Hq % n == 0`` and
``Hkv % n == 0`` (GQA grouping is preserved because every head chunk
contains whole query groups when both divide).  Ring attention has no such
constraint; the dispatcher falls back accordingly.
"""

from __future__ import annotations

import functools

import jax
from jax.sharding import Mesh, PartitionSpec as P

from penroz_tpu.parallel.mesh import SEQ_AXIS


def alltoall_supported(num_heads: int, num_kv_heads: int, mesh=None,
                       axis_name: str = SEQ_AXIS, n: int = None) -> bool:
    """Whether the Ulysses head split is possible on this mesh (or for an
    explicit axis size ``n`` — the manual in-schedule dispatch has no Mesh
    object, only the ambient axis)."""
    if n is None:
        n = mesh.shape[axis_name]
    return num_heads % n == 0 and num_kv_heads % n == 0


def alltoall_attention_manual(q, k, v, *, axis_name: str = SEQ_AXIS,
                              window=None, platform=None, scale=None):
    """Ulysses attention for callers ALREADY inside a manual region that
    binds ``axis_name`` (e.g. the GPipe schedule's shard_map with the
    sequence axis manual) — same math as :func:`alltoall_attention`, minus
    the shard_map wrapper (nesting one inside another is not possible).
    q/k/v: per-shard (B, H, T_local, D) blocks."""
    return _alltoall_local(q, k, v, axis_name=axis_name,
                           scale=scale,
                           window=int(window) if window is not None
                           else None,
                           platform=platform)


def _alltoall_local(q, k, v, *, axis_name: str, window, platform,
                    scale=None):
    """Per-shard body. q/k/v: (B, H, T_local, D) sequence-sharded blocks."""
    from penroz_tpu.ops import attention as attn_ops

    # seq-sharded → head-sharded: split heads n ways, gather the sequence.
    # tiled=True concatenates blocks in axis-index order, so positions stay
    # sorted and ordinary causal masking is correct on the gathered axis.
    q = jax.lax.all_to_all(q, axis_name, split_axis=1, concat_axis=2,
                           tiled=True)
    k = jax.lax.all_to_all(k, axis_name, split_axis=1, concat_axis=2,
                           tiled=True)
    v = jax.lax.all_to_all(v, axis_name, split_axis=1, concat_axis=2,
                           tiled=True)
    out = attn_ops.causal_attention(q, k, v, platform=platform,
                                    window=window, scale=scale)
    # head-sharded → seq-sharded.
    return jax.lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)


def alltoall_attention(q, k, v, mesh: Mesh, *, causal: bool = True,
                       axis_name: str = SEQ_AXIS, window=None, scale=None,
                       platform=None):
    """Sequence-parallel attention via head/sequence all-to-alls.

    q: (B, Hq, T, D); k/v: (B, Hkv, T, D), sharded (or shardable) on T.
    Same contract as :func:`ring_attention.ring_attention`; requires the
    head counts to be divisible by the sequence-axis size.
    """
    if not causal:
        raise ValueError("alltoall_attention supports causal=True only "
                         "(the local pass reuses the causal kernel); use "
                         "ring_attention for bidirectional SP")
    n = mesh.shape[axis_name]
    if q.shape[1] % n or k.shape[1] % n:
        raise ValueError(
            f"alltoall (Ulysses) SP needs heads divisible by the sequence "
            f"axis: Hq={q.shape[1]}, Hkv={k.shape[1]}, {axis_name}={n}; "
            f"use PENROZ_SP_MODE=ring for this config")
    spec = P(None, None, axis_name, None)
    body = functools.partial(
        _alltoall_local, axis_name=axis_name,
        window=int(window) if window is not None else None, scale=scale,
        platform=platform)
    fn = jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec)
    return fn(q, k, v)
