"""Parameter/batch sharding rules for tensor + sequence parallelism.

Tensor parallelism is expressed purely as ``NamedSharding`` placement of the
flat param dict over the mesh's ``model`` axis; XLA's SPMD partitioner then
inserts the all-gathers/reduce-scatters on ICI.  The layout heuristic follows
the Megatron column→row pairing using weight geometry:

- expanding Linear weights (out > in: QKV, MLP up/gate, LM head) are
  column-parallel — shard the out dim;
- contracting Linear weights (out < in: attention proj, MLP down) are
  row-parallel — shard the in dim;
- square weights and vectors are replicated;
- embedding tables shard the vocab dim;
- stacked MoE expert weights (E, ., .) shard E over the ``expert`` axis.

Sequence parallelism: the batch's time dimension is sharded over the
``sequence`` axis; XLA gathers K/V for full attention (ring attention as a
Pallas kernel is the planned upgrade path).
"""

from __future__ import annotations

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from penroz_tpu.parallel.mesh import (DATA_AXIS, EXPERT_AXIS, MODEL_AXIS,
                                      SEQ_AXIS)


def _divides(dim: int, mesh: Mesh, axis: str) -> bool:
    return mesh.shape[axis] > 0 and dim % mesh.shape[axis] == 0


def param_spec(key: str, shape: tuple, mesh: Mesh) -> P:
    """PartitionSpec for one flat-dict parameter."""
    if len(shape) == 3 and ".experts." in key:
        # Stacked MoE expert weights (E, ., .): expert-parallel on dim 0.
        if _divides(shape[0], mesh, EXPERT_AXIS):
            return P(EXPERT_AXIS, None, None)
        return P()
    if len(shape) != 2:
        return P()
    out_dim, in_dim = shape
    is_embedding = key.endswith(".weight") and out_dim > 8 * in_dim
    if is_embedding and _divides(out_dim, mesh, MODEL_AXIS):
        return P(MODEL_AXIS, None)  # vocab-sharded table / lm head
    if out_dim > in_dim and _divides(out_dim, mesh, MODEL_AXIS):
        return P(MODEL_AXIS, None)  # column parallel
    if in_dim > out_dim and _divides(in_dim, mesh, MODEL_AXIS):
        return P(None, MODEL_AXIS)  # row parallel
    return P()


def param_shardings(params: dict, mesh: Mesh, fsdp: bool = False) -> dict:
    """NamedShardings for a flat param dict under the TP layout.

    ``fsdp=True`` (ZeRO-3 / fully-sharded data parallel) additionally
    spreads every param over the ``data`` axis on a dim the TP layout
    leaves free — XLA all-gathers each weight just-in-time for its matmul
    and discards it after, so per-device param memory drops by the
    data-axis size.  Pair with ``opt_state_sharding_tree(wus=True)`` (the
    moments follow the same rule) and pin the training step's outputs via
    ``train_epoch_fn(out_shardings=...)``.
    """
    out = {}
    for k, v in params.items():
        spec = param_spec(k, tuple(v.shape), mesh)
        if fsdp:
            spec = _data_axis_spec(spec, tuple(v.shape), mesh)
        out[k] = NamedSharding(mesh, spec)
    return out


def place(value, sharding):
    """``device_put`` that also works host→non-addressable.

    Under a multi-host mesh the target sharding spans devices this process
    cannot address, and ``device_put`` of a committed process-local array
    would demand a cross-host transfer (unsupported on CPU/gloo, and
    pointless here: every process holds the identical full value after a
    deterministic init or checkpoint load).  Route through host memory and
    let each process contribute exactly its local shards.
    """
    import jax
    if getattr(value, "sharding", None) == sharding:
        return value
    if getattr(sharding, "is_fully_addressable", True):
        return jax.device_put(value, sharding)
    if not (getattr(value, "is_fully_addressable", True)
            or getattr(value, "is_fully_replicated", False)):
        # already cross-host sharded (e.g. FSDP params from a previous
        # train run): only a device-side reshard can express this
        return jax.device_put(value, sharding)
    host = np.asarray(value)
    return jax.make_array_from_callback(host.shape, sharding,
                                        lambda idx: host[idx])


def place_tree(tree, sharding_tree):
    """Leaf-wise :func:`place` over matching pytrees."""
    import jax
    return jax.tree.map(place, tree, sharding_tree,
                        is_leaf=lambda x: x is None)


def place_update(update, dst):
    """Re-shard a hand-off update operand onto ``dst``'s own layout.

    The disaggregated-prefill d2d transport hands the importer *device*
    page planes gathered on the exporter's mesh.  The
    ``lax.dynamic_update_slice`` scatter wants both operands co-sharded,
    and the pool specs from :func:`paged_kv_sharding_tree` are
    shape-polymorphic on the row dim (``P(model|None, None, None)``), so
    the destination pool array's committed sharding applies verbatim to
    the smaller update slab.  No-op when ``dst`` is uncommitted (single
    device) or the layouts already match.
    """
    import jax
    sharding = getattr(dst, "sharding", None)
    if sharding is None or getattr(update, "sharding", None) == sharding:
        return update
    return jax.device_put(update, sharding)


def shard_params(params: dict, mesh: Mesh, fsdp: bool = False) -> dict:
    """Place a flat param dict onto the mesh under the TP (+FSDP) layout."""
    shardings = param_shardings(params, mesh, fsdp=fsdp)
    return {k: place(v, shardings[k]) for k, v in params.items()}


def paged_kv_sharding_tree(kv, mesh: Mesh, kv_specs):
    """Sharding pytree for a paged KV state (ops/kv_cache.py PagedKVState /
    QuantPagedKVState) under a serving mesh: every layer's flat
    ``(Hkv, pages*page_size, D)`` page pool shards its head dim over
    ``model`` when every attention layer's KV head count divides the axis
    (GQA models with too few KV heads stay replicated — a torn head is
    worse than a copied pool); the int8 variants' ``(Hkv, rows, 1)`` scale
    planes follow their pools leaf-by-leaf.  The block table, the packed
    allocator counters and the ragged lengths stay replicated: page
    indices are host-authored and every head shard walks the same map.
    """
    import jax
    tp = mesh.shape[MODEL_AXIS]
    heads_ok = tp > 1 and all(h % tp == 0 for h, _ in kv_specs)
    pool = NamedSharding(
        mesh, P(MODEL_AXIS if heads_ok else None, None, None))
    repl = NamedSharding(mesh, P())

    def leaf_sharding(leaf):
        return pool if getattr(leaf, "ndim", 0) == 3 else repl

    return jax.tree.map(leaf_sharding, kv)


def paged_kv_stage_shard(kv, meshes, kv_bounds, kv_specs):
    """Place a pipeline group's paged pools stage-by-stage: attention
    layers ``kv_bounds[s] = (lo, hi)`` land on ``meshes[s]`` (their own
    TP sharding via :func:`paged_kv_sharding_tree`), so each stage's
    device group holds ONLY its own layers' KV — per-device HBM drops
    ~1/S, the pipeline-serving capacity claim.  The shared block table /
    counters / ragged lengths follow the last stage's mesh replicated
    (host-authored; every stage dispatch re-stages them — small int32
    arrays, not pools).  Degenerate meshes (every stage on the same
    devices, the CPU case) make this a no-op placement-wise."""
    import jax

    from penroz_tpu.ops import kv_cache as KV
    for mesh, (lo, hi) in zip(meshes, kv_bounds):
        view = KV.stage_kv_view(kv, lo, hi)
        tree = paged_kv_sharding_tree(view, mesh, kv_specs[lo:hi])
        kv = KV.merge_stage_kv(kv, lo, hi, jax.device_put(view, tree))
    return kv


def batch_spec(mesh: Mesh, *, leading_steps: bool = False,
               shard_sequence: bool = False) -> P:
    """Spec for (B, T) or (num_steps, B, T) token batches."""
    seq = SEQ_AXIS if (shard_sequence and mesh.shape[SEQ_AXIS] > 1) else None
    spec = (DATA_AXIS, seq)
    if leading_steps:
        spec = (None,) + spec
    return P(*spec)


def shard_batch(batch, mesh: Mesh, **kw):
    import jax
    return jax.device_put(batch, NamedSharding(mesh, batch_spec(mesh, **kw)))


def global_batch(batch, mesh: Mesh, *, leading_steps: bool = False,
                 shard_sequence: bool = False,
                 process_replicated: bool = False):
    """Place a batch on the mesh, lifting process-local rows to a global
    array under multi-host (SURVEY.md §7.1: the rank-strided Loader feeds
    each host its slice; ``jax.make_array_from_process_local_data`` stitches
    the slices into one global batch whose data-axis sharding makes XLA
    insert the cross-host gradient psum).

    Single-process this is exactly :func:`shard_batch`.  The batch dim is
    axis 1 with ``leading_steps`` (num_steps, B, T), else axis 0.

    ``process_replicated=True``: every process already holds the SAME,
    complete batch (pipeline stages spanning hosts — the loader does not
    rank-stride), so the global shape equals the local shape and each
    process just serves its devices' slices via callback.
    """
    import jax
    from penroz_tpu.parallel import dist
    world = dist.process_count()
    if world <= 1:
        return shard_batch(batch, mesh, leading_steps=leading_steps,
                           shard_sequence=shard_sequence)
    spec = batch_spec(mesh, leading_steps=leading_steps,
                      shard_sequence=shard_sequence)
    sharding = NamedSharding(mesh, spec)
    if process_replicated:
        return place(np.asarray(batch), sharding)
    batch_axis = 1 if leading_steps else 0
    global_shape = list(np.shape(batch))
    global_shape[batch_axis] *= world
    if mesh.shape[DATA_AXIS] % world == 0:
        # Each process's rows are exactly the slice its data-axis devices
        # address — stitch without any host traffic.
        return jax.make_array_from_process_local_data(
            sharding, np.asarray(batch), tuple(global_shape))
    # The data axis does not span every process (e.g. pure TP across
    # hosts, data=1): devices address more batch rows than this host
    # loaded, so materialize the full global batch on every host first
    # (rank-order concat matches the loader's rank striding).
    from jax.experimental import multihost_utils
    gathered = multihost_utils.process_allgather(np.asarray(batch))
    full = np.concatenate(list(gathered), axis=batch_axis)
    assert list(full.shape) == global_shape, (full.shape, global_shape)
    return place(full, sharding)


def _data_axis_spec(spec: P, shape: tuple, mesh: Mesh) -> P:
    """Add the ``data`` axis to a spec on the first dim the TP layout
    leaves free — the ZeRO sharding rule (Xu et al. 2020,
    arXiv:2004.13336), applied to BOTH sides of the ladder:

    - optimizer moments (``opt_state_sharding_tree(wus=True)``, ZeRO-1):
      each DP replica stores 1/data of the moments and updates only its
      slice of the weights;
    - the params themselves (``param_shardings(fsdp=True)``, ZeRO-3):
      1/data per device as the persistent layout, all-gathered
      just-in-time per matmul.

    When both sides opt in (``fsdp=True`` pairs with ``wus=True``), param
    and moment specs come out identical for a given leaf (the update math
    is elementwise across them) because both callers route through this
    one function.  WUS-only mode (``PENROZ_WUS=1`` without FSDP) is the
    deliberate exception: moments are data-sharded here while params keep
    the TP layout — GSPMD inserts the gather/scatter around the update.
    The training step pins its outputs to these layouts via
    ``train_epoch_fn(out_shardings=...)`` — without the pin GSPMD
    propagates whatever the update ran in."""
    if mesh.shape[DATA_AXIS] <= 1 or not shape:
        return spec
    entries = list(spec) + [None] * (len(shape) - len(spec))
    for dim, axis in enumerate(entries):
        # A dim is free if unsharded OR held by a trivial size-1 axis
        # (param_spec emits e.g. P('model', None) even when model=1; the
        # size-1 partition is a no-op, so the moments may claim the dim).
        free = axis is None or (isinstance(axis, str)
                                and mesh.shape[axis] == 1)
        if free and _divides(shape[dim], mesh, DATA_AXIS):
            entries[dim] = DATA_AXIS
            return P(*entries)
    return spec


def opt_state_sharding_tree(opt_state, params: dict, mesh: Mesh,
                            wus: bool = False):
    """Sharding pytree for an optax state matching the param layout.

    optax moment trees (e.g. AdamW's ``mu``/``nu``) mirror the flat param
    dict, so any leaf reached through a dict key that names a parameter (and
    whose shape matches it) inherits that parameter's TP sharding; scalars
    (step counts) and anything unrecognized stay replicated.  Keeping the
    moments sharded like the weights is what makes TP across hosts
    checkpointable — no host ever needs the full optimizer state.

    ``wus=True`` additionally shards every moment leaf over the ``data``
    axis on a dim the param layout leaves free (ZeRO-1 weight-update
    sharding): under pure DP this cuts optimizer memory by the data-axis
    size and distributes the update math, at the cost of an all-gather of
    the fresh params per optimizer step.  Pair it with
    ``train_epoch_fn(out_shardings=(param_shardings, this tree))`` so the
    updated params are pinned back to the parameter layout.
    """
    import jax
    from jax.tree_util import DictKey

    pspecs = {k: param_spec(k, tuple(v.shape), mesh)
              for k, v in params.items()}
    repl = NamedSharding(mesh, P())

    def leaf_sharding(path, leaf):
        shape = tuple(getattr(leaf, "shape", ()))
        for entry in reversed(path):
            if (isinstance(entry, DictKey) and entry.key in pspecs
                    and shape == tuple(params[entry.key].shape)):
                spec = pspecs[entry.key]
                if wus:
                    spec = _data_axis_spec(spec, shape, mesh)
                return NamedSharding(mesh, spec)
        return repl

    return jax.tree_util.tree_map_with_path(leaf_sharding, opt_state)
