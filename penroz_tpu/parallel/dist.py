"""Multi-host process topology.

The reference derives rank/world from torchrun env vars and wraps models in
DistributedDataParallel (ddp.py:13-17, neural_net_model.py:609).  On TPU there
is one process per host and per-chip parallelism lives inside the compiled
program, so the only process-level concepts we need are:

- ``initialize()`` — call ``jax.distributed.initialize`` once per process when
  a multi-host environment is detected (or explicitly requested);
- ``process_index`` / ``process_count`` — which replace RANK / WORLD_SIZE in
  the rank-strided data-loader arithmetic (reference: neural_net_model.py:581-584);
- ``master_proc`` — gates checkpoint writes and progress recording.

Device-level world size (how many chips participate in an allreduce) is the
mesh size, not the process count — see parallel/mesh.py.
"""

from __future__ import annotations

import logging
import os

import jax

log = logging.getLogger(__name__)

_initialized = False


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> bool:
    """Initialize the multi-host JAX runtime (idempotent).

    Auto-detects standard cluster envs (TPU pod metadata, or explicit
    JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID).  Safe to
    call on a single host — it becomes a no-op.
    """
    global _initialized
    if _initialized:
        return True
    coordinator_address = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    num_processes = num_processes or _env_int("JAX_NUM_PROCESSES")
    process_id = process_id if process_id is not None else _env_int("JAX_PROCESS_ID")
    if coordinator_address is None and num_processes is None:
        return False  # single-host; nothing to do
    log.info("Initializing jax.distributed: coordinator=%s procs=%s id=%s",
             coordinator_address, num_processes, process_id)
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    _initialized = True
    reconfig_logging()
    return True


def reconfig_logging(log_dir: str | None = None) -> str | None:
    """Per-process log files for multi-host runs.

    The reference reconfigures per-rank rotating file handlers so DDP
    workers stay distinguishable (ddp.py:87-114); the analog here is one
    process per host, so each process mirrors its records into
    ``<log_dir>/penroz_rank{i}.log`` (``PENROZ_LOG_DIR``, default
    ``logs/``) with the rank baked into the format.  Idempotent —
    re-calling replaces the previously installed handler.  Single-host is
    a no-op (the console handler already tells the whole story).
    Returns the installed path, or None.
    """
    if process_count() <= 1:
        return None
    import logging.handlers
    log_dir = log_dir or os.environ.get("PENROZ_LOG_DIR", "logs")
    os.makedirs(log_dir, exist_ok=True)
    rank = process_index()
    path = os.path.join(log_dir, f"penroz_rank{rank}.log")
    root = logging.getLogger()
    for h in list(root.handlers):
        if getattr(h, "_penroz_rank_handler", False):
            root.removeHandler(h)
            h.close()
    # Handlers present now (before ours goes in) mean an operator configured
    # logging deliberately (basicConfig / dictConfig); their level is
    # authoritative even if it happens to equal the stock WARNING default.
    operator_configured = bool(root.handlers)
    handler = logging.handlers.RotatingFileHandler(
        path, maxBytes=10_000_000, backupCount=3)
    handler.setFormatter(logging.Formatter(
        f"%(asctime)s %(levelname)s [rank{rank}/{process_count()}] "
        f"%(name)s: %(message)s"))
    handler._penroz_rank_handler = True
    root.addHandler(handler)
    # An unconfigured root (NOTSET, or the stock handler-less WARNING
    # default with no explicit PENROZ_LOG_CONFIG) is lowered so training
    # records reach the rank files; an operator-configured level — any
    # pre-existing handler implies one — stays authoritative.
    if root.level == logging.NOTSET or (
            root.level == logging.WARNING and not operator_configured
            and "PENROZ_LOG_CONFIG" not in os.environ):
        root.setLevel(logging.INFO)
    log.info("Per-rank logging for process %d/%d -> %s", rank,
             process_count(), path)
    return path


def _env_int(name: str):
    value = os.environ.get(name)
    return int(value) if value is not None else None


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def master_proc() -> bool:
    return process_index() == 0


def is_distributed() -> bool:
    return process_count() > 1


def barrier(name: str, timeout_s: float = 600.0) -> None:
    """Rendezvous every process at a named point — coordination-service RPC
    only, no device collective (a gloo/ICI group might not exist yet, and
    lazily creating one times out in ~30s if the peer is busy in
    process-local work; the RPC barrier tolerates the full ``timeout_s``).

    ``name`` must be identical on every process AND unique per rendezvous:
    derive it from state that advances in lockstep on all hosts (e.g. a
    counter bumped at request *start*, which stays synchronized even when
    one host errors out mid-run) — a process-local call counter would
    desynchronize permanently after any one-sided failure.
    """
    if process_count() == 1:
        return
    client = None
    try:
        from jax._src import distributed
        client = getattr(distributed.global_state, "client", None)
    except ImportError:
        pass
    if client is None:
        # The private coordination-service client moved or was never
        # initialised.  A silent no-op here would reintroduce the lazy
        # comm-group timeout race this fence exists to prevent — fall back
        # to the public device-collective barrier and say so loudly.
        import logging
        logging.getLogger(__name__).error(
            "dist.barrier(%s): jax coordination-service client unavailable "
            "(private jax._src.distributed API changed?) — falling back to "
            "multihost_utils.sync_global_devices; expect ~30s lazy "
            "comm-group setup on first use", name)
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(f"penroz_{name}")
        return
    client.wait_at_barrier(f"penroz_{name}",
                           timeout_in_ms=int(timeout_s * 1000))


def all_reduce_mean(value: float) -> float:
    """Average a host-local scalar across processes.

    Replaces the reference's ``ddp_all_reduce`` with NCCL ``ReduceOp.AVG``
    (ddp.py:80-85, used for the eval cost at neural_net_model.py:352-354).
    Single-process: identity.
    """
    if process_count() == 1:
        return float(value)
    import numpy as np
    from jax.experimental import multihost_utils
    gathered = multihost_utils.process_allgather(
        np.asarray(value, np.float32))
    return float(np.mean(gathered))
