"""Multi-host process topology.

The reference derives rank/world from torchrun env vars and wraps models in
DistributedDataParallel (ddp.py:13-17, neural_net_model.py:609).  On TPU there
is one process per host and per-chip parallelism lives inside the compiled
program, so the only process-level concepts we need are:

- ``initialize()`` — call ``jax.distributed.initialize`` once per process when
  a multi-host environment is detected (or explicitly requested);
- ``process_index`` / ``process_count`` — which replace RANK / WORLD_SIZE in
  the rank-strided data-loader arithmetic (reference: neural_net_model.py:581-584);
- ``master_proc`` — gates checkpoint writes and progress recording.

Device-level world size (how many chips participate in an allreduce) is the
mesh size, not the process count — see parallel/mesh.py.
"""

from __future__ import annotations

import logging
import os

import jax

log = logging.getLogger(__name__)

_initialized = False


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> bool:
    """Initialize the multi-host JAX runtime (idempotent).

    Auto-detects standard cluster envs (TPU pod metadata, or explicit
    JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID).  Safe to
    call on a single host — it becomes a no-op.
    """
    global _initialized
    if _initialized:
        return True
    coordinator_address = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    num_processes = num_processes or _env_int("JAX_NUM_PROCESSES")
    process_id = process_id if process_id is not None else _env_int("JAX_PROCESS_ID")
    if coordinator_address is None and num_processes is None:
        return False  # single-host; nothing to do
    log.info("Initializing jax.distributed: coordinator=%s procs=%s id=%s",
             coordinator_address, num_processes, process_id)
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    _initialized = True
    return True


def _env_int(name: str):
    value = os.environ.get(name)
    return int(value) if value is not None else None


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def master_proc() -> bool:
    return process_index() == 0


def is_distributed() -> bool:
    return process_count() > 1


def all_reduce_mean(value: float) -> float:
    """Average a host-local scalar across processes.

    Replaces the reference's ``ddp_all_reduce`` with NCCL ``ReduceOp.AVG``
    (ddp.py:80-85, used for the eval cost at neural_net_model.py:352-354).
    Single-process: identity.
    """
    if process_count() == 1:
        return float(value)
    import numpy as np
    from jax.experimental import multihost_utils
    gathered = multihost_utils.process_allgather(
        np.asarray(value, np.float32))
    return float(np.mean(gathered))
