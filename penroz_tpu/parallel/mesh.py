"""Device meshes and sharding rules.

All parallelism is expressed as shardings over a ``jax.sharding.Mesh`` and
compiled by XLA into ICI/DCN collectives — there is no wrapper object doing
gradient allreduce (the reference's DistributedDataParallel + NCCL buckets,
neural_net_model.py:609, ddp.py:80-85).  Axes:

- ``data``      — batch sharding (DP); gradients are averaged by XLA because
                  replicated params + sharded batch force a psum.
- ``model``     — tensor parallelism for weight matrices (TP).
- ``sequence``  — context/sequence parallelism for long sequences (SP).
- ``expert``    — expert parallelism for MoE layers (EP): stacked expert
                  weights shard their leading E dim; the top-k combine is a
                  contraction over E that XLA lowers to a psum on the axis.
- ``pipe``      — pipeline parallelism (PP): stacked transformer-block
                  params shard their leading layer dim; microbatches stream
                  between stages via ppermute (parallel/pipeline.py).

Single-device training uses a trivial 1-device mesh so the code path is
identical everywhere.
"""

from __future__ import annotations

import logging
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

log = logging.getLogger(__name__)

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "sequence"
EXPERT_AXIS = "expert"
PIPE_AXIS = "pipe"


def make_mesh(devices=None, *, data: Optional[int] = None, model: int = 1,
              sequence: int = 1, expert: int = 1, pipe: int = 1,
              pipe_outermost: bool = False) -> Mesh:
    """Build a (data, model, sequence, expert, pipe) mesh over the given
    (default: all) devices.  ``data`` defaults to whatever is left over.

    ``pipe_outermost=True`` makes ``pipe`` the slowest-varying axis of the
    device assignment: stage ``s`` occupies the contiguous global device
    range ``[s·n/P, (s+1)·n/P)``.  ``jax.devices()`` orders devices by
    process, so under multi-host this maps each pipeline stage onto a
    contiguous group of hosts — the stage handoff (``ppermute``) crosses
    DCN once per tick while the within-stage axes stay on ICI.  The
    default (pipe fastest-varying) keeps whole pipelines inside a host:
    right when PP is used for schedule overlap rather than to fit a model
    across hosts.  Axis *names* are identical either way; only the
    device→coordinate assignment differs.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    denom = model * sequence * expert * pipe
    if data is None:
        if n % denom != 0:
            raise ValueError(f"{n} devices not divisible by model={model} × "
                             f"sequence={sequence} × expert={expert} × "
                             f"pipe={pipe}")
        data = n // denom
    if data * denom != n:
        raise ValueError(f"mesh {data}×{model}×{sequence}×{expert}×{pipe} "
                         f"!= {n} devices")
    if pipe_outermost:
        arr = np.moveaxis(
            np.array(devices).reshape(pipe, data, model, sequence, expert),
            0, -1)
    else:
        arr = np.array(devices).reshape(data, model, sequence, expert, pipe)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS, SEQ_AXIS, EXPERT_AXIS,
                      PIPE_AXIS))


def serve_mesh(model: int = 1, devices=None) -> Mesh:
    """Serving mesh for ONE decode engine: ``model`` tensor-parallel
    devices, every other axis trivial.  Data parallelism across engines is
    the router's job (serve/router.py) — replicas own disjoint meshes
    rather than sharing a ``data`` axis, so one replica's crash recovery
    never invalidates another's compiled programs.  Built over the FIRST
    ``model`` local devices so a 1-wide mesh on a multi-device host stays
    on device 0 exactly like the unmeshed engine (the token-parity
    guarantee the CPU suite proves rides on this)."""
    devices = list(devices if devices is not None else jax.local_devices())
    if model < 1 or model > len(devices):
        raise ValueError(f"serve mesh needs 1 <= model <= {len(devices)} "
                         f"local devices (got model={model})")
    return make_mesh(devices[:model], model=model)


def serve_stage_meshes(stages: int, model: int = 1,
                       devices=None) -> list[Mesh]:
    """Per-stage serving meshes for ONE pipeline group
    (PENROZ_SERVE_PIPE_STAGES × PENROZ_SERVE_MESH_MODEL): stage ``s``
    owns the contiguous local device range ``[s·model, (s+1)·model)``
    as its own ``model``-wide TP mesh.  Disjoint meshes rather than one
    ``pipe``-axis mesh because serving stages are MPMD — each stage
    compiles and dispatches its own program and the scheduler hands
    activations across (PAPERS.md #3), so a stage recompile or crash
    never invalidates a sibling's programs (same isolation argument as
    router replicas).  When the host has fewer than ``stages × model``
    devices every stage collapses onto the first ``model`` devices —
    placement degenerates but the schedule, partition, and numerics are
    identical (the CPU parity suite rides this)."""
    devices = list(devices if devices is not None else jax.local_devices())
    stages = int(stages)
    if stages < 1 or model < 1:
        raise ValueError(f"need stages >= 1 and model >= 1 "
                         f"(got {stages}, {model})")
    if len(devices) < stages * model:
        return [serve_mesh(model=model, devices=devices)] * stages
    return [make_mesh(devices[s * model:(s + 1) * model], model=model)
            for s in range(stages)]


def batch_sharding(mesh: Mesh, batch_ndim: int = 2) -> NamedSharding:
    """Shard the leading batch dim over ``data``.  For sequence sharding use
    ``parallel.sharding.shard_batch`` (spec-based, handles both axes)."""
    spec = [DATA_AXIS] + [None] * (batch_ndim - 1)
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def local_data_size(mesh: Mesh) -> int:
    """Number of devices along the data axis."""
    return mesh.shape[DATA_AXIS]
