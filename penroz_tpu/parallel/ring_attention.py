"""Ring attention: causal self-attention with the sequence dimension sharded
over the mesh's ``sequence`` axis.

Each device keeps its local query block resident and processes K/V blocks as
they rotate around the ring via ``lax.ppermute`` (XLA lowers this onto ICI
neighbor links), carrying online-softmax statistics — the distributed
analogue of the flash-attention inner loop.  Peak memory per device is
O(T/n · T/n) for scores and O(T/n · D) for accumulators, enabling context
lengths that cannot fit on one chip.

The reference has no long-context support at all (SURVEY.md §5: sequence
length bounded by block_size, full causal attention only), so this module is
an extension point, not a parity item.

Causal scheduling note: block j of K/V only contributes to query block i when
j <= i, so later ring steps are fully masked for low-index devices.  We still
rotate all n steps (uniform SPMD program) but skip the masked compute via
``lax.cond``-free arithmetic — the masked contribution is zeros and XLA's
predication keeps it cheap relative to the collective itself.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from penroz_tpu.parallel.mesh import SEQ_AXIS

_NEG_INF = -1e30


def _ring_attention_local(q, k, v, *, axis_name: str, causal: bool,
                          window=None, alibi=None, scale=None,
                          softcap=None):
    """Per-shard body. q/k/v: (B, H, T_local, D) — the local blocks.

    ``alibi``: per-query-head slopes — the ring already tracks GLOBAL
    query/key positions for its causal masks, so the linear position
    bias ``slope·(k − q)`` drops straight onto each rotation step's
    score block (heads are never sharded by the ring, so the slope
    table stays static per device)."""
    B, Hq, Tl, D = q.shape
    Hkv = k.shape[1]
    group = Hq // Hkv
    n = jax.lax.axis_size(axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    scale = float(scale) if scale is not None else 1.0 / (D ** 0.5)

    qg = q.reshape(B, Hkv, group, Tl, D)
    q_pos = my_idx * Tl + jnp.arange(Tl, dtype=jnp.int32)
    slopes_hg = (jnp.asarray(alibi, jnp.float32).reshape(Hkv, group)
                 if alibi is not None else None)
    # A static window bounds how many ring steps can contribute: step i
    # brings the K block i hops back, and blocks more than
    # ceil((window-1)/Tl) hops back lie entirely below every local row's
    # band (on every device — steps beyond the bound are acausal for the
    # low-index devices anyway), so the rotation stops there.  Uniform
    # SPMD: the count is the same on all devices.
    num_steps = n
    if window is not None:
        num_steps = min(n, -(-(window - 1) // Tl) + 1)

    def step(i, carry):
        m, l, acc, k_cur, v_cur = carry
        # k_cur originated on device (my_idx - i) mod n after i rotations.
        src = (my_idx - i) % n
        k_pos = src * Tl + jnp.arange(Tl, dtype=jnp.int32)
        s = jnp.einsum("bhgtd,bhsd->bhgts", qg, k_cur,
                       preferred_element_type=jnp.float32) * scale
        if softcap is not None:
            # Gemma-2 score capping: applied per rotation step BEFORE the
            # online-softmax update — tanh is elementwise, so capping
            # block-by-block equals capping the full score matrix.
            s = softcap * jnp.tanh(s / softcap)
        if slopes_hg is not None:
            rel = (k_pos[None, :] - q_pos[:, None]).astype(jnp.float32)
            s = s + (slopes_hg[:, :, None, None]
                     * rel[None, None])[None]
        if causal:
            mask = k_pos[None, :] <= q_pos[:, None]
            if window is not None:
                # sliding band; ring steps whose K block lies fully outside
                # a row's window leave that row at m == _NEG_INF, and the
                # online rescaling (alpha -> 0 once a live block arrives —
                # each row's own position is always in-band) cancels the
                # uniform exp(0) contribution those steps would add.
                mask &= k_pos[None, :] > q_pos[:, None] - window
            s = jnp.where(mask[None, None, None], s, _NEG_INF)
        s_max = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, s_max)
        # Guard fully-masked rows: keep them at -inf without producing NaNs.
        safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - safe_m[..., None])
        p = jnp.where(jnp.isfinite(m_new)[..., None], p, 0.0)
        alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bhgts,bhsd->bhgtd", p.astype(v_cur.dtype), v_cur,
            preferred_element_type=jnp.float32)
        # Rotate K/V one hop around the ring: device d sends to d+1.
        perm = [(d, (d + 1) % n) for d in range(n)]
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return m_new, l_new, acc_new, k_next, v_next

    # Derive the carry inits from qg so they inherit EVERY manual axis the
    # inputs vary over — under the GPipe schedule that set is
    # {pipe, data, sequence}, not just the ring axis, and a fixed pcast
    # list would mismatch the loop-carry types there.
    m0 = jnp.full_like(qg[..., 0], -jnp.inf, dtype=jnp.float32)
    l0 = jnp.zeros_like(qg[..., 0], dtype=jnp.float32)
    acc0 = jnp.zeros_like(qg, dtype=jnp.float32)
    m, l, acc, _, _ = jax.lax.fori_loop(0, num_steps, step,
                                        (m0, l0, acc0, k, v))

    l = jnp.where(l == 0.0, 1.0, l)
    out = (acc / l[..., None]).astype(q.dtype)
    return out.reshape(B, Hq, Tl, D)


def ring_attention_manual(q, k, v, *, axis_name: str = SEQ_AXIS,
                          causal: bool = True, window=None, alibi=None,
                          scale=None, softcap=None):
    """Ring attention for callers ALREADY inside a manual region binding
    ``axis_name`` (e.g. the GPipe schedule's shard_map with the sequence
    axis manual) — same math as :func:`ring_attention`, minus the
    shard_map wrapper (nesting one inside another is not possible).
    q/k/v: per-shard (B, H, T_local, D) blocks."""
    if window is not None and not causal:
        raise ValueError("ring_attention window requires causal=True")
    return _ring_attention_local(q, k, v, axis_name=axis_name,
                                 causal=causal,
                                 window=int(window) if window is not None
                                 else None, alibi=alibi, scale=scale,
                                 softcap=softcap)


def ring_attention(q, k, v, mesh: Mesh, *, causal: bool = True,
                   axis_name: str = SEQ_AXIS, window=None, alibi=None,
                   scale=None, softcap=None):
    """Sequence-parallel attention over ``mesh``'s sequence axis.

    q: (B, Hq, T, D); k/v: (B, Hkv, T, D), all sharded (or shardable) on the
    T dimension.  Returns attention output with the same sharding.
    ``window``: sliding-window width — query t attends keys in
    ``(t - window, t]`` (same band as the flash kernels); requires
    ``causal=True`` (a bidirectional band has no defined semantics here).
    """
    if window is not None and not causal:
        raise ValueError("ring_attention window requires causal=True")
    spec = P(None, None, axis_name, None)
    body = functools.partial(_ring_attention_local, axis_name=axis_name,
                             causal=causal,
                             window=int(window) if window is not None
                             else None, alibi=alibi, scale=scale,
                             softcap=softcap)
    fn = jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec)
    return fn(q, k, v)
