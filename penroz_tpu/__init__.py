"""penroz_tpu — a TPU-native (JAX/XLA/Pallas) neural-network model service.

A ground-up re-design of the capabilities of
``derinworks/penr-oz-neural-network-v3-torch-ddp`` (see SURVEY.md) for TPU:

- JSON layer/optimizer DSL compiled once into a functional module tree whose
  parameter names mirror the reference's ``state_dict`` keys
  (reference: mappers.py:19-99).
- ``jax.value_and_grad`` + optax training under ``jax.jit`` with sharding over a
  ``jax.sharding.Mesh`` instead of subprocess DDP (reference: ddp.py:38-85).
- Preallocated functional KV cache with optional int8 TurboQuant
  (reference: kv_cache.py) threaded through a jitted decode step.
- An aiohttp web service exposing the same 15-route REST surface
  (reference: main.py).
"""

__version__ = "0.1.0"
