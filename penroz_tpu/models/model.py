"""Model runtime: compiled architectures and the NeuralNetworkModel facade.

TPU-native re-design of the reference's ``neural_net_model.py``:

- ``CompiledArch`` — a layer DSL compiled once into a bound functional module
  tree with cached jitted programs: forward (all intermediate activations +
  CE/MSE cost, reference :250-271), a grad-accumulating train epoch
  (reference :552-722 hot loop → one ``lax.scan`` under ``jax.jit``), fused
  decode+sample steps over a preallocated KV cache (reference :360-406), and
  an instrumented stats pass (reference :735-777) using an explicit
  activation-delta VJP instead of ``retain_grad``.
- ``NeuralNetworkModel`` — create/train/evaluate/generate/serialize/
  deserialize/delete/from_huggingface lifecycle with the same progress/
  avg-cost/stats/status bookkeeping and /dev/shm write-through checkpoints
  (reference :98-174, 516-722).

Decode is chunked and pipelined: up to ``PENROZ_DECODE_CHUNK`` (default 128)
fused decode+sample steps run per dispatch via ``lax.scan`` with power-of-two
chunk sizes (tails round up to the compiled ceiling and discard the
overshoot), and the next chunk is dispatched before the previous chunk's
tokens are transferred to the host (the last sampled token stays on-device),
bounding per-token dispatch overhead, compile variants, and host round-trips.
"""

from __future__ import annotations

import atexit
import contextlib
import json
import logging
import os
import threading
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from penroz_tpu.models import dsl
from penroz_tpu.models.dsl import Mapper
from penroz_tpu.ops import kv_cache as KV
from penroz_tpu.ops import losses
from penroz_tpu.ops import modules as M
from penroz_tpu.parallel import dist
from penroz_tpu.parallel import mesh as mesh_lib
from penroz_tpu.parallel import sharding as sharding_lib
from penroz_tpu.utils import checkpoint, profiling, stats as stats_lib

log = logging.getLogger(__name__)

DECODE_CHUNK_ENV = "PENROZ_DECODE_CHUNK"

# Per-model count of /train/ requests this process has started — feeds the
# train-end barrier id, so it must advance in lockstep on every host and
# survive the per-request model deserialization (see train_model).
_TRAIN_SEQ: dict = {}

# Decode-priority dispatch: /generate/ handlers wrap their device work in
# decode_priority(); the training loop consults decode_pending() between
# epochs and briefly yields the chip so queued decodes slip in ahead of
# the next epoch program (the reference sidesteps the contention by
# forking training into separate processes/devices, main.py:461-464).
_DECODE_PENDING = 0
_DECODE_LOCK = threading.Lock()

# Live training-worker subprocesses by model id (PENROZ_TRAIN_WORKER=1):
# observability + test hook; entries removed as workers exit.  The atexit
# sweep covers clean parent shutdown; the worker also self-terminates on
# parent death (train_worker._watch_parent) so a SIGKILLed server never
# leaves an orphan racing checkpoint writes against its replacement.
_TRAIN_WORKERS: dict = {}


def _kill_train_workers():
    for proc in list(_TRAIN_WORKERS.values()):
        if proc.poll() is None:
            proc.kill()


atexit.register(_kill_train_workers)


@contextlib.contextmanager
def decode_priority():
    """Mark a decode request in flight for the duration of its device work."""
    global _DECODE_PENDING
    with _DECODE_LOCK:
        _DECODE_PENDING += 1
    try:
        yield
    finally:
        with _DECODE_LOCK:
            _DECODE_PENDING -= 1


def decode_pending() -> int:
    return _DECODE_PENDING


def _yield_to_decodes():
    """Between-epoch decode-priority window (single-process only: a
    one-sided pause under a multi-host mesh would just stall the peers'
    collectives).  Caps at PENROZ_DECODE_PRIORITY_MS (default 1000; 0
    disables) so a decode storm cannot starve training."""
    if dist.process_count() > 1:
        return
    cap_ms = float(os.environ.get("PENROZ_DECODE_PRIORITY_MS", "1000"))
    if cap_ms <= 0 or decode_pending() <= 0:
        return
    deadline = time.monotonic() + cap_ms / 1000.0
    while decode_pending() > 0 and time.monotonic() < deadline:
        time.sleep(0.005)


def _sharded_zero_grads(params: dict) -> dict:
    """fp32 zero-gradient tree laid out like ``params`` — shard-local
    allocation via ``make_array_from_callback``, so a ZeRO-3/TP-sharded
    model never materializes its full unsharded gradient tree on one
    device (the fused epoch's zeros are born inside jit under GSPMD;
    this is the eager-side equivalent for the micro-step driver)."""
    out = {}
    for k, v in params.items():
        sharding = getattr(v, "sharding", None)
        if sharding is None:
            out[k] = jnp.zeros(v.shape, jnp.float32)
            continue

        def shard_zeros(idx, shape=v.shape):
            dims = tuple((sl.stop if sl.stop is not None else d)
                         - (sl.start or 0) for sl, d in zip(idx, shape))
            return np.zeros(dims, np.float32)

        out[k] = jax.make_array_from_callback(v.shape, sharding,
                                              shard_zeros)
    return out


def run_microstepped_epoch(micro_fn, finalize_fn, params, opt_state,
                           buffers, xs, ys, rng, num_steps: int,
                           yield_cb=None):
    """Drive one epoch through ``CompiledArch.train_micro_fns`` programs:
    one device dispatch per micro-step with a decode-priority window
    (``yield_cb``, default :func:`_yield_to_decodes`) opened between
    them.  Shared by the /train/ path and bench.py's background trainer
    so the TTFT benchmark measures exactly the production policy."""
    if yield_cb is None:
        yield_cb = _yield_to_decodes
    grads = _sharded_zero_grads(params)
    cost = jnp.zeros((), jnp.float32)
    bufs = buffers
    for i in range(num_steps):
        if i:
            yield_cb()
        bufs, grads, cost = micro_fn(params, bufs, grads, cost,
                                     xs[i], ys[i], rng, i)
    return finalize_fn(params, opt_state, grads, bufs, cost)


def _check_pipe_composition(pipe: int, seq: int) -> None:
    """The GPipe schedule composes with data parallelism (its microbatch
    spec shards rows over ``data``), with tensor parallelism, AND with
    expert parallelism: stacked leaves carry P(pipe, <tp/ep>, …) specs and
    the stage body leaves the model/expert axes GSPMD-automatic, so XLA
    inserts the TP collectives and the MoE dispatch/combine psums inside
    each stage (EP×pipe parity: costs and router fractions match the
    sequential run to fp tolerance — test_train_model_pipe_composes_with_
    expert_parallel) — and with sequence parallelism in BOTH modes: the
    schedule's shard_map binds the sequence axis as a manual axis and the
    attention modules run the ring or Ulysses body on it directly
    (Ctx.sp_manual_axis; their shard_map wrappers cannot nest, the manual
    entry points skip them).  Every mesh axis now composes with pipe;
    the per-model constraints (attention dropout, bf16 storage) are
    validated at layout entry.  Kept as the shared seam between the
    single- and multi-host mesh builders."""
    del pipe, seq  # every composition valid at mesh level


def _chunk_budget() -> int:
    """Decode steps fused per dispatch (PENROZ_DECODE_CHUNK, default 128)."""
    return max(1, int(os.environ.get(DECODE_CHUNK_ENV, "128")))


def _decode_chunk_size(remaining: int, cap: int) -> int:
    """Pow-2 ceiling of the remaining tail, clipped by ``cap`` (a non-pow-2
    cap floors back down) — the bounded-program-set chunk policy shared by
    the single-sequence and batched decode loops."""
    chunk = min(1 << (remaining - 1).bit_length(), cap)
    if chunk & (chunk - 1):
        chunk = 1 << (chunk.bit_length() - 1)
    return chunk


def _max_generate_batch() -> int:
    """Server-side /generate_batch/ row cap (PENROZ_MAX_GENERATE_BATCH)."""
    try:
        return max(1, int(os.environ.get("PENROZ_MAX_GENERATE_BATCH", "64")))
    except ValueError:
        log.warning("Unparseable PENROZ_MAX_GENERATE_BATCH=%r; "
                    "using default 64",
                    os.environ.get("PENROZ_MAX_GENERATE_BATCH"))
        return 64


def validate_batch_generation(prompts: list[list[int]], block_size: int,
                              max_new_tokens: int) -> None:
    """Reject batched-generation requests the ragged path cannot serve
    losslessly: the batched decode has no overflow crop/re-prefill, so any
    row with ``prompt_len + max_new_tokens > block_size`` would be silently
    truncated — name the offending rows in a ValueError (HTTP 400) instead.
    Shared by ``generate_tokens_batched`` and the continuous-batching route
    so both surfaces enforce identical contracts."""
    if not prompts or any(not p for p in prompts):
        raise ValueError("each batched prompt needs at least one token")
    max_batch = _max_generate_batch()
    if len(prompts) > max_batch:
        raise ValueError(
            f"batched generation accepts at most {max_batch} prompts "
            f"(got {len(prompts)}; raise PENROZ_MAX_GENERATE_BATCH to "
            f"override) — each row allocates a block_size KV cache per "
            f"layer")
    over = [(i, len(p)) for i, p in enumerate(prompts)
            if len(p) + max_new_tokens > block_size]
    if over:
        detail = ", ".join(f"row {i} (prompt {n} tokens)"
                           for i, n in over[:8])
        more = f" and {len(over) - 8} more" if len(over) > 8 else ""
        raise ValueError(
            f"batched generation needs prompt_len + max_new_tokens "
            f"({max_new_tokens}) <= block_size ({block_size}) for every "
            f"row; overflowing: {detail}{more} — the batched path has no "
            f"overflow crop/re-prefill, so these rows would be silently "
            f"truncated; crop prompts first")


def _resolve_device(device: Optional[str]):
    """Map an API device string to a jax.Device (None = leave placement).

    Unknown strings raise ValueError (→ HTTP 400) — silently falling back
    to default placement would train on the wrong device for a typo like
    ``"tpuu"``."""
    if device is None:
        return None
    device = device.lower()
    # local_devices, not devices: under multi-host the global list leads
    # with process 0's devices, and device_put onto another process's
    # device is an error ("Cannot copy array to non-addressable device").
    if device == "cpu":
        return jax.local_devices(backend="cpu")[0]
    if device in ("tpu", "cuda", "gpu", "axon", "accelerator"):
        for backend in ("tpu", "axon", "gpu"):
            try:
                return jax.local_devices(backend=backend)[0]
            except RuntimeError:
                continue
        return jax.local_devices()[0]
    raise ValueError(f"Unknown device {device!r}; expected 'cpu', 'tpu', "
                     f"'gpu', 'cuda', 'axon' or 'accelerator'")


class CompiledArch:
    """A layer DSL compiled once; jitted programs cached per configuration.

    Shared across model instances with the same DSL (the reference rebuilds
    module trees per request; here jit caches amortize across requests).
    """

    _cache: dict[str, "CompiledArch"] = {}

    @classmethod
    def get(cls, layers: list[dict]) -> "CompiledArch":
        key = json.dumps(layers, sort_keys=True, default=str)
        arch = cls._cache.get(key)
        if arch is None:
            arch = cls._cache[key] = cls(layers)
        return arch

    def __init__(self, layers: list[dict]):
        self.layers_dsl = layers
        self.mods = dsl.build_modules(layers)
        self.algos = [dsl.layer_algo(entry) for entry in layers]
        self.classification = any(isinstance(m, M.Softmax) for m in self.mods)
        self.param_order: list[str] = []
        for mod in self.mods:
            for sub in mod.walk():
                for name in sub.param_shapes():
                    self.param_order.append(sub.key(name))
        self.attn_layers: list[M.CausalSelfAttention] = []
        self.ssm_layers: list[M.GatedSSM] = []
        self._index_attention()
        self._jit_cache: dict = {}

    # -- structure ----------------------------------------------------------

    def _index_attention(self):
        """Assign KV-cache slots and infer head dims from the preceding fused
        QKV projection (reference derives head dim the same way:
        neural_net_layers.py:61-75).  ``ssm`` blocks get their own slot
        sequence — their state lives in the recurrent child of the KV
        pytree, indexed independently of the attention pools."""

        def visit(mod):
            if isinstance(mod, M.CausalSelfAttention):
                mod.layer_idx = len(self.attn_layers)
                self.attn_layers.append(mod)
            if isinstance(mod, M.GatedSSM):
                mod.layer_idx = len(self.ssm_layers)
                self.ssm_layers.append(mod)
            if isinstance(mod, M.Sequential):
                prev = None
                for child in mod.layers:
                    if (isinstance(child, M.CausalSelfAttention)
                            and child.head_dim is None
                            and isinstance(prev, M.Linear)):
                        child.head_dim = prev.out_features // (
                            child.num_heads + 2 * child.num_kv_heads)
                    visit(child)
                    prev = child
            else:
                for _, child in mod.children():
                    visit(child)

        for mod in self.mods:
            visit(mod)

    @property
    def kv_specs(self) -> list[tuple[int, int]]:
        """Per-attention-layer (num_kv_heads, head_dim) for KV allocation."""
        specs = []
        for mod in self.attn_layers:
            if mod.head_dim is None:
                raise ValueError("Attention head_dim could not be inferred; "
                                 "precede attention with a fused QKV linear "
                                 "or pass head_dim explicitly")
            specs.append((mod.num_kv_heads, mod.head_dim))
        return specs

    @property
    def ssm_specs(self) -> list[tuple[int, int, int]]:
        """Per-``ssm``-layer (num_heads, head_dim, value_dim) for the
        fixed-size recurrent state (ops/ssm.py::SSMState.create)."""
        return [(mod.num_heads, mod.head_dim, mod.value_dim)
                for mod in self.ssm_layers]

    def jit_program_counts(self) -> dict[str, int]:
        """Live jitted-program count per function family — cache keys are
        tuples whose first element names the family (``"sched_step"``,
        ``"mixed_step"``, …).  The ``penroz_jit_programs`` gauge reads
        this at scrape time: shape bucketing exists to keep these counts
        bounded, and the gauge is where churn becomes visible."""
        counts: dict[str, int] = {}
        for key in self._jit_cache:
            fam = key[0] if isinstance(key, tuple) and key else str(key)
            counts[str(fam)] = counts.get(str(fam), 0) + 1
        return counts

    # -- forward ------------------------------------------------------------

    def _apply(self, params, buffers, x, *, training=False, rng=None, kv=None,
               pos_offset=None, skip_softmax=False, compute_dtype=None,
               sp_mesh=None, platform=None, sp_mode="ring", ep_mesh=None,
               lora=None, lora_idx=None, ragged_descs=None, ragged_rows=None):
        ctx = M.Ctx(params, buffers, training=training, rng=rng, kv=kv,
                    pos_offset=pos_offset, compute_dtype=compute_dtype,
                    sp_mesh=sp_mesh, platform=platform, sp_mode=sp_mode,
                    ep_mesh=ep_mesh, lora=lora, lora_idx=lora_idx,
                    ragged_descs=ragged_descs, ragged_rows=ragged_rows)
        acts = []
        h = x
        logits = None
        for mod in self.mods:
            if isinstance(mod, M.Softmax):
                if logits is None:
                    logits = h  # pre-softmax activation feeds the CE cost
                if skip_softmax:
                    continue
            h = mod.apply(h, ctx)
            acts.append(h)
        if logits is None:
            logits = h
        return acts, logits, ctx

    def _cost_from_logits(self, logits, targets, platform=None):
        """CE for classification stacks, MSE otherwise (reference forward
        cost semantics: neural_net_model.py:250-271).

        CE streams chunks through a fused custom-VJP loss (Pallas kernels on
        TPU) instead of upcasting the full (B, T, V) logits to fp32
        (ops/losses.py)."""
        if self.classification:
            return losses.fused_cross_entropy_mean(logits, targets,
                                                   platform=platform)
        return jnp.mean((logits.astype(jnp.float32)
                         - targets.astype(jnp.float32)) ** 2)

    def forward(self, params, buffers, tokens, targets=None, *,
                training=False, rng=None, kv=None, pos_offset=None,
                skip_softmax=False, compute_dtype=None, sp_mesh=None,
                platform=None, sp_mode="ring", ep_mesh=None, lora=None,
                lora_idx=None, ragged_descs=None, ragged_rows=None):
        """Full forward collecting every top-level activation.

        Returns ``(activations, cost, buffer_updates, new_kv)``; ``cost`` is
        None without targets, ``new_kv`` is the advanced KV state (or None).
        ``lora``/``lora_idx`` carry the stacked mixed-adapter pack + per-row
        slot indices (models/lora.py) into the module Ctx; single-adapter
        application instead binds ``lora_A/B/scale`` keys into ``params``.
        ``ragged_descs``/``ragged_rows`` (paged caches only) switch
        attention to the packed mixed-batch path: ``tokens`` is (1, Tp)
        packed, ``pos_offset`` the (1, Tp) per-token positions, and
        ``new_kv`` advances per-descriptor instead of by ``T``.
        """
        acts, logits, ctx = self._apply(
            params, buffers, tokens, training=training, rng=rng, kv=kv,
            pos_offset=pos_offset, skip_softmax=skip_softmax,
            compute_dtype=compute_dtype, sp_mesh=sp_mesh, platform=platform,
            sp_mode=sp_mode, ep_mesh=ep_mesh, lora=lora, lora_idx=lora_idx,
            ragged_descs=ragged_descs, ragged_rows=ragged_rows)
        cost = (self._cost_from_logits(logits, targets, platform=platform)
                if targets is not None else None)
        if cost is not None and ctx.aux_losses:
            # Auxiliary training losses (MoE load balancing) ride the same
            # scalar so value_and_grad backpropagates them with the task loss.
            cost = cost + sum(ctx.aux_losses)
        if ctx.kv is None:
            new_kv = None
        elif ragged_descs is not None:
            new_kv = ctx.kv.with_lengths(
                ctx.kv.lengths_after_packed(ragged_descs))
        else:
            new_kv = ctx.kv.advanced(tokens.shape[-1])
        return acts, cost, ctx.buffer_updates, new_kv

    def jit_forward(self, params, buffers, tokens, targets=None, *,
                    skip_softmax=False, compute_dtype=None, platform=None):
        """Jitted inference forward (cached per static configuration)."""
        key = ("fwd", targets is not None, skip_softmax, str(compute_dtype),
               platform)
        fn = self._jit_cache.get(key)
        if fn is None:
            if targets is None:
                def fwd(p, b, t):
                    return self.forward(p, b, t, None,
                                        skip_softmax=skip_softmax,
                                        compute_dtype=compute_dtype,
                                        platform=platform)
            else:
                def fwd(p, b, t, y):
                    return self.forward(p, b, t, y,
                                        skip_softmax=skip_softmax,
                                        compute_dtype=compute_dtype,
                                        platform=platform)
            fn = self._jit_cache[key] = jax.jit(fwd)
        if targets is None:
            return fn(params, buffers, tokens)
        return fn(params, buffers, tokens, targets)

    def eval_cost_fn(self, params, buffers, tokens, targets, *,
                     platform=None, sp_mesh=None, sp_mode="ring",
                     ep_mesh=None):
        """Cost-only jitted forward for ``/evaluate/``.

        Returning just the scalar lets XLA dead-code-eliminate every
        intermediate activation that :meth:`jit_forward` would materialize
        as an output; with mesh-placed params and a data-sharded batch the
        same program evaluates across every chip (the reference evaluates
        DDP-sharded across all workers, neural_net_model.py:319-354 — "no
        grad" here is simply not calling ``value_and_grad``).  ``sp_mesh``
        enables the same ring/all-to-all sequence-parallel attention the
        training epoch uses, for sequence-sharded eval batches.
        """
        key = ("evalcost", platform, sp_mesh, sp_mode, ep_mesh)
        fn = self._jit_cache.get(key)
        if fn is None:
            def fwd(p, b, t, y):
                _, cost, _, _ = self.forward(p, b, t, y, skip_softmax=True,
                                             sp_mesh=sp_mesh,
                                             sp_mode=sp_mode,
                                             platform=platform,
                                             ep_mesh=ep_mesh)
                return cost
            fn = self._jit_cache[key] = jax.jit(fwd)
        return fn(params, buffers, tokens, targets)

    # -- training -----------------------------------------------------------

    def train_epoch_fn(self, optimizer_config: dict, num_steps: int,
                       remat: bool = False, compute_dtype=None, sp_mesh=None,
                       platform=None, with_ratios: bool = True,
                       out_shardings=None, sp_mode: str = "ring",
                       pipe_cfg=None, pipe_remat: str = "block",
                       ep_mesh=None):
        """One jitted epoch: ``num_steps`` grad-accumulation micro-steps via
        ``lax.scan`` then a single optax update (reference hot loop:
        neural_net_model.py:614-677; sync deferred to the final micro-step is
        implicit here — XLA schedules gradient collectives once).

        Returns ``fn(params, opt_state, buffers, xs, ys, rng) ->
        (params, opt_state, buffers, cost, weight_update_ratios)`` where
        ``xs``/``ys`` are ``(num_steps, B, T)`` token batches.

        ``with_ratios=False`` compiles a variant that skips the per-weight
        update-ratio stds (two full passes over the parameters) — the
        reference only needs them on progress-sampled epochs
        (neural_net_model.py:686-700), so the hot loop shouldn't pay them
        every step; the skipping variant returns ``ratios=None``.

        ``out_shardings=(param_shardings, opt_shardings)`` pins the updated
        params/optimizer state to the given layouts via
        ``with_sharding_constraint``.  Without the pin, GSPMD propagates
        whatever layout the update math ran in into the outputs — under
        ZeRO-1 weight-update sharding (``PENROZ_WUS=1``) that would leave
        the fresh params data-sharded instead of forcing the all-gather
        back to the parameter layout, changing their aval between epochs
        (recompile every call) and leaving cross-host-sharded params behind
        after training.
        """
        # PENROZ_REMAT=1 and pipe_remat='block' compose rather than exclude:
        # the whole-loss checkpoint discards pre/post-block residuals but
        # its backward REPLAYS the forward, and without per-block remat that
        # replay materializes every (layer, tick) block internal at once —
        # the exact residency the OOM lever exists to avoid.  Stacked, the
        # blocks run once more (fwd, outer replay, per-block replay) in
        # exchange for the bound holding everywhere.
        shard_key = None
        if out_shardings is not None:
            shard_key = (tuple(sorted(out_shardings[0].items())),
                         tuple(jax.tree.leaves(out_shardings[1])))
        key = ("epoch", json.dumps(optimizer_config, sort_keys=True),
               int(num_steps), bool(remat), str(compute_dtype), sp_mesh,
               platform, bool(with_ratios), shard_key, sp_mode,
               (pipe_cfg[0], pipe_cfg[1], pipe_cfg[2], pipe_cfg[3])
               if pipe_cfg else None,
               pipe_remat if pipe_cfg is not None else None, ep_mesh)
        fn = self._jit_cache.get(key)
        if fn is not None:
            return fn

        optimizer = dsl.build_optimizer(optimizer_config)

        if pipe_cfg is None:
            def loss_fn(params, buffers, x, y, rng):
                _, cost, buf_upd, _ = self.forward(
                    params, buffers, x, y, training=True, rng=rng,
                    skip_softmax=True, compute_dtype=compute_dtype,
                    sp_mesh=sp_mesh, platform=platform, sp_mode=sp_mode,
                    ep_mesh=ep_mesh)
                return cost, buf_upd
        else:
            loss_fn = self._pipelined_loss_fn(pipe_cfg, compute_dtype,
                                              platform,
                                              pipe_remat=pipe_remat,
                                              sp_mode=sp_mode)

        if remat:
            loss_fn = jax.checkpoint(loss_fn)
        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

        def epoch(params, opt_state, buffers, xs, ys, rng):
            # Cast params to the compute dtype ONCE per epoch, outside the
            # micro-step scan — the cast's VJP is an upcast of the incoming
            # (bf16) gradients, so accumulating them in fp32 below yields
            # bit-identical grads to casting inside every micro-step while
            # saving num_steps-1 full passes over the parameters.
            if compute_dtype is not None:
                params_c = {
                    k: v.astype(compute_dtype)
                    if jnp.issubdtype(v.dtype, jnp.floating) else v
                    for k, v in params.items()}
            else:
                params_c = params

            def micro(carry, batch):
                grads_acc, bufs, cost_acc, i = carry
                x, y = batch
                (cost, upd), grads = grad_fn(params_c, bufs, x, y,
                                             jax.random.fold_in(rng, i))
                bufs = {**bufs, **upd}
                grads_acc = jax.tree.map(
                    lambda a, g: a + g.astype(jnp.float32), grads_acc, grads)
                return (grads_acc, bufs, cost_acc + cost, i + 1), None

            zeros = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32),
                                 params)
            init = (zeros, buffers, jnp.zeros((), jnp.float32), 0)
            (grads, new_buffers, cost_sum, _), _ = jax.lax.scan(
                micro, init, (xs, ys))
            return finalize(params, opt_state, grads, new_buffers, cost_sum)

        finalize = self._finalize_update_fn(optimizer, num_steps,
                                            out_shardings, with_ratios,
                                            pipe_cfg)
        fn = jax.jit(epoch, donate_argnums=(0, 1))
        self._jit_cache[key] = fn
        return fn

    def _finalize_update_fn(self, optimizer, num_steps: int, out_shardings,
                            with_ratios: bool, pipe_cfg):
        """Pure epoch tail shared by the fused epoch program and the
        micro-chunked decode-priority path: average the accumulated
        grads, apply the optax update (+sharding pins), derive the
        update-ratio stds."""

        def finalize(params, opt_state, grads, new_buffers, cost_sum):
            inv = 1.0 / num_steps
            cost = cost_sum * inv
            grads = jax.tree.map(
                lambda g, p: (g * inv).astype(p.dtype), grads, params)
            updates, new_opt_state = optimizer.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            if out_shardings is not None:
                new_params = jax.lax.with_sharding_constraint(
                    new_params, out_shardings[0])
                new_opt_state = jax.lax.with_sharding_constraint(
                    new_opt_state, out_shardings[1])
            if not with_ratios:
                return new_params, new_opt_state, new_buffers, cost, None
            # per-weight update ratio std(Δw)/std(w) (reference :686-700)

            def ratio(dw_src, w_src, stacked=False):
                std = (jax.vmap(lambda a: jnp.std(a.astype(jnp.float32)))
                       if stacked else
                       lambda a: jnp.std(a.astype(jnp.float32)))
                dw, denom = std(dw_src), std(w_src)
                return jnp.where(denom > 0, dw / (denom + 1e-12), 0.0)

            if pipe_cfg is None:
                ratio_map = {k: ratio(new_params[k] - params[k], params[k])
                             for k in self.param_order}
            else:
                # Stacked leaves yield one std per layer (vmap over the
                # leading L dim) so the dashboard's per-weight curves keep
                # the canonical flat ordering.
                _, start, count, _ = pipe_cfg
                ratio_map = {}
                for k in params:
                    if k.startswith("__pipe__."):
                        r = ratio(new_params[k] - params[k], params[k],
                                  stacked=True)
                        suffix = k[len("__pipe__."):]
                        for j in range(count):
                            ratio_map[f"layers.{start + j}.{suffix}"] = r[j]
                    else:
                        ratio_map[k] = ratio(new_params[k] - params[k],
                                             params[k])
            ratios = (jnp.stack([ratio_map[k] for k in self.param_order])
                      if self.param_order else jnp.zeros((0,)))
            return new_params, new_opt_state, new_buffers, cost, ratios

        return finalize

    def train_micro_fns(self, optimizer_config: dict, num_steps: int,
                        remat: bool = False, compute_dtype=None,
                        sp_mesh=None, platform=None,
                        with_ratios: bool = True, out_shardings=None,
                        sp_mode: str = "ring", ep_mesh=None):
        """The fused :meth:`train_epoch_fn` program split at grad-accum
        micro-step boundaries for decode-priority dispatch: with the epoch
        issued one micro-step per device program, a pending ``/generate/``
        dispatch slips onto the chip between micro-steps instead of
        waiting out the whole epoch — worst-case added TTFT drops from
        one epoch to one micro-step (the reference bounds this with
        process isolation instead: main.py:461-464).

        Returns ``(micro_fn, finalize_fn)``:

        - ``micro_fn(params, buffers, grads, cost, x, y, rng, i)`` →
          ``(buffers, grads, cost)`` — one micro-step's grads accumulated
          in fp32.
        - ``finalize_fn(params, opt_state, grads, buffers, cost)`` → the
          epoch fn's 5-tuple.

        Numerics match the fused epoch to fp tolerance: same
        ``fold_in(rng, i)`` stream, same fp32 accumulation order, the
        identical finalize body (``_finalize_update_fn``) — bitwise
        equality is NOT guaranteed (the standalone micro program fuses
        differently than the scanned epoch body).  The params'
        compute-dtype cast runs
        once per micro dispatch instead of once per epoch — identical
        values, ``num_steps-1`` extra cast passes, the price of
        preemptibility.  Pipelined (``pipe_cfg``) training keeps the
        fused path: its schedule is one shard_map program by design.
        """
        key = ("microstep", json.dumps(optimizer_config, sort_keys=True),
               int(num_steps), bool(remat), str(compute_dtype), sp_mesh,
               platform, bool(with_ratios),
               (tuple(sorted(out_shardings[0].items())),
                tuple(jax.tree.leaves(out_shardings[1])))
               if out_shardings is not None else None, sp_mode, ep_mesh)
        cached = self._jit_cache.get(key)
        if cached is not None:
            return cached

        optimizer = dsl.build_optimizer(optimizer_config)

        def loss_fn(params, buffers, x, y, rng):
            _, cost, buf_upd, _ = self.forward(
                params, buffers, x, y, training=True, rng=rng,
                skip_softmax=True, compute_dtype=compute_dtype,
                sp_mesh=sp_mesh, platform=platform, sp_mode=sp_mode,
                ep_mesh=ep_mesh)
            return cost, buf_upd

        if remat:
            loss_fn = jax.checkpoint(loss_fn)
        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

        def micro(params, bufs, grads_acc, cost_acc, x, y, rng, i):
            if compute_dtype is not None:
                params_c = {
                    k: v.astype(compute_dtype)
                    if jnp.issubdtype(v.dtype, jnp.floating) else v
                    for k, v in params.items()}
            else:
                params_c = params
            (cost, upd), grads = grad_fn(params_c, bufs, x, y,
                                         jax.random.fold_in(rng, i))
            bufs = {**bufs, **upd}
            grads_acc = jax.tree.map(
                lambda a, g: a + g.astype(jnp.float32), grads_acc, grads)
            return bufs, grads_acc, cost_acc + cost

        finalize = self._finalize_update_fn(optimizer, num_steps,
                                            out_shardings, with_ratios,
                                            None)
        # Donation is restricted to carries a concurrent decode can never
        # see (grads/cost accumulators, the optimizer state): the whole
        # point of this path is /generate/ reading self.params and
        # self.buffers BETWEEN micro dispatches, so neither may be donated
        # (the fused epoch donates params safely because nothing yields
        # mid-program).  The price is one transient extra params copy at
        # finalize.
        fns = (jax.jit(micro, donate_argnums=(2, 3)),
               jax.jit(finalize, donate_argnums=(1, 2)))
        self._jit_cache[key] = fns
        return fns

    def _pipelined_loss_fn(self, pipe_cfg, compute_dtype, platform,
                           pipe_remat: str = "block",
                           sp_mode: str = "ring"):
        """Loss for the GPipe training layout: pre-block modules run on the
        full batch, the stacked blocks stream microbatches through the
        pipe-axis stages (``parallel/pipeline.gpipe_apply``), post-block
        modules + fused CE close the loss.  Params arrive in the mixed
        layout built by ``NeuralNetworkModel._enter_pipe_layout``:
        ``__pipe__.<suffix>`` stacked leaves plus flat non-block keys.

        Extends the reference's single DDP strategy (SURVEY §2.4 — it has
        no PP) as a depth sharding inside the same compiled program.
        """
        from penroz_tpu.parallel import pipeline
        pmesh, start, count, micro = pipe_cfg
        # MoE blocks route their balance loss + router-fraction buffers
        # through the schedule's aux channel (bubble-masked, see
        # gpipe_apply); blocks without stateful modules skip the plumbing.
        with_aux = any(isinstance(sub, M.MixtureOfExperts)
                       for sub in self.mods[start].walk())
        # SP inside the stages (both modes): the sequence axis joins the
        # schedule's manual set and attention runs the ring or Ulysses
        # body on it directly.  Layout entry validates dropout-free
        # attention and fp32 parameter storage; indivisible heads fall
        # back from alltoall to ring with a trace-time warning; MoE
        # blocks compose (the aux channel folds the seq axis).
        seq_shard = pmesh.shape[mesh_lib.SEQ_AXIS] > 1
        block_fn = pipeline.block_fn_from_arch(
            self, start, training=True, compute_dtype=compute_dtype,
            platform=platform, with_aux=with_aux, sp_manual=seq_shard,
            sp_mode=sp_mode)
        # Shape probe for the aux channel: the real block_fn references
        # the manual sequence axis, unbound outside the schedule.
        aux_probe_fn = (pipeline.block_fn_from_arch(
            self, start, training=True, compute_dtype=compute_dtype,
            platform=platform, with_aux=True)
            if (with_aux and seq_shard) else None)
        pre = self.mods[:start]
        post = self.mods[start + count:]

        def loss_fn(params, buffers, x, y, rng):
            ctx = M.Ctx(params, buffers, training=True, rng=rng,
                        compute_dtype=compute_dtype, platform=platform)
            h = x
            for mod in pre:
                h = mod.apply(h, ctx)
            stacked = {k[len("__pipe__."):]: v for k, v in params.items()
                       if k.startswith("__pipe__.")}
            res = pipeline.gpipe_apply(block_fn, stacked, h, pmesh, micro,
                                       rng=jax.random.fold_in(rng, 0x9e3779),
                                       remat=pipe_remat, with_aux=with_aux,
                                       seq_shard=seq_shard,
                                       aux_probe_fn=aux_probe_fn)
            if with_aux:
                h, aux_sums = res
                # Per-(layer, microbatch) sums -> mean over microbatches.
                # Microbatches partition the rows, so the fraction means
                # equal the sequential whole-batch fractions exactly; the
                # balance loss matches the grad-accum path where each
                # micro-step's aux joins its own cost and costs average.
                ctx.aux_losses.append(jnp.sum(aux_sums["loss"]) / micro)
                for key, leaf in aux_sums.items():
                    if key == "loss":
                        continue
                    suffix = key[len("buf."):]
                    for j in range(count):
                        ctx.buffer_updates[
                            f"layers.{start + j}.{suffix}"] = leaf[j] / micro
            else:
                h = res
            logits = None
            for mod in post:
                if isinstance(mod, M.Softmax):
                    if logits is None:
                        logits = h  # skip_softmax semantics (cost on logits)
                    continue
                h = mod.apply(h, ctx)
            if logits is None:
                logits = h
            cost = self._cost_from_logits(logits, y, platform=platform)
            if ctx.aux_losses:
                cost = cost + sum(ctx.aux_losses)
            return cost, ctx.buffer_updates

        return loss_fn

    # -- decode -------------------------------------------------------------

    def _decode_step(self, params, buffers, kv, tokens, rng, temp, *,
                     greedy, top_k, compute_dtype, platform=None,
                     lora=None, lora_idx=None):
        """Feed tokens through the stack with the KV cache, sample the next
        token on-device (reference samples on host: :393-405)."""
        acts, _, _, new_kv = self.forward(
            params, buffers, tokens, None, kv=kv, pos_offset=kv.length,
            skip_softmax=True, compute_dtype=compute_dtype,
            platform=platform, lora=lora, lora_idx=lora_idx)
        logits = acts[-1]
        if logits.ndim == 3:
            logits = logits[:, -1, :]
        tok = self._sample(logits, rng, temp, greedy=greedy, top_k=top_k)
        return tok[:, None], new_kv

    @staticmethod
    def _sample(logits, rng, temp, *, greedy, top_k):
        """(B,) next tokens from (B, V) logits: argmax | top-k | categorical
        (reference sampling: neural_net_model.py:393-405, on-device)."""
        logits = logits.astype(jnp.float32)
        if greedy:
            tok = jnp.argmax(logits, axis=-1)
        else:
            logits = logits / jnp.maximum(temp, 1e-6)
            if top_k is not None:
                vals, idx = jax.lax.top_k(logits, int(top_k))
                choice = jax.random.categorical(rng, vals)
                tok = jnp.take_along_axis(idx, choice[..., None], -1)[..., 0]
            else:
                tok = jax.random.categorical(rng, logits)
        return tok.astype(jnp.int32)

    @staticmethod
    def _sample_packed(logits, rng, row_ids, positions, temp, top_k):
        """(Tp,) sampled tokens from packed (Tp, V) logits with a
        POSITIONAL key per slot: ``fold_in(fold_in(rng, row), position)``.
        A (row, position) pair draws the same token no matter which packed
        slot, superstep, chunk split or pipeline micro-block it rides in —
        the invariance that lets seeded temperature>0 streams stay
        identical across spec-on/off (rejection sampling over point-mass
        drafts reduces to prefix matching against these draws) and across
        pipeline stage counts.  Padding slots carry ``row_ids < 0``;
        clipped to 0, sampled, and discarded by the host replay."""
        logits = logits.astype(jnp.float32)
        logits = logits / jnp.maximum(temp, 1e-6)
        keys = jax.vmap(
            lambda rid, pos: jax.random.fold_in(
                jax.random.fold_in(rng, jnp.clip(rid, 0)),
                jnp.maximum(pos, 0))
        )(row_ids.astype(jnp.int32), positions.astype(jnp.int32))
        if top_k is not None:
            vals, idx = jax.lax.top_k(logits, int(top_k))
            choice = jax.vmap(jax.random.categorical)(keys, vals)
            tok = jnp.take_along_axis(idx, choice[..., None], -1)[..., 0]
        else:
            tok = jax.vmap(jax.random.categorical)(keys, logits)
        return tok.astype(jnp.int32)

    def decode_fn(self):
        """Dispatcher for single decode/prefill steps (jits per static
        (greedy, top_k, dtype); shapes retrace automatically)."""

        def decode(params, buffers, kv, tokens, rng, temp, *,
                   compute_dtype=None, greedy=False, top_k=None,
                   platform=None):
            key = ("decode", bool(greedy), top_k, str(compute_dtype),
                   platform)
            fn = self._jit_cache.get(key)
            if fn is None:
                def step(p, b, k, t, r, tmp):
                    return self._decode_step(p, b, k, t, r, tmp,
                                             greedy=greedy, top_k=top_k,
                                             compute_dtype=compute_dtype,
                                             platform=platform)
                fn = self._jit_cache[key] = jax.jit(step, donate_argnums=(2,))
            return fn(params, buffers, kv, tokens, rng, temp)

        return decode

    def decode_chunk(self, params, buffers, kv, last_tok, rng, temp, *,
                     chunk: int, greedy=False, top_k=None, compute_dtype=None,
                     platform=None):
        """Run ``chunk`` fused decode+sample steps in one dispatch."""
        key = ("chunk", int(chunk), bool(greedy), top_k, str(compute_dtype),
               platform)
        fn = self._jit_cache.get(key)
        if fn is None:
            def run(p, b, kv0, tok0, r, tmp):
                def step(carry, i):
                    kv_c, tok = carry
                    new_tok, kv_c = self._decode_step(
                        p, b, kv_c, tok, jax.random.fold_in(r, i), tmp,
                        greedy=greedy, top_k=top_k,
                        compute_dtype=compute_dtype, platform=platform)
                    return (kv_c, new_tok), new_tok[:, 0]

                (kv_c, _), toks = jax.lax.scan(step, (kv0, tok0),
                                               jnp.arange(chunk))
                return toks.T, kv_c

            fn = self._jit_cache[key] = jax.jit(run, donate_argnums=(2,))
        return fn(params, buffers, kv, last_tok, rng, temp)

    # -- diagnostics --------------------------------------------------------

    def stats_grads(self, params, buffers, x, y, compute_dtype=None,
                    platform=None):
        """Activations, activation-gradients and weight-gradients for one
        batch — the /stats/ inputs.  Activation grads come from an explicit
        zero-delta VJP (JAX has no ``retain_grad``; reference :643-646)."""
        acts, _, _, _ = self.jit_forward(params, buffers, x, y,
                                         skip_softmax=True,
                                         compute_dtype=compute_dtype,
                                         platform=platform)
        deltas = [jnp.zeros(a.shape, a.dtype) for a in acts]

        key = ("statsgrad", str(compute_dtype), platform)
        fn = self._jit_cache.get(key)
        if fn is None:
            def f(p, d, xb, yb, bufs):
                ctx = M.Ctx(p, bufs, training=False,
                            compute_dtype=compute_dtype, platform=platform)
                h = xb
                i = 0
                for mod in self.mods:
                    if isinstance(mod, M.Softmax):
                        continue
                    h = mod.apply(h, ctx) + d[i]
                    i += 1
                return self._cost_from_logits(h, yb, platform=platform)

            fn = self._jit_cache[key] = jax.jit(
                lambda p, d, xb, yb, bufs:
                jax.grad(f, argnums=(0, 1))(p, d, xb, yb, bufs))
        weight_grads, act_grads = fn(params, deltas, x, y, buffers)
        return acts, act_grads, weight_grads


class ServePipeline:
    """Stage partition of a compiled arch for MPMD pipeline serving
    (``PENROZ_SERVE_PIPE_STAGES``).

    Unlike the training pipeline (``__pipe__`` stacked layouts + ppermute
    inside one jit, parallel/pipeline.py) the serving pipeline is MPMD:
    each stage is its own :class:`CompiledArch` over a contiguous slice of
    the layer DSL, compiling and dispatching its own per-stage program
    while the scheduler hands activations across stage boundaries
    (PAPERS.md #3).  The slice boundaries come from
    ``parallel.pipeline.serve_stage_bounds`` — contiguous runs of the
    repeated transformer block, with the prologue (embeddings) glued to
    the first stage and the epilogue (final norm / head) to the last.

    Per-stage KV: stage ``s`` owns attention layers ``kv_bounds[s] =
    [lo, hi)`` of the full paged cache — its pools live on its own stage
    mesh (``ops.kv_cache.stage_kv_view`` / ``merge_stage_kv``), which is
    what drops per-device HBM ~1/S.  Stage archs index their attention
    layers 0.. locally, matching the sliced pool lists exactly.

    Params/buffers are NOT copied: :meth:`stage_params` re-keys the
    canonical flat dict (``layers.{i}.*`` → ``layers.{i-lo}.*``) per
    dispatch — dict slicing over array references, no device traffic.
    """

    def __init__(self, arch: "CompiledArch", stages: int):
        from penroz_tpu.parallel import pipeline
        if arch.ssm_specs:
            raise ValueError(
                "pipeline serving does not support SSM/recurrent blocks: "
                "stage_kv_view slices attention pools only and would drop "
                "the per-row recurrent state")
        self.stages = int(stages)
        self.bounds = pipeline.serve_stage_bounds(arch.layers_dsl,
                                                  self.stages)
        self.archs = [CompiledArch.get(arch.layers_dsl[lo:hi])
                      for lo, hi in self.bounds]
        self.kv_bounds: list[tuple] = []
        off = 0
        for s, stage_arch in enumerate(self.archs):
            n = len(stage_arch.kv_specs)
            if n == 0:
                raise ValueError(
                    f"pipeline stage {s} owns no attention layers; lower "
                    f"PENROZ_SERVE_PIPE_STAGES (bounds {self.bounds[s]})")
            self.kv_bounds.append((off, off + n))
            off += n
        if off != len(arch.kv_specs):
            raise ValueError(
                f"stage KV partition covers {off} attention layers, "
                f"model has {len(arch.kv_specs)}")
        # Per-stage TP meshes, filled by _enter_serve_pipe_mesh when the
        # group really spans devices (None = degenerate single-device
        # layout — no placement, no per-dispatch re-staging needed).
        self.meshes = None

    def stage_key_range(self, s: int):
        """Half-open top-level DSL entry range owned by stage ``s``."""
        return self.bounds[s]

    def _rekey(self, flat: dict, s: int) -> dict:
        lo, hi = self.bounds[s]
        out = {}
        for k, v in flat.items():
            if not k.startswith("layers."):
                if s == 0:  # prologue state rides with the first stage
                    out[k] = v
                continue
            idx, _, suffix = k[len("layers."):].partition(".")
            i = int(idx)
            if lo <= i < hi:
                out[f"layers.{i - lo}.{suffix}"] = v
        return out

    def stage_params(self, params: dict, s: int) -> dict:
        return self._rekey(params, s)

    def stage_buffers(self, buffers: dict, s: int) -> dict:
        return self._rekey(buffers, s)


class NeuralNetworkModel:
    """Full model lifecycle facade (reference: NeuralNetworkModel,
    neural_net_model.py:28-779)."""

    def __init__(self, model_id: str, mapper: Mapper):
        self.model_id = model_id
        self.layers_dsl = mapper.layers
        self.optimizer_config = mapper.optimizer
        self.arch = CompiledArch.get(mapper.layers)
        self.params, self.buffers = mapper.init_params(self.arch.mods)
        self.opt_state = mapper.to_optimizer().init(self.params)
        self.progress: list[dict] = []
        self.avg_cost: Optional[float] = None
        self.avg_cost_history: list[float] = []
        self.stats: Optional[dict] = None
        self.status = {"code": "Created", "message": "Model created"}
        self.device = None
        self._sample_rng = jax.random.key(0)
        # (start, count) while params live in the GPipe stacked layout
        self._pipe_layout: Optional[tuple] = None

    # -- introspection ------------------------------------------------------

    @property
    def num_params(self) -> int:
        return sum(int(np.prod(v.shape)) for v in self.params.values())

    @property
    def dtype(self):
        for v in self.params.values():
            if jnp.issubdtype(v.dtype, jnp.floating):
                return v.dtype
        return jnp.dtype(jnp.float32)

    def state_dict(self) -> dict:
        """Flat params + buffers under reference-compatible key names."""
        out = {k: np.asarray(v) for k, v in self.params.items()}
        out.update({k: np.asarray(v) for k, v in self.buffers.items()})
        return out

    def to(self, dtype=None):
        """Cast floating params/buffers (reference bf16 policy:
        neural_net_model.py:145-157)."""
        if dtype is not None:
            self.params = {
                k: v.astype(dtype) if jnp.issubdtype(v.dtype, jnp.floating)
                else v for k, v in self.params.items()}
            self.buffers = {
                k: v.astype(dtype) if jnp.issubdtype(v.dtype, jnp.floating)
                else v for k, v in self.buffers.items()}
        return self

    def to_device(self, device: Optional[str]):
        dev = _resolve_device(device)
        if dev is not None:
            self.params = jax.device_put(self.params, dev)
            self.buffers = jax.device_put(self.buffers, dev)
            self.opt_state = jax.device_put(self.opt_state, dev)
            self.device = dev
        return self

    @property
    def _platform(self) -> Optional[str]:
        """Execution-platform hint for the Pallas kernel gates.  A model
        explicitly placed (device='cpu' on a TPU-attached host) must not
        trace TPU kernels that cannot lower for its backend; params placement
        is what actually decides where jit runs."""
        if self.device is not None:
            return self.device.platform
        try:
            p = next(iter(self.params.values()))
            if isinstance(p, jax.Array) and not isinstance(p, jax.core.Tracer):
                return next(iter(p.devices())).platform
        except StopIteration:
            pass
        return None

    # -- inference ----------------------------------------------------------

    def _as_input(self, data):
        try:
            arr = np.asarray(data)
        except ValueError:
            raise ValueError(
                "input rows have inconsistent lengths; expected a "
                "rectangular batch like [[1, 2, 3], [4, 5, 6]]")
        if arr.dtype.kind in "iu":
            if self.arch.attn_layers and arr.ndim != 2:
                # A flat token list on a sequence model dies deep in the
                # stack with an opaque unpack error; say what's wrong at
                # the API boundary instead (→ HTTP 400).
                raise ValueError(
                    f"token input must be 2-D (batch, length) for this "
                    f"model, e.g. [[1, 2, 3]]; got {arr.ndim}-D")
            return jnp.asarray(arr.astype(np.int64), jnp.int32)
        return jnp.asarray(arr).astype(self.dtype)

    def compute_output(self, input, target=None):
        """Raw forward; returns (final activation as lists, cost or None)
        (reference: neural_net_model.py:273-298)."""
        x = self._as_input(input)
        if target is None:
            acts, cost, _, _ = self.arch.jit_forward(self.params, self.buffers,
                                                     x,
                                                     platform=self._platform)
        else:
            t = np.asarray(target)
            if self.arch.classification:
                t = jnp.asarray(t.astype(np.int64), jnp.int32)
            else:
                t = jnp.asarray(t, jnp.float32)
            acts, cost, _, _ = self.arch.jit_forward(self.params, self.buffers,
                                                     x, t,
                                                     platform=self._platform)
        output = np.asarray(acts[-1], np.float32).tolist()
        return output, (float(cost) if cost is not None else None)

    def evaluate_model(self, dataset_id, target_dataset_id, shard, epochs,
                       batch_size, block_size, step_size) -> float:
        """Forward-only evaluation with the training loader math
        (reference: neural_net_model.py:300-358).

        Reference parity: one ``(batch_size, block_size)`` buffer is loaded
        per epoch and forwarded ``num_steps`` times under no-grad
        (:337-351) — identical data each step, so we forward once and weight
        by ``1/epochs`` (numerically equal, ``num_steps``× fewer FLOPs).
        The result is averaged across processes like the reference's
        ``ddp_all_reduce`` (:352-354).  Multi-host contract: as with
        ``/train/`` over a global mesh, every host's server must receive
        the same request — the final reduction is a collective and a
        single-host request would block until the distributed runtime
        times out.
        """
        from penroz_tpu.data.loaders import Loader
        world = dist.process_count()
        rank = dist.process_index()
        buffer_size = batch_size * block_size
        loader = Loader(dataset_id, begin_shard=shard,
                        begin_idx=buffer_size * rank, buffer_size=buffer_size,
                        idx_offset=buffer_size * world)
        target_loader = None
        if target_dataset_id:
            target_loader = Loader(target_dataset_id, begin_shard=shard,
                                   begin_idx=buffer_size * rank,
                                   buffer_size=buffer_size,
                                   idx_offset=buffer_size * world)
        mesh = self._eval_mesh(batch_size, block_size)
        sp_mesh = None
        ep_mesh = None
        sp_mode = os.environ.get("PENROZ_SP_MODE", "ring")
        if mesh is not None:
            log.info("Evaluating over device mesh %s", dict(mesh.shape))
            if mesh.shape[mesh_lib.SEQ_AXIS] > 1:
                # Sequence-parallel eval: shard the block over the seq
                # axis and run the ring/all-to-all attention, same as the
                # training epoch — without this the seq-axis chips would
                # do purely redundant replicated work.
                if sp_mode not in ("ring", "alltoall"):
                    raise ValueError(f"PENROZ_SP_MODE={sp_mode!r}; "
                                     "expected 'ring' or 'alltoall'")
                sp_mesh = mesh
            if mesh.shape[mesh_lib.EXPERT_AXIS] > 1:
                ep_mesh = mesh
            # Mirror the training layout (TP over `model`, experts over
            # `expert`, ZeRO-3 over `data` when PENROZ_FSDP=1) so an
            # already-mesh-placed model is a no-op and a freshly loaded one
            # gets the layout its size may require (a TP-trained model
            # larger than one chip cannot evaluate single-device at all).
            fsdp = os.environ.get("PENROZ_FSDP", "0") == "1"
            self.params = sharding_lib.shard_params(self.params, mesh,
                                                    fsdp=fsdp)
            self.buffers = {
                k: sharding_lib.place(v, mesh_lib.replicated(mesh))
                for k, v in self.buffers.items()}
        avg_cost = 0.0
        for _ in range(epochs):
            if target_loader is not None:
                x, _ = loader.next_batch(target_offset=0)
                y, _ = target_loader.next_batch(target_offset=0)
            else:
                x, y = loader.next_batch()
            x = x.reshape(batch_size, block_size)
            y = y.reshape(batch_size, block_size)
            if mesh is not None:
                x = sharding_lib.global_batch(
                    x, mesh, shard_sequence=sp_mesh is not None)
                y = sharding_lib.global_batch(
                    y, mesh, shard_sequence=sp_mesh is not None)
            else:
                x = jnp.asarray(x)
                y = jnp.asarray(y)
            cost = self.arch.eval_cost_fn(self.params, self.buffers, x, y,
                                          platform=self._platform,
                                          sp_mesh=sp_mesh, sp_mode=sp_mode,
                                          ep_mesh=ep_mesh)
            avg_cost += float(cost) / epochs
        # Under a global multi-host mesh the compiled cost is already the
        # global-batch mean (identical on every process), so this reduce is
        # an identity; it remains load-bearing for the mesh-less multi-host
        # path, where each process averaged only its own stride.
        return dist.all_reduce_mean(avg_cost)

    # -- training -----------------------------------------------------------

    def train_model(self, dataset_id, shard=0, epochs=1, batch_size=1,
                    block_size=1024, step_size=1):
        """Grad-accumulated training with progress/stats bookkeeping and
        periodic checkpoints (reference: neural_net_model.py:552-722).

        Reference micro-batch semantics (:581-586, :629-631): every
        micro-step consumes a full ``(batch_size, block_size)`` buffer from
        the loader; ``step_size`` only sets how many such micro-steps
        accumulate into one optimizer step
        (``num_steps = buffer_size // (step_size * block_size * world)``).
        Progress/stats reset at train start (:597-601); ``speedPerSec``
        counts ``buffer_size`` tokens per epoch exactly as the reference
        does (:684-703), although an epoch consumes ``num_steps`` buffers.

        Per-epoch cost under a multi-host mesh is computed over the global
        batch inside the compiled program, which subsumes the reference's
        per-epoch ``ddp_all_reduce(cost)`` (:664-665).
        """
        from penroz_tpu.data.loaders import Loader
        master = dist.master_proc()
        saves_shards = False
        epoch = 0
        # Bumped at request START so it advances in lockstep on every host
        # regardless of how this run ends (multi-host contract: every host
        # receives the same requests) — the train-end barrier id derives
        # from it and must never desynchronize.  Module-level (not an
        # instance attribute): /train/ deserializes a fresh model object
        # per request, but the counter must survive across them for the
        # process lifetime.  A single host restarting would reset only its
        # own counters, but that state is unreachable: jax.distributed
        # requires every process alive, so one host restarting forces a
        # fleet-wide restart that resets all counters together.
        _TRAIN_SEQ[self.model_id] = train_seq = \
            _TRAIN_SEQ.get(self.model_id, 0) + 1
        try:
            world = dist.process_count()
            rank = dist.process_index()
            buffer_size = batch_size * block_size
            # Reset run state before anything that can raise (mesh config,
            # missing dataset): an Error from THIS request must not present
            # the previous run's progress as its own.
            self.progress = []
            self.stats = None
            mesh = self._training_mesh(batch_size, block_size)
            # When pipeline stages span processes, what's distributed
            # across hosts is the MODEL, not the data: every process feeds
            # the same batch (rank striding off, DP width 1 in the
            # reference buffer math) and the within-stage data axis shards
            # those rows locally.
            pipe_over_hosts = (world > 1 and mesh is not None
                               and mesh.shape[mesh_lib.PIPE_AXIS] > 1)
            dp_world = 1 if pipe_over_hosts else world
            dp_rank = 0 if pipe_over_hosts else rank
            num_steps = max(1, buffer_size
                            // (step_size * block_size * dp_world))
            loader = Loader(dataset_id, begin_shard=shard,
                            begin_idx=buffer_size * dp_rank,
                            buffer_size=buffer_size,
                            idx_offset=buffer_size * dp_world)
            self.status = {"code": "Training",
                           "message": f"Training on {dataset_id}"}
            if master:
                self.serialize()
            sp_mesh = None
            ep_mesh = None
            epoch_out_shardings = None
            pipe_cfg = None
            if mesh is not None and mesh.shape[mesh_lib.PIPE_AXIS] > 1:
                log.info("Training over device mesh %s", dict(mesh.shape))
                pipe_cfg, epoch_out_shardings = self._enter_pipe_layout(
                    mesh, batch_size)
                self.buffers = {
                    k: sharding_lib.place(v, mesh_lib.replicated(mesh))
                    for k, v in self.buffers.items()}
            elif mesh is not None:
                log.info("Training over device mesh %s", dict(mesh.shape))
                # ZeRO ladder on top of the TP layout (arXiv:2004.13336):
                # PENROZ_WUS=1 spreads the optimizer moments over the data
                # axis (each DP replica updates 1/data of the weights);
                # PENROZ_FSDP=1 also shards the params themselves (ZeRO-3 —
                # XLA all-gathers each weight just-in-time per matmul).
                # The epoch fn's out_shardings pin keeps both layouts
                # stable across steps instead of whatever GSPMD propagates.
                fsdp = os.environ.get("PENROZ_FSDP", "0") == "1"
                wus = fsdp or os.environ.get("PENROZ_WUS", "0") == "1"
                self.params = sharding_lib.shard_params(self.params, mesh,
                                                        fsdp=fsdp)
                epoch_out_shardings = (
                    sharding_lib.param_shardings(self.params, mesh,
                                                 fsdp=fsdp),
                    sharding_lib.opt_state_sharding_tree(self.opt_state,
                                                         self.params, mesh,
                                                         wus=wus))
                self.opt_state = sharding_lib.place_tree(
                    self.opt_state, epoch_out_shardings[1])
                self.buffers = {
                    k: sharding_lib.place(v, mesh_lib.replicated(mesh))
                    for k, v in self.buffers.items()}
                if mesh.shape[mesh_lib.SEQ_AXIS] > 1:
                    sp_mesh = mesh
                if mesh.shape[mesh_lib.EXPERT_AXIS] > 1:
                    # MoE capacity dispatch routes tokens over the expert
                    # axis via all_to_all (ops/modules._apply_capacity_ep)
                    # instead of the dense-combine psum.
                    ep_mesh = mesh
            # With cross-host-sharded state every process must persist its
            # own shard file at each checkpoint; the master also writes the
            # metadata blob (serialize() handles the split internally).
            # Checked over ALL persisted items: under PENROZ_WUS only the
            # optimizer moments are cross-host data-sharded (params stay
            # host-readable), and under PENROZ_FSDP the params are too —
            # both need the shard-file treatment, so a params-only check
            # would tear either checkpoint.
            saves_shards = (mesh is not None and world > 1
                            and not all(self._is_host_readable(v)
                                        for v in
                                        self._checkpoint_items().values()))
            # PENROZ_REMAT=1 rematerializes the forward inside the backward
            # (jax.checkpoint) — trades ~1/3 more FLOPs for activation memory,
            # the lever for configs that would otherwise exceed HBM.
            remat = os.environ.get("PENROZ_REMAT", "0") == "1"
            # PENROZ_PIPE_REMAT selects the pipelined path's activation
            # schedule: 'block' (default — backward recomputes each block
            # tick-by-tick, bounding stage memory to live microbatch
            # activations the way 1F1B does) or 'none' (save everything).
            pipe_remat = os.environ.get("PENROZ_PIPE_REMAT", "block")
            if pipe_remat not in ("none", "block"):
                raise ValueError(f"PENROZ_PIPE_REMAT={pipe_remat!r}; "
                                 "expected 'none' or 'block'")
            # Reference parity: training autocasts to bf16 on CUDA
            # (neural_net_model.py:567-578) and stays full-precision on CPU.
            # The TPU-native equivalent is bf16 compute on TPU — params and
            # optimizer state remain fp32; no GradScaler is needed on TPU.
            # PENROZ_TRAIN_DTYPE=float32|bfloat16 overrides.
            dtype_env = os.environ.get("PENROZ_TRAIN_DTYPE", "")
            if dtype_env:
                compute_dtype = (None if dtype_env == "float32"
                                 else jnp.dtype(dtype_env))
            elif self._platform in ("tpu", "axon") or (
                    self._platform is None
                    and jax.default_backend() in ("tpu", "axon")):
                compute_dtype = jnp.bfloat16
            else:
                compute_dtype = None
            # PENROZ_SP_MODE selects the sequence-parallel attention:
            # 'ring' (ppermute rotation, default) or 'alltoall' (Ulysses
            # head re-partitioning; needs heads divisible by the axis).
            sp_mode = os.environ.get("PENROZ_SP_MODE", "ring")
            if sp_mode not in ("ring", "alltoall"):
                raise ValueError(f"PENROZ_SP_MODE={sp_mode!r}; expected "
                                 "'ring' or 'alltoall'")
            if sp_mode == "alltoall" and sp_mesh is not None:
                from penroz_tpu.parallel import alltoall_attention as a2a
                undiv = [i for i, mod in enumerate(self.arch.attn_layers)
                         if not a2a.alltoall_supported(
                             mod.num_heads, mod.num_kv_heads, sp_mesh)]
                if undiv:
                    log.warning(
                        "PENROZ_SP_MODE=alltoall: attention layer(s) %s "
                        "have head counts not divisible by the sequence "
                        "axis (%d) and fall back to ring attention",
                        undiv, sp_mesh.shape[mesh_lib.SEQ_AXIS])
            epoch_fn = self.arch.train_epoch_fn(
                self.optimizer_config, num_steps, remat=remat,
                compute_dtype=compute_dtype, sp_mesh=sp_mesh,
                platform=self._platform,
                out_shardings=epoch_out_shardings, sp_mode=sp_mode,
                pipe_cfg=pipe_cfg, pipe_remat=pipe_remat, ep_mesh=ep_mesh)
            # Non-sampled epochs skip the two full parameter passes the
            # update-ratio stds cost.  The choice is a pure function of the
            # epoch index so every host runs the same compiled program
            # (collective schedules must match under a multi-host mesh).
            sample_every = max(1, epochs // 100)
            epoch_fn_fast = (
                self.arch.train_epoch_fn(self.optimizer_config, num_steps,
                                         remat=remat,
                                         compute_dtype=compute_dtype,
                                         sp_mesh=sp_mesh,
                                         platform=self._platform,
                                         with_ratios=False,
                                         out_shardings=epoch_out_shardings,
                                         sp_mode=sp_mode,
                                         pipe_cfg=pipe_cfg,
                                         pipe_remat=pipe_remat,
                                         ep_mesh=ep_mesh)
                if sample_every > 1 else epoch_fn)
            rng = jax.random.key(0)
            last_save = time.monotonic()
            last_stats = time.monotonic()
            # Stats refresh runs a full instrumented pass (the reference
            # histograms grads already retained by its backward,
            # :643-646, which is nearly free; ours re-derives them), so
            # it gets its own, longer cadence than the 10s checkpoint.
            stats_interval = float(
                os.environ.get("PENROZ_STATS_INTERVAL", "60"))
            last_batch = None  # host-local numpy micro-batch for /stats/
            for epoch in range(epochs):
                # Decode-priority window: queued /generate/ dispatches get
                # the chip before the next epoch program is enqueued.
                _yield_to_decodes()
                t0 = time.monotonic()
                long_training = t0 - last_save >= 10
                if saves_shards:
                    # All hosts must agree on checkpoint epochs or the blob
                    # and the per-host shard files would mix training steps;
                    # a tiny scalar reduction makes the clock-based decision
                    # deterministic across the fleet.
                    long_training = dist.all_reduce_mean(
                        1.0 if long_training else 0.0) >= 0.5
                with profiling.span("penroz/load_batch"):
                    xs, ys = [], []
                    for _ in range(num_steps):
                        x, y = loader.next_batch()
                        xs.append(x.reshape(batch_size, block_size))
                        ys.append(y.reshape(batch_size, block_size))
                    # stay on host: global_batch/jit place them exactly once
                    xs = np.stack(xs)
                    ys = np.stack(ys)
                last_batch = (xs[-1], ys[-1])
                if mesh is not None:
                    xs = sharding_lib.global_batch(
                        xs, mesh, leading_steps=True,
                        shard_sequence=sp_mesh is not None,
                        process_replicated=pipe_over_hosts)
                    ys = sharding_lib.global_batch(
                        ys, mesh, leading_steps=True,
                        shard_sequence=sp_mesh is not None,
                        process_replicated=pipe_over_hosts)
                sampled = epoch % sample_every == 0
                fn = epoch_fn if sampled else epoch_fn_fast
                # Micro-step granularity when a decode is in flight: the
                # fused epoch is one device program a /generate/ can only
                # wait out; chunked dispatch bounds the decode's wait to
                # one micro-step (+ its own work).  Fused otherwise — the
                # chunked path pays per-dispatch overhead num_steps times.
                use_micro = (pipe_cfg is None and world == 1
                             and num_steps > 1 and decode_pending() > 0
                             and float(os.environ.get(
                                 "PENROZ_DECODE_PRIORITY_MS", "1000")) > 0)
                with profiling.span("penroz/train_epoch"):
                    if use_micro:
                        (self.params, self.opt_state, self.buffers, cost,
                         ratios) = self._train_epoch_microstepped(
                            xs, ys, jax.random.fold_in(rng, epoch),
                            num_steps, remat=remat,
                            compute_dtype=compute_dtype, sp_mesh=sp_mesh,
                            out_shardings=epoch_out_shardings,
                            sp_mode=sp_mode, ep_mesh=ep_mesh,
                            with_ratios=sampled)
                    else:
                        (self.params, self.opt_state, self.buffers, cost,
                         ratios) = fn(self.params, self.opt_state,
                                      self.buffers, xs, ys,
                                      jax.random.fold_in(rng, epoch))
                cost = float(cost)
                duration = time.monotonic() - t0
                if master:
                    if epoch % sample_every == 0:
                        self.progress.append({
                            "epoch": epoch + 1,
                            "cost": cost,
                            "durationInSecs": duration,
                            "speedPerSec": buffer_size / max(duration, 1e-9),
                            "weight_upd_ratio":
                                np.asarray(ratios, np.float64).tolist(),
                        })
                    log.info("Epoch %d: cost=%.4f %.0f tokens/sec",
                             epoch + 1, cost,
                             buffer_size / max(duration, 1e-9))
                if long_training:
                    if master:
                        refresh = (time.monotonic() - last_stats
                                   >= stats_interval)
                        self._record_overall_progress(
                            last_batch if refresh else None)
                        if refresh:
                            last_stats = time.monotonic()
                    if master or saves_shards:
                        self.serialize(tag=epoch)
                    last_save = time.monotonic()
            self._exit_pipe_layout()
            self.status = {"code": "Trained",
                           "message": f"Trained {epochs} epoch(s)"}
            if master:
                self._record_overall_progress(last_batch)
            if master or saves_shards:
                self.serialize(tag=epochs)
            # Fence the run's end across processes: the master's post-train
            # bookkeeping (stats capture compiles a fresh program) can take
            # minutes, and a peer racing ahead into the next collective
            # (e.g. /evaluate/) would hit the ~30s lazy comm-group init
            # timeout waiting for this host.  RPC barrier, so it tolerates
            # the wait without any device group existing yet.  The id
            # comes from the train-start counter (in lockstep on every
            # host even if a peer errored mid-run); a failure here is a
            # pacing miss, not a training failure — the run is already
            # Trained and checkpointed, so never regress it to Error.
            try:
                dist.barrier(f"train_end_{self.model_id}_{train_seq}")
            except Exception:  # noqa: BLE001
                log.warning("train-end barrier failed; a peer may have "
                            "errored mid-run", exc_info=True)
        except Exception as e:  # noqa: BLE001
            try:
                # Hosts reach this handler independently — never run the
                # (collective) cross-host unstack one-sided.
                self._exit_pipe_layout(local_only=dist.is_distributed())
            except Exception:  # noqa: BLE001
                log.exception("Failed to restore flat param layout")
            self.status = {"code": "Error", "message": str(e)}
            # Untagged on purpose: hosts reach this handler independently
            # (possibly at different epochs, possibly only one of them), so
            # a shard-file rewrite here could tear the last consistent
            # checkpoint.  serialize() degrades an untagged sharded save to
            # a master-only metadata update — Error status is recorded,
            # weights stay at the last coordinated checkpoint.
            if master or saves_shards:
                try:
                    self.serialize(sync_flush=True)
                except Exception:  # noqa: BLE001
                    log.exception("Failed to persist error status")
            # Best-effort join of the train-end fence so healthy peers are
            # released promptly instead of eating the full barrier timeout
            # waiting for this (failed) host.  Short timeout: if the peers
            # are themselves far from the barrier, give up and let the
            # original error surface.
            try:
                dist.barrier(f"train_end_{self.model_id}_{train_seq}",
                             timeout_s=60.0)
            except Exception:  # noqa: BLE001
                log.warning("train-end barrier join from error path "
                            "failed", exc_info=True)
            raise

    def _record_overall_progress(self, last_batch):
        """Fold the run's progress into the overall average-cost history and
        refresh /stats/ (reference ``_record_training_overall_progress``,
        neural_net_model.py:724-733)."""
        import random
        if self.progress:
            avg_progress_cost = (sum(p["cost"] for p in self.progress)
                                 / len(self.progress))
            self.avg_cost = ((self.avg_cost or avg_progress_cost)
                             + avg_progress_cost) / 2.0
            self.avg_cost_history.append(self.avg_cost)
            if len(self.avg_cost_history) > 100:
                self.avg_cost_history.pop(random.randint(1, 98))
        if last_batch is not None:
            self.stats = self._compute_stats(*last_batch)

    def _train_epoch_microstepped(self, xs, ys, call_rng, num_steps: int, *,
                                  remat, compute_dtype, sp_mesh,
                                  out_shardings, sp_mode, ep_mesh,
                                  with_ratios: bool):
        """Decode-priority epoch: one device program per micro-step, with a
        priority window opened before each so pending ``/generate/``
        dispatches interleave at micro-step granularity (see
        ``CompiledArch.train_micro_fns`` for the numerics contract)."""
        micro_fn, finalize_fn = self.arch.train_micro_fns(
            self.optimizer_config, num_steps, remat=remat,
            compute_dtype=compute_dtype, sp_mesh=sp_mesh,
            platform=self._platform, with_ratios=with_ratios,
            out_shardings=out_shardings, sp_mode=sp_mode, ep_mesh=ep_mesh)
        return run_microstepped_epoch(micro_fn, finalize_fn, self.params,
                                      self.opt_state, self.buffers, xs, ys,
                                      call_rng, num_steps)

    def _training_mesh(self, micro_batch: int, block_size: int):
        """Device mesh for the training run (None = single device).

        ``micro_batch`` is the per-process rows of one micro-step —
        ``batch_size`` under the reference's buffer semantics.
        Data-parallelism over every local device is automatic when the
        micro-batch divides the data axis; ``PENROZ_MESH_MODEL`` /
        ``PENROZ_MESH_SEQUENCE`` / ``PENROZ_MESH_EXPERT`` carve tensor/
        sequence/expert-parallel axes out of the same device set, and
        ``PENROZ_TRAIN_MESH=0`` disables meshing (single-process only).
        This replaces the reference's per-request DDP process tree
        (ddp.py:38-73) — the mesh lives inside one compiled program.
        """
        if os.environ.get("PENROZ_TRAIN_MESH", "1") == "0":
            if dist.process_count() > 1:
                # Opting out of the mesh under multi-host would train
                # divergent per-host replicas with no gradient sync while
                # the loader still rank-strides the data — silent
                # corruption, so refuse loudly.
                raise RuntimeError(
                    "PENROZ_TRAIN_MESH=0 is invalid when "
                    f"process_count={dist.process_count()} > 1: multi-host "
                    "training requires the global mesh for gradient sync")
            return None
        if dist.process_count() > 1:
            return self._multihost_mesh(micro_batch, block_size)
        return self._local_mesh(micro_batch, block_size, fold_pipe=False)

    def _local_mesh(self, micro_batch: int, block_size: int, *,
                    fold_pipe: bool):
        """Single-host mesh from the ``PENROZ_MESH_*`` env family (None =
        single device).  ``fold_pipe=True`` folds the pipe axis into
        ``data`` (forward-only callers: no pipeline schedule to run, so
        the pipe-stage chips serve as extra data-parallel capacity);
        ``fold_pipe=False`` keeps it as a mesh axis.
        """
        try:
            platform = self.device.platform if self.device is not None else None
            devices = (jax.local_devices(backend=platform) if platform
                       else jax.local_devices())
        except RuntimeError:
            return None
        try:
            model = int(os.environ.get("PENROZ_MESH_MODEL", "1"))
            seq = int(os.environ.get("PENROZ_MESH_SEQUENCE", "1"))
            expert = int(os.environ.get("PENROZ_MESH_EXPERT", "1"))
            pipe = int(os.environ.get("PENROZ_MESH_PIPE", "1"))
        except ValueError:
            log.warning("Invalid PENROZ_MESH_MODEL/PENROZ_MESH_SEQUENCE/"
                        "PENROZ_MESH_EXPERT/PENROZ_MESH_PIPE; falling back "
                        "to single device")
            return None
        if model < 1 or seq < 1 or expert < 1 or pipe < 1:
            return None
        if fold_pipe:
            pipe = 1
        else:
            _check_pipe_composition(pipe, seq)
        n = len(devices)
        if n <= 1 or n % (model * seq * expert * pipe):
            return None
        data = n // (model * seq * expert * pipe)
        if micro_batch % data or (seq > 1 and block_size % seq):
            log.info("Mesh fallback to single device: micro-batch %d / "
                     "block %d not divisible by data=%d / sequence=%d",
                     micro_batch, block_size, data, seq)
            return None
        return mesh_lib.make_mesh(devices, model=model, sequence=seq,
                                  expert=expert, pipe=pipe)

    def _eval_mesh(self, batch_size: int, block_size: int):
        """Device mesh for forward-only evaluation (None = single device).

        Same axes as :meth:`_training_mesh` except the ``pipe`` axis is
        folded into ``data``.  Falls back to a single device (never
        raises) on divisibility misses single-host; the multi-host path
        keeps :meth:`_multihost_mesh`'s raise-don't-degrade contract.
        """
        if os.environ.get("PENROZ_TRAIN_MESH", "1") == "0":
            # Unlike training, the mesh-less multi-host eval is still
            # exact: each process averages its own stride and
            # all_reduce_mean combines them — no gradient sync to lose.
            return None
        if dist.process_count() > 1:
            return self._multihost_mesh(batch_size, block_size,
                                        fold_pipe=True)
        return self._local_mesh(batch_size, block_size, fold_pipe=True)

    def _multihost_mesh(self, micro_batch: int, block_size: int = 0,
                        fold_pipe: bool = False):
        """Global mesh spanning every host's devices.

        The data axis is ordered by process (jax.devices() groups by
        process_index), so each host's rank-strided loader rows land on its
        own chips.  PENROZ_MESH_MODEL / PENROZ_MESH_SEQUENCE /
        PENROZ_MESH_EXPERT carve TP/SP/EP axes out of the global device set;
        the resulting cross-host-sharded params/optimizer are persisted via
        per-host shard files (see :meth:`serialize`).

        ``PENROZ_MESH_PIPE>1`` builds the pipe axis *outermost* so each
        GPipe stage occupies a contiguous host group and the stage handoff
        rides DCN (``fold_pipe=True`` — forward-only callers — folds it
        into data capacity instead).  Stages spanning hosts means every
        process feeds the SAME batch (the model, not the data, is what's
        distributed across hosts); train() switches the loader off rank
        striding accordingly.
        """
        world = dist.process_count()
        # Every failure here RAISES: falling back to mesh=None under
        # multi-process would train divergent per-host replicas with no
        # gradient sync while the loader still stripes the data — silent
        # corruption, not degradation.
        platform = self.device.platform if self.device is not None else None
        devices = jax.devices(platform) if platform else jax.devices()
        n = len(devices)
        if n % world:
            raise RuntimeError(f"multi-host training: {n} global devices "
                               f"not divisible by {world} processes")
        try:
            model = int(os.environ.get("PENROZ_MESH_MODEL", "1"))
            seq = int(os.environ.get("PENROZ_MESH_SEQUENCE", "1"))
            expert = int(os.environ.get("PENROZ_MESH_EXPERT", "1"))
        except ValueError as e:
            raise ValueError(f"Invalid mesh-axis env knob: {e}")
        try:
            pipe = int(os.environ.get("PENROZ_MESH_PIPE", "1") or "1")
        except ValueError as e:
            raise ValueError(f"Invalid mesh-axis env knob: {e}")
        if pipe < 1:
            raise ValueError(f"PENROZ_MESH_PIPE={pipe} must be >= 1")
        if fold_pipe:
            pipe = 1
        if pipe > 1:
            _check_pipe_composition(pipe, seq)
            if pipe % world and world % pipe:
                # Stages are contiguous global device ranges (pipe
                # outermost); alignment with process boundaries keeps each
                # ppermute hop a single DCN (or pure-ICI) transfer instead
                # of a shuffle that splits one stage across host fractions.
                raise RuntimeError(
                    f"PENROZ_MESH_PIPE={pipe} must divide or be a multiple "
                    f"of the process count ({world}) so pipeline stages "
                    f"align with host boundaries")
        denom = model * seq * expert * pipe
        if model < 1 or seq < 1 or expert < 1 or n % denom:
            raise ValueError(
                f"multi-host training: {n} global devices not divisible by "
                f"model={model} × sequence={seq} × expert={expert} × "
                f"pipe={pipe}")
        data = n // denom
        if pipe > 1:
            # Every process feeds the same global batch (no rank striding
            # — see train()); the data axis shards those rows within each
            # stage's host group.
            if micro_batch % data:
                raise ValueError(
                    f"multi-host training: batch_size {micro_batch} must "
                    f"be divisible by the data axis ({data}) under "
                    f"PENROZ_MESH_PIPE={pipe}")
        elif (micro_batch * world) % data:
            raise ValueError(
                f"multi-host training: global micro-batch "
                f"{micro_batch * world} (batch_size × processes) must be "
                f"divisible by the data axis ({data})")
        if seq > 1 and block_size and block_size % seq:
            raise ValueError(
                f"multi-host training: block_size {block_size} must be "
                f"divisible by the sequence axis ({seq})")
        return mesh_lib.make_mesh(devices, model=model, sequence=seq,
                                  expert=expert, pipe=pipe,
                                  pipe_outermost=pipe > 1)

    # -- pipeline-parallel training layout ----------------------------------

    def _enter_pipe_layout(self, mesh, batch_size: int):
        """Switch params/opt_state to the GPipe stacked layout.

        The repeated transformer blocks' per-layer params
        ``layers.{i}.<suffix>`` become ``__pipe__.<suffix>`` leaves with a
        leading ``(L, ...)`` dim sharded over the mesh's ``pipe`` axis —
        each stage physically holds only its ``L/P`` blocks (the depth
        analog of TP's width sharding).  Optimizer moment dicts get the
        identical restructuring so the elementwise update math lines up.
        The checkpoint format stays canonical flat: :meth:`serialize`
        converts back via :meth:`_canonical_state`.

        Returns ``(pipe_cfg, epoch_out_shardings)`` where ``pipe_cfg =
        (mesh, start, count, num_microbatches)`` feeds
        :meth:`CompiledArch.train_epoch_fn`.
        """
        from penroz_tpu.parallel import pipeline
        pipe = mesh.shape[mesh_lib.PIPE_AXIS]
        data = mesh.shape[mesh_lib.DATA_AXIS]
        # ZeRO ladder over the stacked layout: PENROZ_WUS=1 data-shards
        # the optimizer moments on a dim the pipe/TP layout leaves free;
        # PENROZ_FSDP=1 shards the stacked params' storage the same way —
        # gpipe_apply's shard_map in_spec (P(pipe), replicated over data)
        # then forces a just-in-time all-gather at the schedule boundary,
        # and its AD transpose reduce-scatters the gradients: ZeRO-3
        # semantics from the resharding rule, no bespoke gather code.
        fsdp = os.environ.get("PENROZ_FSDP", "0") == "1"
        wus = fsdp or os.environ.get("PENROZ_WUS", "0") == "1"
        start, count = pipeline.pipeline_block_range(self.layers_dsl)
        if count < pipe or count % pipe:
            raise RuntimeError(
                f"PENROZ_MESH_PIPE={pipe}: the longest run of identical "
                f"blocks is {count} (need a multiple of the pipe axis); "
                f"this DSL cannot pipeline at that depth")
        # MoE blocks pipeline: balance loss + router fractions travel the
        # schedule's aux channel (gpipe_apply with_aux).  BatchNorm stays
        # refused — its running stats are read AND written per microbatch,
        # a sequential dependency the parallel schedule cannot honor.
        seq = mesh.shape[mesh_lib.SEQ_AXIS]
        if seq > 1 and any(
                jnp.issubdtype(v.dtype, jnp.floating)
                and v.dtype != jnp.float32 for v in self.params.values()):
            # XLA CHECK-fails ("Invalid binary instruction opcode copy",
            # hlo_instruction.cc) compiling the manual pipe×seq program
            # with bf16 parameter leaves — an UNCATCHABLE process abort,
            # reproduced on the CPU backend with a minimal rope stack.
            # Refuse until the toolchain moves; fp32 storage (the
            # non-imported default) is unaffected.
            raise RuntimeError(
                "PENROZ_MESH_PIPE>1 with PENROZ_MESH_SEQUENCE>1 requires "
                "float32 parameter storage (bf16-imported models trip an "
                "XLA compiler abort on this composition); convert the "
                "model or drop one axis")
        for i in range(start, start + count):
            for sub in self.arch.mods[i].walk():
                if isinstance(sub, M.BatchNorm1d):
                    raise RuntimeError(
                        f"PENROZ_MESH_PIPE>1 cannot pipeline blocks with "
                        f"{type(sub).__name__}: running statistics are "
                        f"read and written per microbatch, which the "
                        f"parallel schedule cannot order")
                if seq > 1 and isinstance(sub, M.CausalSelfAttention):
                    if sub.dropout > 0.0:
                        # The manual SP branch (ring or Ulysses)
                        # requires dropout-free attention (same constraint
                        # as the sp_mesh path), but here falling through
                        # would run SHARD-LOCAL attention — silently
                        # wrong, so refuse.
                        raise RuntimeError(
                            "PENROZ_MESH_PIPE>1 with PENROZ_MESH_SEQUENCE"
                            ">1 cannot pipeline attention with dropout>0: "
                            "the sequence-parallel attention path is "
                            "dropout-free")
        base = batch_size // data
        env_m = os.environ.get("PENROZ_PIPE_MICROBATCHES", "")
        if env_m:
            micro = int(env_m)
            if micro < 1 or base % micro:
                raise RuntimeError(
                    f"PENROZ_PIPE_MICROBATCHES={micro} must divide the "
                    f"per-data-shard batch ({base})")
        else:
            # GPipe bubble is (P-1)/(M+P-1): aim for M ≈ 4P, constrained
            # to divide the per-data-shard batch so rows split evenly.
            target = min(base, 4 * pipe)
            micro = next(m for m in range(target, 0, -1) if base % m == 0)
        idx = list(range(start, start + count))
        stacked = pipeline.stack_block_params(self.params, idx)
        block_keys = {f"layers.{i}.{s}" for i in idx for s in stacked}
        mixed = {k: v for k, v in self.params.items() if k not in block_keys}
        mixed.update({f"__pipe__.{s}": v for s, v in stacked.items()})
        pkeys = set(self.params)

        def mix(d: dict) -> dict:
            st = pipeline.stack_block_params(d, idx)
            out = {k: v for k, v in d.items() if k not in block_keys}
            out.update({f"__pipe__.{s}": v for s, v in st.items()})
            return out

        opt_mixed = jax.tree.map(
            lambda n: mix(n) if isinstance(n, dict) and set(n) == pkeys
            else n,
            self.opt_state,
            is_leaf=lambda n: isinstance(n, dict) and set(n) == pkeys)
        repl = mesh_lib.replicated(mesh)

        def pipe_spec(suffix: str):
            # Stacked leaves: leading L dim over `pipe`, trailing dims in
            # the Megatron TP layout of the per-layer leaf (a no-op spec
            # when the model axis is 1) — this is what lets pipe×model
            # meshes train; gpipe_apply leaves the model axis
            # GSPMD-automatic inside the stage body.
            base = sharding_lib.param_spec(
                f"layers.{idx[0]}.{suffix}",
                tuple(stacked[suffix].shape[1:]), mesh)
            return jax.sharding.PartitionSpec(mesh_lib.PIPE_AXIS, *base)

        base_spec = {}
        for k, v in mixed.items():
            if k.startswith("__pipe__."):
                base_spec[k] = pipe_spec(k[len("__pipe__."):])
            else:
                # Non-block params (embeddings, final LN, lm head) take
                # their flat TP layout; replicated when model == 1.
                base_spec[k] = sharding_lib.param_spec(k, tuple(v.shape),
                                                       mesh)

        def with_data(k):
            # ZeRO rule: data axis on the first dim the pipe/TP layout
            # leaves free (sharding._data_axis_spec; no-op when data==1
            # or no dim divides).
            return sharding_lib._data_axis_spec(
                base_spec[k], tuple(mixed[k].shape), mesh)

        param_shd = {k: jax.sharding.NamedSharding(
                         mesh, with_data(k) if fsdp else base_spec[k])
                     for k in mixed}
        moment_shd = {k: jax.sharding.NamedSharding(
                          mesh, with_data(k) if wus else base_spec[k])
                      for k in mixed}
        opt_shd = jax.tree.map(
            lambda n: ({k: moment_shd[k] for k in n}
                       if isinstance(n, dict) and set(n) == set(mixed)
                       else repl),
            opt_mixed,
            is_leaf=lambda n: isinstance(n, dict) and set(n) == set(mixed))
        self.params = {k: sharding_lib.place(v, param_shd[k])
                       for k, v in mixed.items()}
        self.opt_state = sharding_lib.place_tree(opt_mixed, opt_shd)
        self._pipe_layout = (start, count)
        log.info("Pipeline layout: blocks %d..%d stacked over pipe=%d, "
                 "%d microbatch(es)%s", start, start + count - 1, pipe,
                 micro,
                 " + FSDP" if fsdp else (" + WUS" if wus else ""))
        return (mesh, start, count, micro), (param_shd, opt_shd)

    def _canonical_params(self, params=None) -> dict:
        """Flat per-layer param dict regardless of an active pipeline
        layout (the canonical checkpoint/serving key naming)."""
        from penroz_tpu.parallel import pipeline
        params = self.params if params is None else params
        if self._pipe_layout is None:
            return params
        start, count = self._pipe_layout
        idx = list(range(start, start + count))
        stacked = {k[len("__pipe__."):]: v for k, v in params.items()
                   if k.startswith("__pipe__.")}
        flat = {k: v for k, v in params.items()
                if not k.startswith("__pipe__.")}
        flat.update(pipeline.unstack_block_params(stacked, idx))
        return flat

    def _canonical_state(self):
        """(params, opt_state) in the canonical flat layout."""
        if self._pipe_layout is None:
            return self.params, self.opt_state
        mixed_keys = set(self.params)
        opt = jax.tree.map(
            lambda n: (self._canonical_params(n)
                       if isinstance(n, dict) and set(n) == mixed_keys
                       else n),
            self.opt_state,
            is_leaf=lambda n: isinstance(n, dict) and set(n) == mixed_keys)
        return self._canonical_params(), opt

    def _exit_pipe_layout(self, local_only: bool = False):
        """Restore the canonical flat layout after a pipelined train run.

        ``local_only=True`` (the error path, where hosts arrive
        independently): skip the conversion when stacked leaves are
        cross-host sharded — unstacking them is a collective, and running
        it one-sided would hang until the comm timeout.  The model object
        keeps its stacked layout; the next operation reloads from the last
        coordinated checkpoint.
        """
        if self._pipe_layout is None:
            return
        if local_only and not all(self._is_host_readable(v)
                                  for v in self.params.values()):
            log.warning("Keeping pipeline-stacked layout: cross-host "
                        "shards cannot be restored one-sidedly")
            return
        self.params, self.opt_state = self._canonical_state()
        self._pipe_layout = None

    @classmethod
    def train_model_on_device(cls, model_id, device, dataset_id, shard,
                              epochs, batch_size, block_size, step_size,
                              adapter=None):
        """Worker entry: deserialize → place → train (reference DDP worker:
        neural_net_model.py:516-550, minus the process tree — one process
        owns the TPU runtime and the mesh handles per-chip parallelism).

        ``PENROZ_TRAIN_WORKER=1`` (single-host only) instead trains in a
        CHILD process — the reference's crash-containment shape
        (main.py:461-464 spawns ``mp.Process``): a native crash in
        training (XLA abort, OOM kill, libtpu segfault) kills the worker,
        never the serving process.  State flows through the existing
        checkpoint stream (the worker serializes every ~10s; every API
        route deserializes), so /progress/ and /stats/ keep updating
        while the worker runs.  Caveat: a real TPU chip is single-process
        — worker mode fits deployments where training owns the
        accelerator and the parent serves from CPU/another chip, or
        relay backends that multiplex; it is opt-in for exactly that
        reason.
        """
        if (os.environ.get("PENROZ_TRAIN_WORKER", "0") == "1"
                and dist.process_count() == 1):
            return cls._train_in_worker_process(
                model_id, device, dataset_id, shard, epochs, batch_size,
                block_size, step_size, adapter=adapter)
        model = cls.deserialize(model_id)
        model.to_device(device)
        if adapter is not None:
            # LoRA fine-tune: the base stays frozen, only the adapter tree
            # trains, and the checkpoint written is adapter-only
            # (models/lora.py) — registry-loadable the moment it lands.
            from penroz_tpu.models import lora
            lora.train_adapter(model, adapter["adapter_id"], adapter,
                               dataset_id, shard=shard, epochs=epochs,
                               batch_size=batch_size, block_size=block_size,
                               step_size=step_size)
            return model
        model.train_model(dataset_id, shard=shard, epochs=epochs,
                          batch_size=batch_size, block_size=block_size,
                          step_size=step_size)
        return model

    @classmethod
    def _train_in_worker_process(cls, model_id, device, dataset_id, shard,
                                 epochs, batch_size, block_size, step_size,
                                 adapter=None):
        """Run the training job in a subprocess and contain its crashes.

        The parent blocks (callers already run this on an executor
        thread), watches the worker, and post-mortems the checkpoint: a
        worker that died mid-run leaves status ``Training`` behind, which
        the parent rewrites to ``Error`` — the same contract as the
        startup orphan sweep (serve/app.py::_sweep_orphaned_training),
        applied the moment the death is observed instead of at the next
        restart."""
        import subprocess
        import sys
        args = {"model_id": model_id, "device": device,
                "dataset_id": dataset_id, "shard": shard, "epochs": epochs,
                "batch_size": batch_size, "block_size": block_size,
                "step_size": step_size, "adapter": adapter}
        env = dict(os.environ)
        env.pop("PENROZ_TRAIN_WORKER", None)  # the child trains in-process
        from penroz_tpu.utils import checkpoint
        env["PENROZ_SHM_PATH"] = checkpoint.SHM_PATH
        # The child runs in the parent's cwd (model/data folders are
        # relative), which need not contain the package — resolve imports
        # from this install's location.
        repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        prev = env.get("PYTHONPATH", "")
        env["PYTHONPATH"] = repo + (os.pathsep + prev if prev else "")
        proc = subprocess.Popen(
            [sys.executable, "-m", "penroz_tpu.models.train_worker",
             json.dumps(args)], env=env, cwd=os.getcwd())
        _TRAIN_WORKERS[model_id] = proc
        try:
            rc = proc.wait()
        finally:
            _TRAIN_WORKERS.pop(model_id, None)
        if adapter is not None:
            cls._post_mortem_adapter_worker(adapter["adapter_id"], rc)
            return cls.deserialize(model_id)
        model = cls.deserialize(model_id)
        if rc != 0 and model.status.get("code") == "Training":
            log.error("Training worker for model %s died (rc=%s); marking "
                      "Error", model_id, rc)
            model.status = {
                "code": "Error",
                "message": f"Training worker died (rc={rc}); last "
                           f"checkpoint retained"}
            model.serialize(sync_flush=True)
        elif rc != 0:
            # Clean Python-level failure: the child already recorded status
            # Error and exited 1 — the parent still logs the death so a
            # fleet operator sees it without polling /progress/.
            log.error("Training worker for model %s exited rc=%s "
                      "(status %s)", model_id, rc,
                      model.status.get("code"))
        return model

    @staticmethod
    def _post_mortem_adapter_worker(adapter_id: str, rc: int):
        """Adapter-run analog of the base post-mortem: a worker that died
        mid-run leaves the ADAPTER blob saying 'Training' — rewrite it to
        Error; a clean failure (status already Error, rc=1) is logged."""
        try:
            blob = checkpoint.load_adapter(adapter_id)
        except KeyError:
            if rc != 0:
                log.error("Adapter-training worker for %s died (rc=%s) "
                          "before writing any checkpoint", adapter_id, rc)
            return
        code = (blob.get("status") or {}).get("code")
        if rc != 0 and code == "Training":
            log.error("Adapter-training worker for %s died (rc=%s); "
                      "marking Error", adapter_id, rc)
            blob["status"] = {
                "code": "Error",
                "message": f"Training worker died (rc={rc}); last "
                           f"checkpoint retained"}
            checkpoint.save_adapter(adapter_id, blob, sync_flush=True)
        elif rc != 0:
            log.error("Adapter-training worker for %s exited rc=%s "
                      "(status %s)", adapter_id, rc, code)

    def _compute_stats(self, x, y) -> dict:
        """/stats/ histograms from one host-local micro-batch.

        Under multi-host the params are global arrays spanning hosts; the
        instrumented pass runs process-locally on this host's copy of the
        (replicated) params with its local sub-batch — the reference always
        produces stats on master (neural_net_model.py:705-709), so a
        master-local sample preserves the feature instead of skipping it.
        """
        # Raw-layout readability check BEFORE the canonical conversion:
        # with a pipeline-stacked layout active on a multi-host mesh, the
        # unstack is itself a collective and stats run master-only — a
        # one-sided dispatch would hang against peers that never join.
        if any(not self._is_host_readable(v)
               for v in self.params.values()):
            log.info("Skipping stats capture: params sharded across hosts")
            return self.stats
        params, buffers = self._canonical_params(), self.buffers
        if any(not getattr(v, "is_fully_addressable", True)
               for v in params.values()):
            if not all(getattr(v, "is_fully_replicated", True)
                       for v in params.values()):
                log.info("Skipping stats capture: params sharded across "
                         "hosts")
                return self.stats
            dev = jax.local_devices()[0]
            params = {k: jax.device_put(np.asarray(v), dev)
                      for k, v in params.items()}
            buffers = {k: jax.device_put(np.asarray(v), dev)
                       for k, v in buffers.items()}
        acts, act_grads, weight_grads = self.arch.stats_grads(
            params, buffers, x, y, platform=self._platform)
        acts_np = [np.asarray(a, np.float32) for a in acts]
        grads_np = [np.asarray(g, np.float32) for g in act_grads]
        weights = [np.asarray(params[k], np.float32)
                   for k in self.arch.param_order]
        wgrads = [np.asarray(weight_grads[k], np.float32)
                  for k in self.arch.param_order]
        return stats_lib.build_stats(self.arch.algos, acts_np, grads_np,
                                     weights, wgrads)

    # -- generation ---------------------------------------------------------

    def _kv_dtype(self):
        dt = self.dtype
        return dt if jnp.issubdtype(dt, jnp.floating) else jnp.float32

    def _decode_mesh(self, batch: int = 1):
        """Device mesh for generation (None = single-device decode).

        TP-sharded decode: attention-head K/V buffers and the Megatron
        weight layout shard over ``model``, stacked MoE expert weights
        over ``expert``, sampling replicated — so an imported model larger
        than one chip's HBM can *serve*, not just train/evaluate
        (reference decode is single-device too: neural_net_model.py:
        360-406; this is the beyond-parity axis).  A single stream has no
        data axis; the BATCHED path additionally shards rows over ``data``
        when ``PENROZ_DECODE_DP=1`` and the batch divides the leftover
        devices (throughput scaling for /generate_batch/ — opt-in so
        multi-device hosts don't silently change decode placement).
        Gated to the contiguous fp/bf16 cache — the paged and int8
        layouts keep single-device decode (their block tables and scale
        planes have no mesh layout yet).
        """
        if dist.process_count() > 1:
            return None  # serving is per-host; the API serves local chips
        if os.environ.get("PENROZ_TRAIN_MESH", "1") == "0":
            return None
        if KV.paged_enabled() or KV.turbo_quant_enabled():
            return None
        try:
            model = int(os.environ.get("PENROZ_MESH_MODEL", "1"))
            expert = int(os.environ.get("PENROZ_MESH_EXPERT", "1"))
        except ValueError:
            log.warning("Invalid PENROZ_MESH_MODEL/PENROZ_MESH_EXPERT; "
                        "falling back to single-device decode")
            return None
        if model < 1 or expert < 1:
            return None
        try:
            platform = (self.device.platform if self.device is not None
                        else None)
            devices = (jax.local_devices(backend=platform) if platform
                       else jax.local_devices())
        except RuntimeError:
            return None
        if len(devices) < model * expert:
            return None
        dp = 1
        if (batch > 1
                and os.environ.get("PENROZ_DECODE_DP", "0") == "1"):
            leftover = len(devices) // (model * expert)
            dp = next((d for d in range(min(leftover, batch), 0, -1)
                       if batch % d == 0), 1)
        if model * expert * dp <= 1:
            return None
        return mesh_lib.make_mesh(devices[:model * expert * dp],
                                  model=model, expert=expert)

    def _kv_sharding_tree(self, kv, mesh, batch: int = 1):
        """Sharding pytree for a contiguous KVState: (B, Hkv, S, D) leaves
        shard heads over ``model`` when every attention layer's KV head
        count divides the axis (GQA models with few KV heads stay
        replicated — a torn head is worse than a copied cache) and rows
        over ``data`` when the batch divides it; lengths and scalars
        replicate."""
        from jax.sharding import PartitionSpec as P
        tp = mesh.shape[mesh_lib.MODEL_AXIS]
        dp = mesh.shape[mesh_lib.DATA_AXIS]
        heads_ok = all(h % tp == 0 for h, _ in self.arch.kv_specs)
        # Row sharding stays behind the PENROZ_DECODE_DP opt-in even here:
        # the live branch hands this a TRAINING mesh whose data axis the
        # decode-mesh gate never saw, and rows silently sharding over it
        # is exactly the placement surprise the opt-in exists to prevent.
        dp_rows = (dp > 1 and batch % dp == 0
                   and os.environ.get("PENROZ_DECODE_DP", "0") == "1")
        kv_spec = P(mesh_lib.DATA_AXIS if dp_rows else None,
                    mesh_lib.MODEL_AXIS if heads_ok and tp > 1 else None,
                    None, None)

        def leaf_sharding(leaf):
            spec = kv_spec if getattr(leaf, "ndim", 0) == 4 else P()
            return jax.sharding.NamedSharding(mesh, spec)

        return jax.tree.map(leaf_sharding, kv)

    def _enter_decode_mesh(self, kv, batch: int = 1):
        """Place params/buffers/cache for mesh decode; returns the placed
        cache (identity when no decode mesh is configured)."""
        mesh = self._decode_mesh(batch)
        if mesh is None:
            return kv
        if any(k.startswith("__pipe__") for k in self.params):
            return kv  # mid-pipeline-training layout: leave decode alone
        live = [v for v in self.params.values()
                if isinstance(getattr(v, "sharding", None),
                              jax.sharding.NamedSharding)
                and len(v.sharding.device_set) > 1]
        if live:
            # Params already live on a (training/eval) mesh — do NOT
            # reshard them: gathering ZeRO-3 storage onto the decode
            # submesh could OOM the exact models FSDP exists for, and a
            # decode interleaving with mesh training would flip layouts
            # every time (full param copy + micro-step recompile).  GSPMD
            # decodes fine on the existing layout; only the fresh KV
            # cache follows that mesh.
            return jax.device_put(
                kv, self._kv_sharding_tree(kv, live[0].sharding.mesh,
                                           batch))
        log.info("Generating over device mesh %s", dict(mesh.shape))
        self.params = sharding_lib.shard_params(self.params, mesh)
        self.buffers = {
            k: sharding_lib.place(v, mesh_lib.replicated(mesh))
            for k, v in self.buffers.items()}
        return jax.device_put(kv, self._kv_sharding_tree(kv, mesh, batch))

    def _serve_mesh(self):
        """Serving mesh for a continuous-batching DecodeEngine (None =
        single-device, today's layout).  Opt-in via ``PENROZ_SERVE_MESH=1``
        with ``PENROZ_SERVE_MESH_MODEL`` tensor-parallel devices — unlike
        :meth:`_decode_mesh` this path DOES cover the paged and int8
        layouts (the page pools shard their head dim; block tables and
        allocator counters stay replicated, the scheduler keeps authoring
        them host-side)."""
        if os.environ.get("PENROZ_SERVE_MESH", "0") != "1":
            return None
        if dist.process_count() > 1:
            return None  # engines are per-host; scale-out is the router
        try:
            model = int(os.environ.get("PENROZ_SERVE_MESH_MODEL", "1"))
        except ValueError:
            log.warning("Invalid PENROZ_SERVE_MESH_MODEL; serving "
                        "single-device")
            return None
        if model < 1:
            return None
        try:
            platform = (self.device.platform if self.device is not None
                        else None)
            devices = (jax.local_devices(backend=platform) if platform
                       else jax.local_devices())
        except RuntimeError:
            return None
        if len(devices) < model:
            log.warning("PENROZ_SERVE_MESH_MODEL=%d exceeds %d local "
                        "devices; serving single-device", model,
                        len(devices))
            return None
        return mesh_lib.serve_mesh(model=model, devices=devices)

    def enter_serve_mesh(self, kv, pipe=None):
        """Place params/buffers and a DecodeEngine's freshly allocated KV
        state on the serving mesh (``PENROZ_SERVE_MESH=1``).  Returns
        ``(kv, devices)`` where ``devices`` is the mesh size (1 when
        unmeshed).  A 1-device mesh is numerically a GSPMD no-op —
        token-identical to the unmeshed engine — which is what lets the
        CPU tier-1 parity matrix keep proving correctness for the sharded
        serving path.

        A model still in the ``__pipe__`` stacked layout from a pipelined
        train run is restored to the canonical flat layout first (the
        decode programs address ``layers.{i}.*``) — serving no longer
        refuses the layout; only cross-host stacked shards (where the
        unstack would be a one-sided collective) are left alone.

        ``pipe`` (a :class:`ServePipeline`) switches to stage-partitioned
        placement: each stage's params/buffers and its slice of the paged
        pools land on that stage's own mesh
        (``parallel.mesh.serve_stage_meshes`` ×
        ``PENROZ_SERVE_MESH_MODEL`` TP width per stage)."""
        if any(k.startswith("__pipe__") for k in self.params):
            if all(self._is_host_readable(v)
                   for v in self.params.values()):
                log.info("Restoring flat layer layout from __pipe__ "
                         "stacked params for serving")
                self._exit_pipe_layout()
            else:
                return kv, 1  # cross-host stacked shards: leave alone
        if pipe is not None:
            return self._enter_serve_pipe_mesh(kv, pipe)
        mesh = self._serve_mesh()
        if mesh is None:
            return kv, 1
        live = [v for v in self.params.values()
                if isinstance(getattr(v, "sharding", None),
                              jax.sharding.NamedSharding)
                and len(v.sharding.device_set) > 1]
        if live:
            # Same rule as _enter_decode_mesh: params already living on a
            # multi-device (training/eval) mesh are NOT reshuffled —
            # gathering ZeRO-3 storage could OOM the exact models FSDP
            # exists for.  The engine's KV simply follows that mesh.
            mesh = live[0].sharding.mesh
        else:
            log.info("Serving over device mesh %s", dict(mesh.shape))
            self.params = sharding_lib.shard_params(self.params, mesh)
            self.buffers = {
                k: sharding_lib.place(v, mesh_lib.replicated(mesh))
                for k, v in self.buffers.items()}
        if isinstance(kv, KV.PagedKVState):
            tree = sharding_lib.paged_kv_sharding_tree(
                kv, mesh, self.arch.kv_specs)
        else:
            tree = self._kv_sharding_tree(kv, mesh)
        return jax.device_put(kv, tree), mesh.size

    def _enter_serve_pipe_mesh(self, kv, pipe):
        """Stage-partitioned placement for one pipeline group: stage ``s``
        gets its params/buffers sharded over its own TP mesh and its
        ``kv_bounds[s]`` slice of the paged pools placed there
        (parallel/sharding.py::paged_kv_stage_shard) — per-device KV HBM
        drops ~1/S.  On a host with fewer devices than ``stages × model``
        every stage collapses onto the same devices: the partition,
        schedule and numerics are identical and placement is skipped (the
        CPU parity suite rides this degenerate layout)."""
        model = 1
        if os.environ.get("PENROZ_SERVE_MESH", "0") == "1":
            try:
                model = max(1, int(os.environ.get(
                    "PENROZ_SERVE_MESH_MODEL", "1")))
            except ValueError:
                model = 1
        try:
            platform = (self.device.platform if self.device is not None
                        else None)
            devices = (jax.local_devices(backend=platform) if platform
                       else jax.local_devices())
        except RuntimeError:
            return kv, 1
        meshes = mesh_lib.serve_stage_meshes(pipe.stages, model=model,
                                             devices=devices)
        distinct = {d for m in meshes for d in np.asarray(m.devices).flat}
        if len(distinct) <= 1:
            pipe.meshes = None
            return kv, 1  # degenerate single-device group: no-op layout
        pipe.meshes = meshes
        log.info("Serving pipeline group: %d stages × %d-wide TP over "
                 "%d devices", pipe.stages, model, len(distinct))
        new_params = dict(self.params)
        new_buffers = dict(self.buffers)
        for s, mesh in enumerate(meshes):
            new_params.update(sharding_lib.shard_params(
                {k: v for k, v in self.params.items()
                 if self._stage_owns(pipe, s, k)}, mesh))
            new_buffers.update({
                k: sharding_lib.place(v, mesh_lib.replicated(mesh))
                for k, v in self.buffers.items()
                if self._stage_owns(pipe, s, k)})
        self.params, self.buffers = new_params, new_buffers
        if isinstance(kv, KV.PagedKVState):
            kv = sharding_lib.paged_kv_stage_shard(
                kv, meshes, pipe.kv_bounds, self.arch.kv_specs)
        return kv, len(distinct)

    @staticmethod
    def _stage_owns(pipe, s: int, key: str) -> bool:
        """Whether flat param/buffer ``key`` belongs to stage ``s``
        (non-``layers.`` keys ride with stage 0 — prologue state)."""
        if not key.startswith("layers."):
            return s == 0
        lo, hi = pipe.bounds[s]
        try:
            i = int(key[len("layers."):].split(".", 1)[0])
        except ValueError:
            return s == 0
        return lo <= i < hi

    def _kv_specs(self, batch: int = 1, max_len: int = 0):
        return self.arch.kv_specs

    def _generate_iter(self, context: list[int], block_size: int,
                       max_new_tokens: int, temperature: float,
                       top_k: Optional[int], metrics: Optional[KV.KVCache],
                       ramp: bool = False):
        """Yield new tokens one at a time, appending each to ``context``.

        Chunked, pipelined decode: one (re)prefill dispatch, then up to
        ``PENROZ_DECODE_CHUNK`` fused decode+sample steps per dispatch.  The
        next chunk is dispatched *before* the previous chunk's tokens are
        transferred to the host — the last sampled token stays on-device as
        the next chunk's input, so host-side conversion/yielding overlaps
        the device compute (a chunk dispatched past a ``stop_token`` is
        simply abandoned).  When the cache fills, the context is cropped
        and re-prefilled (reference overflow path:
        neural_net_model.py:375-389); the re-prefill needs the full host
        context, so the pipeline drains at that boundary.

        Chunk sizes are powers of two (bounded set of compiled programs).
        A tail shorter than its pow-2 ceiling dispatches the *ceiling* and
        discards the overshoot — a few wasted decode steps are far cheaper
        than the extra dispatch round-trips the descending pow-2
        decomposition would pay (e.g. 95 tail tokens = one 128-chunk, not
        64+16+8+4+2+1).  ``ramp=True`` (streaming) starts at 8 and doubles
        per dispatch so early tokens flow without waiting on a full chunk.
        """
        greedy, temp, call_rng = self._sampling_setup(temperature)
        chunk_budget = _chunk_budget()
        ramp_budget = 8 if ramp else chunk_budget
        decode = self.arch.decode_fn()
        # Cache layout (contiguous / paged / int8) is env-configured; the
        # contiguous decode kernel streams K/V tiles through its grid, so
        # long contexts need no auto-paging heuristic.
        kv = KV.create_kv_state(self.arch.kv_specs, 1, block_size,
                                self._kv_dtype(),
                                ssm_specs=self.arch.ssm_specs)
        kv = self._enter_decode_mesh(kv)
        cache_len = 0
        produced = 0    # tokens yielded to the caller
        dispatched = 0  # tokens sampled on-device (may run one chunk ahead)
        dispatch = 0
        last_dev = None  # (B, n) device tokens of the newest chunk
        pending = None   # (device tokens, count, dispatch time) to flush

        def flush(entry):
            nonlocal produced
            arr, count, dispatch_ms, logical, stored, state = entry
            t_wait = time.monotonic()
            toks = [int(t) for t in np.asarray(arr)[0][:count]]
            if metrics is not None:
                # dispatch (trace/enqueue) time of THIS chunk + the blocking
                # wait for its results; bytes captured at enqueue so a
                # pipelined successor's growth isn't charged to this chunk.
                wait_ms = (time.monotonic() - t_wait) * 1000
                metrics.record_step(count, logical, stored,
                                    dispatch_ms + wait_ms)
                metrics.final_state = state
            for tok in toks:
                context.append(tok)
                produced += 1
                yield tok
                if produced >= max_new_tokens:
                    return

        while produced < max_new_tokens:
            new_pending = None
            if dispatched < max_new_tokens:
                at_boundary = cache_len == 0 or cache_len >= block_size
                if at_boundary and pending is not None:
                    # Re-prefill reads context from the host: drain first.
                    yield from flush(pending)
                    pending = None
                    if produced >= max_new_tokens:
                        break
                    at_boundary = cache_len == 0 or cache_len >= block_size
                t0 = time.monotonic()
                rng = jax.random.fold_in(call_rng, dispatch)
                if at_boundary:
                    with profiling.span("penroz/prefill"):
                        kv = kv.reset()
                        feed = context[-block_size:]
                        x = jnp.asarray(np.asarray(feed, np.int64)[None, :],
                                        jnp.int32)
                        tok_arr, kv = decode(self.params, self.buffers, kv,
                                             x, rng, temp, greedy=greedy,
                                             top_k=top_k,
                                             platform=self._platform)
                        cache_len = len(feed)
                        new_pending = (tok_arr, 1,
                                       (time.monotonic() - t0) * 1000,
                                       kv.logical_bytes(), kv.memory_bytes(),
                                       kv)
                        last_dev = tok_arr
                        dispatched += 1
                else:
                    with profiling.span("penroz/decode_chunk"):
                        room = block_size - cache_len
                        remaining = max_new_tokens - dispatched
                        chunk = _decode_chunk_size(
                            remaining, min(chunk_budget, ramp_budget, room))
                        count = min(chunk, remaining)
                        toks_arr, kv = self.arch.decode_chunk(
                            self.params, self.buffers, kv,
                            last_dev[:, -1:], rng, temp, chunk=chunk,
                            greedy=greedy, top_k=top_k,
                            platform=self._platform)
                        cache_len += chunk
                        new_pending = (toks_arr, count,
                                       (time.monotonic() - t0) * 1000,
                                       kv.logical_bytes(), kv.memory_bytes(),
                                       kv)
                        last_dev = toks_arr
                        dispatched += count
                        ramp_budget = min(ramp_budget * 2, chunk_budget)
                dispatch += 1
            # Host conversion of the previous chunk overlaps the dispatch
            # above, which is still executing on-device.
            if pending is not None:
                yield from flush(pending)
            pending = new_pending
        if pending is not None and produced < max_new_tokens:
            yield from flush(pending)

    def generate_tokens_batched(self, inputs, block_size, max_new_tokens,
                                temperature=1.0, top_k=None,
                                stop_token=None) -> list[list[int]]:
        """RAGGED batched generation — N prompts of different lengths share
        one forward per step (beyond the reference, whose generate path is
        single-sequence: neural_net_model.py:457-479).

        Right-padded batched prefill (each row samples at its own last
        prompt position), then per-sequence cache lengths drive ragged
        decode: every row's K/V append, RoPE/position offset, and
        attention mask use that row's own length (ops/kv_cache.py
        ``with_lengths``, the ragged kernels/oracle).  Greedy outputs are
        bit-identical to N separate ``generate_tokens`` calls (tested).

        Contract: ``max(prompt) + max_new_tokens <= block_size`` — the
        batched path has no overflow crop/re-prefill.  Honors the same
        paged/int8 env flags as the single-sequence path (every cache
        variant supports ragged per-sequence lengths).
        """
        prompts = [[int(t) for t in (row if isinstance(row, (list, tuple))
                                     else [row])] for row in inputs]
        validate_batch_generation(prompts, block_size, max_new_tokens)
        B = len(prompts)
        lens = [len(p) for p in prompts]
        max_p = max(lens)
        greedy, temp, call_rng = self._sampling_setup(temperature)
        # Same compute dtype as the single-sequence decode path (its
        # decode_fn default) — anything else would break the documented
        # batched ≡ single greedy parity on near-tied logits.
        compute_dtype = None
        arch = self.arch

        key = ("bprefill", bool(greedy), top_k, str(compute_dtype),
               self._platform)
        prefill = arch._jit_cache.get(key)
        if prefill is None:
            def prefill_fn(p, bufs, kv0, toks, lengths, r, tmp):
                acts, _, _, kv1 = arch.forward(
                    p, bufs, toks, None, kv=kv0, skip_softmax=True,
                    compute_dtype=compute_dtype, platform=self._platform)
                logits = acts[-1]
                last = jnp.take_along_axis(
                    logits, (lengths - 1)[:, None, None], axis=1)[:, 0]
                tok = arch._sample(last, r, tmp, greedy=greedy, top_k=top_k)
                return tok, kv1.with_lengths(lengths)
            prefill = arch._jit_cache[key] = jax.jit(
                prefill_fn, donate_argnums=(2,))

        outs = [list(p) for p in prompts]
        if max_new_tokens <= 0:
            return outs
        padded = np.zeros((B, max_p), np.int32)
        for i, p in enumerate(prompts):
            padded[i, :len(p)] = p
        # Same env-flag factory as the single-sequence path: paged / int8
        # pools do ragged batches too (per-sequence lengths thread through
        # the allocator, appends, and the ragged kernels).
        kv = KV.create_kv_state(arch.kv_specs, B, block_size,
                                self._kv_dtype(),
                                ssm_specs=arch.ssm_specs)
        kv = self._enter_decode_mesh(kv, batch=B)
        lengths = jnp.asarray(lens, jnp.int32)
        done = [False] * B

        def absorb(arr):
            for i, t in enumerate(arr):
                if not done[i]:
                    outs[i].append(int(t))
                    done[i] = (stop_token is not None
                               and int(t) == stop_token)

        with decode_priority():
            prev, kv = prefill(self.params, self.buffers, kv,
                               jnp.asarray(padded), lengths,
                               jax.random.fold_in(call_rng, 0), temp)
            absorb(np.asarray(prev))
            # Fused chunked decode (same scan programs as _generate_iter's
            # decode_chunk, same pow-2-ceiling tails): up to
            # PENROZ_DECODE_CHUNK steps per dispatch instead of one.  The
            # overshoot bound uses the longest prompt, which every row's
            # capacity satisfies (validated above); tokens scanned past an
            # all-rows stop are abandoned.  With a stop_token, ramp from 8
            # doubling per dispatch (as the streaming path does) so an
            # early stop wastes at most the current ramp chunk, not a full
            # budget of fused steps.
            chunk_budget = _chunk_budget()
            ramp_budget = 8 if stop_token is not None else chunk_budget
            last = prev[:, None]
            dispatched = 1
            while dispatched < max_new_tokens and not all(done):
                remaining = max_new_tokens - dispatched
                room = block_size - max_p - dispatched
                chunk = _decode_chunk_size(
                    remaining, min(chunk_budget, ramp_budget, room))
                count = min(chunk, remaining)
                ramp_budget = min(ramp_budget * 2, chunk_budget)
                toks, kv = arch.decode_chunk(
                    self.params, self.buffers, kv, last,
                    jax.random.fold_in(call_rng, dispatched), temp,
                    chunk=chunk, greedy=greedy, top_k=top_k,
                    platform=self._platform)
                arr = np.asarray(toks)[:, :count]
                for col in range(count):
                    absorb(arr[:, col])
                    if all(done):
                        break
                last = toks[:, -1:]
                dispatched += count
        return outs

    # -- step-wise decode API (continuous-batching scheduler) ---------------

    @staticmethod
    def _norm_temperature(temperature):
        """(greedy, temp scalar) with the same None/0.0 → greedy rule as
        ``_sampling_setup`` (no rng split — the scheduler owns its rng)."""
        greedy = temperature is None or float(temperature) == 0.0
        temp = jnp.asarray(float(temperature) if temperature else 1.0,
                           jnp.float32)
        return greedy, temp

    def decode_prefill_single(self, prompt: list[int], block_size: int,
                              rng, temperature=1.0, top_k=None):
        """Prefill one prompt into a fresh batch-1 KV state and sample its
        first token — the exact program the single-sequence generate loop
        dispatches (``_generate_iter``'s prefill), so the first token of a
        scheduler-admitted request is identical to the standalone path.
        Returns ``(first_token:int, kv_single, fed_len:int)``."""
        greedy, temp = self._norm_temperature(temperature)
        decode = self.arch.decode_fn()
        kv = KV.create_kv_state(self.arch.kv_specs, 1, block_size,
                                self._kv_dtype(),
                                ssm_specs=self.arch.ssm_specs)
        feed = prompt[-block_size:]
        x = jnp.asarray(np.asarray(feed, np.int64)[None, :], jnp.int32)
        tok_arr, kv = decode(self.params, self.buffers, kv, x, rng, temp,
                             greedy=greedy, top_k=top_k,
                             platform=self._platform)
        return int(np.asarray(tok_arr)[0, 0]), kv, len(feed)

    def decode_prefill_chunk(self, kv_batch, row: int, tokens, row_len: int,
                             rng, temperature=1.0, top_k=None, lora=None,
                             adapter_slot: int = 0):
        """Feed one prompt chunk for row ``row`` directly into the multi-row
        decode state — the chunked-prefill dispatch the scheduler interleaves
        between shared decode steps so a long prompt never stalls the batch
        for more than one chunk.

        ``tokens`` (T,) extends the row's KV from valid length ``row_len``
        (positions ``row_len + [0, T)``): the chunk attends the row's
        existing cache (including any radix-aliased prefix pages on the
        paged variants) through the same ``cached_attention`` program family
        as one-shot prefill, and its K/V appends land in the row's own
        pages/buffers via ``KVState.row_view``/``merge_row``.  Returns
        ``(sampled_token:int, kv_batch')`` — the token is the greedy/sampled
        continuation at the chunk's last position, i.e. the request's first
        generated token when this was the final chunk (identical to the
        one-shot path: same logits position, same program family).  Jits per
        (T, cache type, sampling); keep chunk sizes power-of-two-bucketed so
        the program set stays bounded.  Donates ``kv_batch`` — always thread
        the returned state.
        """
        greedy, temp = self._norm_temperature(temperature)
        arch = self.arch
        T = len(tokens)
        key = ("prefill_chunk", T, type(kv_batch).__name__, bool(greedy),
               top_k, self._platform)
        fn = arch._jit_cache.get(key)
        if fn is None:
            platform = self._platform

            def chunk_step(p, b, kvb, toks, r_idx, r_len, r, tmp, lo, ai):
                view = kvb.row_view(r_idx, r_len)
                tok, view2 = arch._decode_step(p, b, view, toks, r, tmp,
                                               greedy=greedy, top_k=top_k,
                                               compute_dtype=None,
                                               platform=platform,
                                               lora=lo, lora_idx=ai)
                return tok[0, 0], kvb.merge_row(r_idx, view2)

            fn = arch._jit_cache[key] = jax.jit(chunk_step,
                                                donate_argnums=(2,))
        x = jnp.asarray(np.asarray(tokens, np.int64)[None, :], jnp.int32)
        aidx = (jnp.asarray([adapter_slot], jnp.int32)
                if lora is not None else None)
        with profiling.span("penroz/decode_prefill_chunk"):
            tok, kv_out = fn(self.params, self.buffers, kv_batch, x,
                             jnp.asarray(row, jnp.int32),
                             jnp.asarray(row_len, jnp.int32), rng, temp,
                             lora, aidx)
        return int(np.asarray(tok)), kv_out

    def decode_verify_row(self, kv_batch, row: int, tokens, row_len: int,
                          rng, temperature=1.0, top_k=None, lora=None,
                          adapter_slot: int = 0):
        """Speculative-decoding verify step for one row: one forward over
        the row's T candidate tokens (``tokens[0]`` is the last sampled
        token, the rest a drafter's proposals), sampling at EVERY position.

        Same program family and write path as :meth:`decode_prefill_chunk`
        (``row_view``/``merge_row`` over all four cache variants, appends
        at ``row_len + [0, T)``) — the only difference is that all T
        sampled tokens come back instead of the last one, so the scheduler
        can accept the longest greedy-matching prefix and roll the row's
        KV back past the rejected positions (``KVState.rollback_row``;
        lengths here stay host-authoritative exactly as in the chunk
        path).  Returns ``(list[int] of T sampled tokens, kv_batch')``.
        Jits per (T, cache type, sampling) — keep draft lengths
        power-of-two-bucketed so the program set stays bounded.  Donates
        ``kv_batch`` — always thread the returned state.
        """
        greedy, temp = self._norm_temperature(temperature)
        arch = self.arch
        T = len(tokens)
        key = ("verify_row", T, type(kv_batch).__name__, bool(greedy),
               top_k, self._platform)
        fn = arch._jit_cache.get(key)
        if fn is None:
            platform = self._platform

            def verify_step(p, b, kvb, toks, r_idx, r_len, r, tmp, lo, ai):
                view = kvb.row_view(r_idx, r_len)
                acts, _, _, view2 = arch.forward(
                    p, b, toks, None, kv=view, pos_offset=view.length,
                    skip_softmax=True, compute_dtype=None,
                    platform=platform, lora=lo, lora_idx=ai)
                logits = acts[-1]          # (1, T, V)
                out = arch._sample(logits[0], r, tmp, greedy=greedy,
                                   top_k=top_k)          # (T,)
                return out, kvb.merge_row(r_idx, view2)

            fn = arch._jit_cache[key] = jax.jit(verify_step,
                                                donate_argnums=(2,))
        x = jnp.asarray(np.asarray(tokens, np.int64)[None, :], jnp.int32)
        aidx = (jnp.asarray([adapter_slot], jnp.int32)
                if lora is not None else None)
        with profiling.span("penroz/decode_verify_row"):
            out, kv_out = fn(self.params, self.buffers, kv_batch, x,
                             jnp.asarray(row, jnp.int32),
                             jnp.asarray(row_len, jnp.int32), rng, temp,
                             lora, aidx)
        return [int(t) for t in np.asarray(out)], kv_out

    def decode_insert_row(self, kv_batch, row: int, kv_single):
        """Jitted per-row admission: drop a prefilled batch-1 state into
        row ``row`` of the persistent multi-row decode cache
        (``ops.kv_cache.KVState.insert_row``).  One compiled program covers
        every slot — ``row`` is traced.  Donates ``kv_batch``."""
        key = ("insert_row", type(kv_batch).__name__, self._platform)
        fn = self.arch._jit_cache.get(key)
        if fn is None:
            def ins(kvb, kvs, r):
                return kvb.insert_row(r, kvs)
            fn = self.arch._jit_cache[key] = jax.jit(ins, donate_argnums=(0,))
        return fn(kv_batch, kv_single, jnp.asarray(row, jnp.int32))

    def decode_step_batched(self, kv, last_tokens, lengths, rng,
                            temperature=1.0, top_k=None, lora=None,
                            row_adapter=None, dispatch=None):
        """One shared decode+sample step across every row of a persistent
        multi-row KV state — the continuous-batching hot loop: K in-flight
        requests cost one batch-K forward per token instead of K batch-1
        forwards.

        ``lengths`` (B,) is the host's authoritative per-row valid length
        (0 parks a free slot: its write lands at position 0 of its own row
        and is never attended); it is installed via ``with_lengths`` inside
        the jitted step, so recycled/idle rows never drift on-device.
        With ``dispatch`` set, ``rng`` is the caller's BASE key and the
        per-step key advance ``fold_in(rng, dispatch)`` happens inside the
        jitted program — the caller passes the same base key every step
        plus an integer, instead of launching a host-side fold dispatch
        per token (``fold_in`` is bit-identical either side of the jit
        boundary, so seeded non-greedy output is unchanged — tested).
        Returns ``((B,) int32 next tokens, advanced kv)``; greedy outputs
        per row are identical to the single-sequence path (same ragged
        decode program as ``generate_tokens_batched``).  Donates ``kv`` —
        always thread the returned state.
        """
        greedy, temp = self._norm_temperature(temperature)
        arch = self.arch
        fold = dispatch is not None
        key = ("sched_step", bool(greedy), top_k, self._platform, fold)
        fn = arch._jit_cache.get(key)
        if fn is None:
            platform = self._platform

            def step(p, b, kv0, tok, lens, r, d, tmp, lo, ai):
                if fold:
                    r = jax.random.fold_in(r, d)
                kv1 = kv0.with_lengths(lens)
                t, kv2 = arch._decode_step(p, b, kv1, tok, r, tmp,
                                           greedy=greedy, top_k=top_k,
                                           compute_dtype=None,
                                           platform=platform,
                                           lora=lo, lora_idx=ai)
                return t[:, 0], kv2

            fn = arch._jit_cache[key] = jax.jit(step, donate_argnums=(2,))
        aidx = (jnp.asarray(row_adapter, jnp.int32)
                if lora is not None else None)
        with profiling.span("penroz/decode_step_batched"):
            return fn(self.params, self.buffers, kv,
                      jnp.asarray(last_tokens, jnp.int32),
                      jnp.asarray(lengths, jnp.int32), rng,
                      jnp.asarray(dispatch if fold else 0, jnp.int32),
                      temp, lora, aidx)

    def decode_superstep(self, kv, last_tokens, lengths, active,
                         stop_tokens, remaining, rng, dispatch, n,
                         temperature=1.0, top_k=None, lora=None,
                         row_adapter=None):
        """Run up to ``n`` shared decode+sample steps in ONE jitted
        dispatch — a ``lax.scan`` over the exact per-step program of
        :meth:`decode_step_batched`, so the host dispatch floor (sync
        lengths, check stop tokens, launch again — 73–107 ms/dispatch in
        the bench captures) is paid once per ``n`` tokens instead of once
        per token.

        The scan carry is ``(kv, last_tok, lengths, active, emitted)``:

        - ``kv`` threads through the scan donated-in, so the cache
          advances on device without host copies on all four variants
          (fp/int8 × contiguous/paged — the paged variants walk their
          static block-table partition with trace-static shapes exactly
          as in the single-step program);
        - ``lengths`` (B,) stays carry-authoritative and is re-installed
          via ``with_lengths`` each iteration, advancing by 1 only for
          ``active`` rows — parked/finished rows keep writing their
          compute-but-discard K/V at the same parked position, exactly
          like padded rows in the single-step path;
        - ``active`` (B, bool) is the on-device stop detector: a row
          leaves the mask when it samples its stop token, exhausts its
          ``remaining`` token budget, or fills the cache
          (``length == max_len``).  Finished rows keep computing and
          discard (``where``) — the program stays trace-static;
        - the sampling key for scan step ``i`` is
          ``fold_in(rng, dispatch + i)`` — the identical key sequence
          the host-folded single-step path would produce over the same
          ``n`` dispatch ordinals, so seeded non-greedy output is
          unchanged by fusing (tested; greedy ignores the key entirely).

        ``stop_tokens`` (B,) carries -1 for rows with no stop token;
        ``remaining`` (B,) is the per-row token budget left.  Returns
        ``(toks (n, B) int32 with -1 at masked slots, emitted (n, B)
        bool, final_lengths (B,), kv')`` — ONE host sync for the whole
        block; the scheduler replays ``toks[s, i]`` where ``emitted[s,
        i]`` through its normal per-token retirement path at the
        superstep boundary.  Jits per (n, sampling, cache type); keep
        ``n`` power-of-two-bucketed so the program set stays bounded.
        Donates ``kv`` — always thread the returned state.
        """
        greedy, temp = self._norm_temperature(temperature)
        arch = self.arch
        key = ("superstep", int(n), bool(greedy), top_k, self._platform)
        fn = arch._jit_cache.get(key)
        if fn is None:
            platform = self._platform

            def run(p, b, kv0, tok0, len0, act0, stopt, rem, r, d0, tmp,
                    lo, ai):
                max_len = kv0.max_len  # static

                def step(carry, i):
                    kvc, tok, lens, act, done = carry
                    kv1 = kvc.with_lengths(lens)
                    r_i = jax.random.fold_in(r, d0 + i)
                    t, kv2 = arch._decode_step(p, b, kv1, tok, r_i, tmp,
                                               greedy=greedy, top_k=top_k,
                                               compute_dtype=None,
                                               platform=platform,
                                               lora=lo, lora_idx=ai)
                    t = t[:, 0]
                    new_tok = jnp.where(act, t, tok[:, 0])[:, None]
                    new_lens = lens + act.astype(lens.dtype)
                    new_done = done + act.astype(jnp.int32)
                    still = (act & (t != stopt) & (new_done < rem)
                             & (new_lens < max_len))
                    out = (jnp.where(act, t, -1), act)
                    return (kv2, new_tok, new_lens, still, new_done), out

                init = (kv0, tok0, len0, act0,
                        jnp.zeros_like(len0))
                (kvf, _, lensf, _, _), (toks, emitted) = jax.lax.scan(
                    step, init, jnp.arange(n, dtype=jnp.int32))
                return toks, emitted, lensf, kvf

            fn = arch._jit_cache[key] = jax.jit(run, donate_argnums=(2,))
        aidx = (jnp.asarray(row_adapter, jnp.int32)
                if lora is not None else None)
        with profiling.span("penroz/decode_superstep"):
            return fn(self.params, self.buffers, kv,
                      jnp.asarray(last_tokens, jnp.int32),
                      jnp.asarray(lengths, jnp.int32),
                      jnp.asarray(active, bool),
                      jnp.asarray(stop_tokens, jnp.int32),
                      jnp.asarray(remaining, jnp.int32), rng,
                      jnp.asarray(dispatch, jnp.int32), temp, lora, aidx)

    def decode_mixed_step(self, kv, descs, tok_lit, tok_src, positions,
                          sample_slot, last_tokens, rng, dispatch,
                          temperature=1.0, top_k=None, lora=None,
                          lora_slots=None, row_ids=None):
        """Run ``n`` unified RAGGED steps in one dispatch — the single
        program that subsumes :meth:`decode_prefill_chunk`,
        :meth:`decode_step_batched` and :meth:`decode_verify_row` for
        paged caches: every step is one packed mixed batch where prefill
        chunks, decode steps and spec-verify spans share one kernel
        dispatch (ops/pallas/ragged_paged_attention.py), appends scatter
        straight through the block table (no ``row_view``
        materialization), and sampling happens at every packed position.

        The host plans the whole block up front (it knows each row's
        prompt, so a row can finish its prefill at step s and decode from
        step s+1 *inside the same dispatch* — the ``tok_src`` indirection
        feeds the carry's freshly sampled token forward), then replays
        emissions from the returned ``(n, Tp)`` sample array:

        - ``descs`` (n, NB, 4) int32 per-step descriptor arrays
          (ops/kv_cache.py::build_descriptors; NB shape-bucketed —
          utils/bucketing.py::bucket_count — so the program set stays
          bounded);
        - ``tok_lit``/``tok_src`` (n, Tp): packed input tokens — slot p
          feeds ``last_tokens[tok_src]`` when ``tok_src ≥ 0`` (decode
          continuation) else the literal (prompt/draft tokens);
        - ``positions`` (n, Tp) int32 absolute position per packed slot
          (per-token RoPE);
        - ``sample_slot`` (n, B): the packed slot whose sample becomes
          row b's carry ``last_token`` after that step (-1 keeps it —
          parked rows, non-final prefill chunks);
        - ``lora_slots`` (n, Tp) per-TOKEN adapter slots when ``lora``
          is set (the per-row gather rides the same dispatch).

        The GREEDY sampling key for step ``i`` is ``fold_in(rng,
        dispatch+i)``, the same sequence the phased path folds over its
        dispatch ordinals (unused by argmax; kept for program identity).
        Non-greedy sampling uses POSITIONAL keys —
        :meth:`CompiledArch._sample_packed` over ``row_ids`` (n, Tp, row
        index per packed slot, -1 padding) — so a (row, position) draw is
        invariant to packing, superstep, chunk splits and pipeline
        micro-blocking; spec-on/off and pipeline parity at temperature>0
        ride on this.  Returns ``(sampled (n, Tp) int32, kv')``; the caller
        replays per-row emissions (stop tokens, verify acceptance,
        rollbacks) host-side — host lengths stay authoritative exactly
        as on the phased path.  Jits per (n, NB, Tp, sampling, cache
        type).  Donates ``kv`` — always thread the returned state.
        """
        greedy, temp = self._norm_temperature(temperature)
        arch = self.arch
        descs = np.asarray(descs, np.int32)
        n, NB = descs.shape[0], descs.shape[1]
        tok_lit = np.asarray(tok_lit, np.int32)
        Tp = tok_lit.shape[1]
        if Tp % NB != 0:
            raise ValueError(f"packed length {Tp} must be a multiple of "
                             f"the descriptor count {NB}")
        block_q = Tp // NB
        key = ("mixed_step", n, NB, Tp, type(kv).__name__, bool(greedy),
               top_k, self._platform, lora is not None)
        fn = arch._jit_cache.get(key)
        if fn is None:
            platform = self._platform

            def run(p, b, kv0, dsc_s, tlit_s, tsrc_s, pos_s, sslot_s,
                    li_s, rid_s, last0, r, d0, tmp, lo):
                def step(carry, x):
                    kvc, last = carry
                    dsc, tlit, tsrc, pos, sslot, li, rid, i = x
                    toks = jnp.where(tsrc >= 0,
                                     last[jnp.clip(tsrc, 0)], tlit)
                    rows = kvc.packed_rows(dsc, block_q)
                    r_i = jax.random.fold_in(r, d0 + i)
                    acts, _, _, kv2 = arch.forward(
                        p, b, toks[None, :], None, kv=kvc,
                        pos_offset=pos[None, :], skip_softmax=True,
                        compute_dtype=None, platform=platform, lora=lo,
                        lora_idx=(li[None, :] if lo is not None else None),
                        ragged_descs=dsc, ragged_rows=rows)
                    logits = acts[-1][0]                       # (Tp, V)
                    if greedy:
                        out = arch._sample(logits, r_i, tmp, greedy=True,
                                           top_k=top_k)        # (Tp,)
                    else:
                        out = arch._sample_packed(logits, r, rid, pos,
                                                  tmp, top_k)  # (Tp,)
                    new_last = jnp.where(sslot >= 0,
                                         out[jnp.clip(sslot, 0)], last)
                    return (kv2, new_last), out

                xs = (dsc_s, tlit_s, tsrc_s, pos_s, sslot_s, li_s, rid_s,
                      jnp.arange(n, dtype=jnp.int32))
                (kvf, _), sampled = jax.lax.scan(step, (kv0, last0), xs)
                return sampled, kvf

            fn = arch._jit_cache[key] = jax.jit(run, donate_argnums=(2,))
        li = (np.asarray(lora_slots, np.int32) if lora_slots is not None
              else np.zeros((n, Tp), np.int32))
        rid = (np.asarray(row_ids, np.int32) if row_ids is not None
               else np.full((n, Tp), -1, np.int32))
        with profiling.span("penroz/decode_mixed_step"):
            return fn(self.params, self.buffers, kv,
                      jnp.asarray(descs), jnp.asarray(tok_lit),
                      jnp.asarray(tok_src, jnp.int32).reshape(n, Tp),
                      jnp.asarray(positions, jnp.int32).reshape(n, Tp),
                      jnp.asarray(sample_slot, jnp.int32),
                      jnp.asarray(li), jnp.asarray(rid.reshape(n, Tp)),
                      jnp.asarray(last_tokens, jnp.int32),
                      rng, jnp.asarray(dispatch, jnp.int32), temp, lora)

    def serve_pipeline(self, stages: int) -> "ServePipeline":
        """Build (and validate) the MPMD serving stage partition for this
        model — raises ``ValueError`` when the DSL has fewer repeated
        blocks than ``stages`` or a stage would own no attention layer."""
        return ServePipeline(self.arch, stages)

    def decode_pipe_stage(self, pipe: "ServePipeline", s: int, kv_stage,
                          x, descs, positions, row_ids, rng,
                          temperature=1.0, top_k=None):
        """Run ONE pipeline stage of one unified ragged step over one
        micro-block — the MPMD counterpart of a single
        :meth:`decode_mixed_step` scan iteration, split at stage
        boundaries.  Stage 0 consumes packed tokens ``x`` (1, Tp) int32
        (the host resolves the ``tok_src`` indirection — it already owns
        ``last_tokens`` between micro-blocks); later stages consume the
        previous stage's hidden-state hand-off (1, Tp, D).  Every stage
        appends into its own KV slice via ``kv_stage``
        (ops/kv_cache.py::stage_kv_view) — stage archs index attention
        layers 0.. locally, matching the sliced pools.  The LAST stage
        samples: greedy argmax (bit-identical to the fused program — the
        module stack is split only at module boundaries, so the logits
        are the same floats) or :meth:`CompiledArch._sample_packed`
        positional draws (identical to the unpiped non-greedy stream by
        construction).  Returns ``(hidden|sampled, kv_stage')``.

        Jits per (stage, NB, Tp, cache type, sampling); cached in the
        STAGE arch's program cache so ``jit_program_counts`` attributes
        them per stage.  Deliberately does NOT donate ``kv_stage``: its
        counters/table/lengths buffers are shared with every other
        stage's view of the same cache (and with the full state the
        scheduler threads), so donation would invalidate siblings —
        correctness over the copy-elision, documented perf gap."""
        greedy, temp = self._norm_temperature(temperature)
        arch_s = pipe.archs[s]
        descs = np.asarray(descs, np.int32)
        NB = descs.shape[0]
        positions = np.asarray(positions, np.int32)
        Tp = positions.shape[-1]
        if Tp % NB != 0:
            raise ValueError(f"packed length {Tp} must be a multiple of "
                             f"the descriptor count {NB}")
        block_q = Tp // NB
        last_stage = s == pipe.stages - 1
        key = ("pipe_stage", s, pipe.stages, NB, Tp,
               type(kv_stage).__name__, bool(greedy), top_k,
               self._platform)
        fn = arch_s._jit_cache.get(key)
        if fn is None:
            platform = self._platform

            def run(p, b, kv0, xx, dsc, pos, rid, r, tmp):
                rows = kv0.packed_rows(dsc, block_q)
                acts, _, _, kv2 = arch_s.forward(
                    p, b, xx, None, kv=kv0, pos_offset=pos[None, :],
                    skip_softmax=True, compute_dtype=None,
                    platform=platform, ragged_descs=dsc, ragged_rows=rows)
                h = acts[-1]
                if not last_stage:
                    return h, kv2
                logits = h[0]                                  # (Tp, V)
                if greedy:
                    out = arch_s._sample(logits, r, tmp, greedy=True,
                                         top_k=top_k)
                else:
                    out = arch_s._sample_packed(logits, r, rid, pos,
                                                tmp, top_k)
                return out, kv2

            fn = arch_s._jit_cache[key] = jax.jit(run)
        params = pipe.stage_params(self.params, s)
        buffers = pipe.stage_buffers(self.buffers, s)
        if s == 0:
            x = jnp.asarray(np.asarray(x, np.int32).reshape(1, Tp))
        if pipe.meshes is not None:
            # MPMD placement is live: pull the shared KV metadata and the
            # previous stage's activation hand-off onto THIS stage's mesh
            # (device-to-device) so the stage jit sees one device group.
            repl = mesh_lib.replicated(pipe.meshes[s])
            kv_stage = KV.restage_shared(kv_stage, repl)
            if s > 0 and isinstance(x, jax.Array):
                x = jax.device_put(x, repl)
        with profiling.span("penroz/decode_pipe_stage"):
            return fn(params, buffers, kv_stage, x, jnp.asarray(descs),
                      jnp.asarray(positions.reshape(Tp)),
                      jnp.asarray(np.asarray(row_ids,
                                             np.int32).reshape(Tp)),
                      rng, temp)

    def _sampling_setup(self, temperature):
        """Shared generation preamble: (greedy, temp scalar, call rng).
        None/0.0 temperature means greedy; falsy maps the scalar to 1.0
        (reference sampling knobs: neural_net_model.py:393-405)."""
        greedy = temperature is None or float(temperature) == 0.0
        temp = jnp.asarray(float(temperature) if temperature else 1.0,
                           jnp.float32)
        self._sample_rng, call_rng = jax.random.split(self._sample_rng)
        return greedy, temp, call_rng

    @staticmethod
    def _prompt_tokens(input) -> list[int]:
        row = input[0] if input and isinstance(input[0], (list, tuple)) \
            else input
        return [int(t) for t in row]

    def generate_tokens(self, input, block_size, max_new_tokens,
                        temperature=1.0, top_k=None, stop_token=None):
        """Autoregressive generation; returns prompt + generated ids
        (reference: neural_net_model.py:457-479)."""
        context = self._prompt_tokens(input)
        metrics = KV.create_kv_cache(len(self.arch.attn_layers))
        try:
            with decode_priority():
                for tok in self._generate_iter(context, block_size,
                                               max_new_tokens, temperature,
                                               top_k, metrics):
                    if stop_token is not None and tok == stop_token:
                        break
        finally:
            metrics.log_metrics()
        return context

    def generate_tokens_stream(self, input, block_size, max_new_tokens,
                               temperature=1.0, top_k=None, stop_token=None):
        """Streaming variant yielding each new token (reference:
        neural_net_model.py:481-514)."""
        context = self._prompt_tokens(input)
        metrics = KV.create_kv_cache(len(self.arch.attn_layers))
        it = self._generate_iter(context, block_size, max_new_tokens,
                                 temperature, top_k, metrics, ramp=True)
        try:
            while True:
                # Mark only the device-work advance, not the consumer's
                # wall time between yields — a slow stream reader must not
                # keep training parked at the priority window with an
                # idle chip.
                with decode_priority():
                    try:
                        tok = next(it)
                    except StopIteration:
                        break
                yield tok
                if stop_token is not None and tok == stop_token:
                    return
        finally:
            metrics.log_metrics()

    # -- persistence --------------------------------------------------------

    @staticmethod
    def _is_host_readable(v) -> bool:
        """Whether ``np.asarray(v)`` works on this host (plain / addressable
        / fully-replicated arrays — everything except cross-host shards)."""
        return (getattr(v, "is_fully_addressable", True)
                or getattr(v, "is_fully_replicated", False))

    def _checkpoint_items(self):
        """Flat name → array view of everything persisted (params, buffers,
        optimizer leaves) so sharding-aware save/load handles them
        uniformly.  Optimizer leaves get synthetic ``__opt__{i}`` names.
        An active pipeline-stacked training layout is converted back to the
        canonical flat layout here, so the checkpoint format (and
        :meth:`deserialize`) never sees stacked keys."""
        params, opt_state = self._canonical_state()
        items = dict(params)
        items.update({f"__buf__{k}": v for k, v in self.buffers.items()})
        items.update({f"__opt__{i}": leaf for i, leaf
                      in enumerate(jax.tree.leaves(opt_state))})
        return items

    def serialize(self, sync_flush: bool = False, tag=None):
        """Checkpoint to shm + durable dir (reference:
        neural_net_model.py:98-122).

        Cross-host-sharded arrays (TP/SP/EP over a multi-host mesh) cannot be
        materialized on one host; each process persists the shard pieces it
        owns (``replica_id == 0`` only, so the union covers each index range
        exactly once) into ``model_{id}.shard{rank}.ckpt``, and the master
        blob records their global shape/dtype for reassembly on load.
        ``tag`` (the epoch number during training — identical on every host)
        is stamped into the blob and every shard file so a load can reject a
        checkpoint whose pieces come from different training steps.

        An UNTAGGED call on a model with sharded params (a status update at
        train start, the error path, a serve-side save) is not coordinated
        across hosts, so it must not rewrite shard files — one host's write
        would permanently tear the last consistent checkpoint.  Such calls
        degrade to a master-only metadata update of the existing blob.
        The raw-layout check runs BEFORE the canonical conversion: with a
        pipeline-stacked layout still active, unstacking cross-host leaves
        is itself a collective, and an uncoordinated call must not launch
        one one-sided."""
        if tag is None:
            # Raw-layout check over params + buffers + optimizer leaves:
            # buffers are placed replicated at train start, but epoch
            # OUTPUTS (e.g. pipelined MoE router fractions from the aux
            # channel) carry whatever sharding GSPMD propagated, so they
            # must be checked, not assumed.
            raw_sharded = not all(
                self._is_host_readable(v) for v in (
                    list(self.params.values())
                    + list(self.buffers.values())
                    + jax.tree.leaves(self.opt_state)))
            if raw_sharded:
                if dist.master_proc():
                    self._serialize_meta_only(sync_flush)
                return
        items = self._checkpoint_items()
        sharded_meta: dict = {}
        shard_pieces: dict = {}
        for name, v in items.items():
            if not self._is_host_readable(v):
                sharded_meta[name] = {"shape": tuple(v.shape),
                                      "dtype": str(v.dtype)}
                shard_pieces[name] = [
                    (tuple((sl.start, sl.stop) for sl in shard.index),
                     np.asarray(shard.data))
                    for shard in v.addressable_shards
                    if shard.replica_id == 0]
        if shard_pieces:
            checkpoint.save_shard(
                self.model_id, dist.process_index(),
                {"tag": tag, "pieces": shard_pieces},
                sync_flush=sync_flush, world=dist.process_count())
        if not dist.master_proc():
            return
        # Host-readable materialization only after the master check — every
        # non-master host doing full D2H copies of replicated state just to
        # discard them would waste seconds per checkpoint at scale.
        host_arrays = {name: np.asarray(v) for name, v in items.items()
                       if self._is_host_readable(v)}
        # Key/leaf sets come from the canonical layout (== items), not
        # self.params/opt_state, which may be pipeline-stacked mid-training.
        n_opt = sum(1 for name in items if name.startswith("__opt__"))
        params = {k: host_arrays[k] for k in items
                  if not k.startswith(("__buf__", "__opt__"))
                  and k in host_arrays}
        buffers = {k: host_arrays[f"__buf__{k}"] for k in self.buffers
                   if f"__buf__{k}" in host_arrays}
        opt_leaves = {i: host_arrays[f"__opt__{i}"] for i in range(n_opt)
                      if f"__opt__{i}" in host_arrays}
        data = {
            "layers": self.layers_dsl,
            "optimizer": self.optimizer_config,
            "params": params,
            "buffers": buffers,
            "opt_state_leaves": opt_leaves,
            "sharded": sharded_meta,
            "shard_tag": tag,
            "progress": self.progress,
            "avg_cost": self.avg_cost,
            "avg_cost_history": self.avg_cost_history,
            "stats": self.stats,
            "status": self.status,
        }
        checkpoint.save(self.model_id, data, sync_flush=sync_flush)

    def _serialize_meta_only(self, sync_flush: bool = False):
        """Update progress/status in the existing blob without touching the
        weights or shard files — the safe write for uncoordinated saves on a
        sharded model (preserves the last consistent checkpoint).

        ``checkpoint.patch_meta`` rewrites only the header and streams the
        array payload through verbatim — no decode, no re-encode, no RAM
        spike on multi-GB checkpoints.  (``sync_flush`` is moot:
        patch_meta always writes both copies synchronously.)"""
        del sync_flush
        try:
            checkpoint.patch_meta(self.model_id, {
                "progress": self.progress,
                "avg_cost": self.avg_cost,
                "avg_cost_history": self.avg_cost_history,
                "stats": self.stats,
                "status": self.status,
            })
        except KeyError:
            log.warning("Meta-only checkpoint skipped: no existing blob "
                        "for %s", self.model_id)

    @staticmethod
    def _reassemble_sharded(model_id: str, sharded_meta: dict,
                            expected_tag=None) -> dict:
        """Rebuild full arrays from the per-host shard files (TP/SP/EP
        checkpoints).  Requires every host's shard file to be readable —
        true on shared filesystems and in tests; raises otherwise.  Shard
        files stamped with a different step tag than the blob are rejected
        (a crash between hosts' checkpoints would otherwise stitch weight
        pieces from different training steps)."""
        shards = []
        for i, payload in enumerate(checkpoint.load_shards(model_id)):
            if payload.get("tag") != expected_tag:
                raise RuntimeError(
                    f"Sharded checkpoint for {model_id} is torn: shard file "
                    f"#{i} is from step {payload.get('tag')!r} but the "
                    f"metadata blob is from step {expected_tag!r}")
            shards.append(payload["pieces"])
        out = {}
        for name, meta in sharded_meta.items():
            shape = tuple(meta["shape"])
            # checkpoint.np_dtype: plain np.dtype cannot parse "bfloat16"
            arr = np.zeros(shape, dtype=checkpoint.np_dtype(meta["dtype"]))
            covered = 0
            for shard_data in shards:
                for ranges, piece in shard_data.get(name, []):
                    idx = tuple(slice(a, b) for a, b in ranges)
                    arr[idx] = piece
                    covered += int(np.prod(piece.shape))
            if covered < int(np.prod(shape)):
                raise RuntimeError(
                    f"Sharded checkpoint for {model_id} is incomplete: "
                    f"{name} has {covered}/{int(np.prod(shape))} elements "
                    f"across {len(shards)} shard file(s) — all hosts' shard "
                    f"files must be visible to reassemble")
            out[name] = arr
        return out

    @classmethod
    def deserialize(cls, model_id: str) -> "NeuralNetworkModel":
        """Load a checkpoint, restoring dtypes exactly (reference:
        neural_net_model.py:124-174).  :raises KeyError: unknown model."""
        data = checkpoint.load(model_id)
        model = cls.__new__(cls)
        model.model_id = model_id
        model.layers_dsl = data["layers"]
        model.optimizer_config = data["optimizer"]
        model.arch = CompiledArch.get(model.layers_dsl)
        assembled = (cls._reassemble_sharded(model_id, data["sharded"],
                                             data.get("shard_tag"))
                     if data.get("sharded") else {})
        params = dict(data["params"])
        buffers = dict(data["buffers"])
        opt_leaves_in = data["opt_state_leaves"]
        if isinstance(opt_leaves_in, dict):
            opt_leaves = dict(opt_leaves_in)
        else:  # pre-sharding checkpoint format: plain list
            opt_leaves = dict(enumerate(opt_leaves_in))
        for name, arr in assembled.items():
            if name.startswith("__buf__"):
                buffers[name[len("__buf__"):]] = arr
            elif name.startswith("__opt__"):
                opt_leaves[int(name[len("__opt__"):])] = arr
            else:
                params[name] = arr
        model.params = {k: jnp.asarray(v) for k, v in params.items()}
        model.buffers = {k: jnp.asarray(v) for k, v in buffers.items()}
        # Buffer-schema migration: checkpoints written before a module
        # gained a buffer (e.g. MoE router_fraction) lack its key; training
        # would then grow the lax.scan carry mid-step and fail at trace
        # time.  Fill absent buffers with their module defaults.
        for mod in model.arch.mods:
            for sub in mod.walk():
                for key, value in sub.init_buffers().items():
                    model.buffers.setdefault(key, jnp.asarray(value))
        optimizer = dsl.build_optimizer(model.optimizer_config)
        template = jax.eval_shape(optimizer.init, model.params)
        model.opt_state = jax.tree.unflatten(
            jax.tree.structure(template),
            [jnp.asarray(opt_leaves[i]) for i in range(len(opt_leaves))])
        model.progress = data.get("progress", [])
        model.avg_cost = data.get("avg_cost")
        model.avg_cost_history = data.get("avg_cost_history", [])
        model.stats = data.get("stats")
        model.status = data.get("status", {"code": "Created", "message": None})
        model.device = None
        model._sample_rng = jax.random.key(0)
        model._pipe_layout = None
        return model

    @classmethod
    def delete(cls, model_id: str):
        checkpoint.delete(model_id)

    # -- HuggingFace import -------------------------------------------------

    @classmethod
    def from_huggingface(cls, model_id: str, hf_repo_id: str,
                         revision: Optional[str] = None,
                         device: Optional[str] = None
                         ) -> "NeuralNetworkModel":
        """Import GPT-2/Gemma weights into the flat param pytree as bf16
        (reference: neural_net_model.py:176-237).

        Torch-free: weights come from safetensors files via
        ``hf_loader`` (numpy arrays, no torch graph materialized — the
        reference routes through torch because it *is* torch); only the
        config is read through transformers.  Repos shipping nothing but
        torch ``.bin`` weights fall back to torch when it is installed.
        """
        import transformers
        from . import hf_loader

        local_dir = hf_loader.resolve_checkpoint_dir(hf_repo_id, revision)
        config = transformers.AutoConfig.from_pretrained(local_dir)
        sd = hf_loader.load_state_dict(local_dir)

        n_layer = Mapper.detect_hf_n_layer(sd)
        if not n_layer:
            cfg = getattr(config, "text_config", None) or config
            n_layer = int(getattr(cfg, "n_layer", 0)
                          or getattr(cfg, "num_hidden_layers", 0))
        layers = Mapper.from_hf_config(config, n_layer_override=n_layer)
        mapper = Mapper(layers, {"adamw": {"lr": 6e-4, "betas": [0.9, 0.95],
                                           "eps": 1e-8}})
        model = cls(model_id, mapper)
        mapped = Mapper.map_hf_state_dict_to_custom(sd, n_layer, config)

        expected = set(model.params)
        got = set(mapped)
        if expected != got:
            raise KeyError(f"HF state dict mismatch: missing "
                           f"{sorted(expected - got)}, unexpected "
                           f"{sorted(got - expected)}")
        for key, value in mapped.items():
            if tuple(value.shape) != tuple(model.params[key].shape):
                raise ValueError(f"Shape mismatch for {key}: HF "
                                 f"{tuple(value.shape)} vs model "
                                 f"{tuple(model.params[key].shape)}")
        model.params = {k: jnp.asarray(v, jnp.bfloat16)
                        for k, v in mapped.items()}
        model.opt_state = mapper.to_optimizer().init(model.params)
        model.to_device(device)
        model.status = {"code": "Imported",
                        "message": f"Imported from {hf_repo_id}"}
        model.serialize()
        return model


