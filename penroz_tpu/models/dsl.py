"""JSON layer/optimizer DSL → functional module trees and optax optimizers.

The TPU-native equivalent of the reference's ``mappers.py``:

- a registry of layer algos (reference: mappers.py:19-41) building the
  functional modules in ``penroz_tpu.ops.modules``;
- weight-init overrides (``normal``/``xavier_uniform``/``kaiming_uniform``/
  ``zeros``) plus ``confidence`` weight scaling (reference: mappers.py:43-51,
  63-99);
- an optimizer registry over optax (reference: mappers.py:53-57, 264-274);
- HuggingFace config → DSL builders for GPT-2 and the Gemma family
  (reference: mappers.py:121-262) and HF state-dict → flat-param-dict key
  remapping (reference: mappers.py:304-448).

Parameter key names mirror the reference's torch ``state_dict`` naming
(``layers.{i}...``) so checkpoints and HF imports stay pure table lookups.
"""

from __future__ import annotations

import math
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from penroz_tpu.ops import modules as M

# Init-override keys that may sit alongside the layer algo in a DSL entry
# (reference: mappers.py:43-51; ``confidence`` scaling: mappers.py:88-93).
INIT_KEYS = ("normal", "xavier_uniform", "kaiming_uniform", "zeros")

_CONTAINER_ALGOS = {
    "sequential": M.Sequential,
    "summation": M.Summation,
    "residual": M.ResidualConnection,
    "parallelresidual": M.ParallelResidual,
}

_LEAF_ALGOS = {
    "linear": M.Linear,
    "embedding": M.Embedding,
    "position": M.PositionEmbedding,
    "scaledembedding": M.ScaledEmbedding,
    "flatten": M.Flatten,
    "batchnorm1d": M.BatchNorm1d,
    "layernorm": M.LayerNorm,
    "rmsnorm": M.RMSNorm,
    "relu": M.ReLU,
    "gelu": M.GELU,
    "silu": M.SiLU,
    "sigmoid": M.Sigmoid,
    "tanh": M.Tanh,
    "softmax": M.Softmax,
    "softmaxlast": M.SoftmaxOnLast,
    "dropout": M.Dropout,
    "attention": M.CausalSelfAttention,
    "ssm": M.GatedSSM,
    "gatedmlp": M.GatedMLP,
    "moe": M.MixtureOfExperts,
    "clamp": M.Clamp,
    "softcap": M.Softcap,
}

_OPTIMIZERS = ("adamw", "adam", "sgd")


def layer_algo(entry: dict) -> str:
    """The single layer-algo key of a DSL entry (init keys are siblings)."""
    algos = [k for k in entry if k not in INIT_KEYS and k != "confidence"]
    if len(algos) != 1:
        raise ValueError(f"Layer entry must have exactly one algo key, got "
                         f"{sorted(entry)}")
    return algos[0]


def to_layer(entry: dict) -> M.Module:
    """Recursively build one module from a DSL entry (reference:
    mappers.py:63-99)."""
    algo = layer_algo(entry)
    args = entry[algo]
    if algo in _CONTAINER_ALGOS:
        mod = _CONTAINER_ALGOS[algo](*[to_layer(e) for e in args])
    elif algo == "transformerblock":
        kwargs: dict[str, Any] = {
            "attn_block": to_layer(args["attn_block"]),
            "mlp_block": to_layer(args["mlp_block"]),
            "post_norm_on_residual": bool(args.get("post_norm_on_residual",
                                                   True)),
        }
        for name in ("post_attn_norm", "post_mlp_norm"):
            if name in args:
                kwargs[name] = to_layer(args[name])
        mod = M.TransformerBlock(**kwargs)
    elif algo in _LEAF_ALGOS:
        mod = _LEAF_ALGOS[algo](**args)
    else:
        raise ValueError(f"Unsupported layer: {algo}")
    mod._algo = algo
    mod._init_spec = {k: entry[k] for k in entry
                      if k in INIT_KEYS or k == "confidence"}
    return mod


def build_modules(layers: list[dict]) -> list[M.Module]:
    """Build + bind the top-level module list (param prefix ``layers.{i}``)."""
    mods = [to_layer(entry) for entry in layers]
    for i, mod in enumerate(mods):
        mod.bind(f"layers.{i}")
    return mods


def _fans(shape: tuple) -> tuple[int, int]:
    """(fan_in, fan_out) for a weight stored as (out, in) — torch layout."""
    if len(shape) >= 2:
        return int(shape[-1]), int(shape[0])
    return int(shape[0]), int(shape[0])


def _override_init(mod: M.Module, params: dict, spec: dict, rng) -> dict:
    """Apply an init-override spec to a module's own params (reference:
    mappers.py:63-99: per-layer init + confidence weight scaling)."""
    shapes = mod.param_shapes()
    wkey = mod.key("weight")
    if "weight" in shapes and wkey in params:
        shape = shapes["weight"]
        fan_in, fan_out = _fans(shape)
        w = params[wkey]
        if "normal" in spec:
            mean = float(spec["normal"].get("mean", 0.0))
            std = float(spec["normal"].get("std", 1.0))
            w = jax.random.normal(jax.random.fold_in(rng, 101), shape,
                                  jnp.float32) * std + mean
        elif "xavier_uniform" in spec:
            bound = math.sqrt(6.0 / (fan_in + fan_out))
            w = jax.random.uniform(jax.random.fold_in(rng, 102), shape,
                                   jnp.float32, -bound, bound)
        elif "kaiming_uniform" in spec:
            a = float(spec["kaiming_uniform"].get("a", math.sqrt(5.0)))
            nonlinearity = spec["kaiming_uniform"].get("nonlinearity",
                                                       "leaky_relu")
            if nonlinearity == "relu":
                gain = math.sqrt(2.0)
            elif nonlinearity == "leaky_relu":
                gain = math.sqrt(2.0 / (1.0 + a * a))
            else:
                gain = 1.0
            bound = gain * math.sqrt(3.0 / fan_in)
            w = jax.random.uniform(jax.random.fold_in(rng, 103), shape,
                                   jnp.float32, -bound, bound)
        if "confidence" in spec:
            w = w * float(spec["confidence"])
        params[wkey] = w
    bkey = mod.key("bias")
    if "zeros" in spec and bkey in params:
        params[bkey] = jnp.zeros(shapes["bias"], jnp.float32)
    return params


def init_module_params(mods: list[M.Module], seed: int = 0):
    """Deterministically initialize the flat param/buffer dicts for a bound
    module list, honoring per-layer init-override specs."""
    base = jax.random.key(seed)
    params: dict[str, jax.Array] = {}
    buffers: dict[str, jax.Array] = {}
    idx = 0
    for top in mods:
        for sub in top.walk():
            idx += 1
            rng = jax.random.fold_in(base, idx)
            own = sub.init(rng)
            spec = getattr(sub, "_init_spec", None)
            if spec:
                own = _override_init(sub, own, spec, rng)
            params.update(own)
            buffers.update(sub.init_buffers())
    return params, buffers


def build_optimizer(config: dict) -> optax.GradientTransformation:
    """Optimizer DSL → optax transform (reference: mappers.py:53-57,264-274).

    ``betas`` lists are coerced to the (b1, b2) pair; ``weight_decay`` follows
    torch semantics (decoupled for adamw, L2-into-grad for adam/sgd).
    """
    if len(config) != 1:
        raise ValueError(f"Optimizer config must have exactly one key, got "
                         f"{sorted(config)}")
    (name, args), = config.items()
    if name not in _OPTIMIZERS:
        raise ValueError(f"Unsupported optimizer: {name}")
    args = dict(args)
    lr = float(args.pop("lr", 1e-3))
    if name in ("adamw", "adam"):
        betas = args.pop("betas", (0.9, 0.999))
        b1, b2 = float(betas[0]), float(betas[1])
        eps = float(args.pop("eps", 1e-8))
        if name == "adamw":
            weight_decay = float(args.pop("weight_decay", 0.01))
            return optax.adamw(lr, b1=b1, b2=b2, eps=eps,
                               weight_decay=weight_decay)
        weight_decay = float(args.pop("weight_decay", 0.0))
        opt = optax.adam(lr, b1=b1, b2=b2, eps=eps)
        if weight_decay:
            return optax.chain(optax.add_decayed_weights(weight_decay), opt)
        return opt
    momentum = float(args.pop("momentum", 0.0)) or None
    nesterov = bool(args.pop("nesterov", False))
    weight_decay = float(args.pop("weight_decay", 0.0))
    opt = optax.sgd(lr, momentum=momentum, nesterov=nesterov)
    if weight_decay:
        return optax.chain(optax.add_decayed_weights(weight_decay), opt)
    return opt


class Mapper:
    """Layer + optimizer DSL front-end (reference: mappers.py `Mapper`)."""

    def __init__(self, layers: list[dict], optimizer: dict):
        self.layers = layers
        self.optimizer = optimizer

    def to_modules(self) -> list[M.Module]:
        return build_modules(self.layers)

    def init_params(self, mods: list[M.Module], seed: int = 0):
        return init_module_params(mods, seed=seed)

    def to_optimizer(self) -> optax.GradientTransformation:
        return build_optimizer(self.optimizer)

    # -- HuggingFace config → DSL ------------------------------------------

    @staticmethod
    def from_hf_config(config, n_layer_override: Optional[int] = None
                       ) -> list[dict]:
        """Build the layer DSL for a HuggingFace model config (reference:
        mappers.py:121-262 for GPT-2 and Gemma 1/2/3/4)."""
        model_type = getattr(config, "model_type", "") or ""
        if model_type == "gpt2":
            return _gpt2_dsl_from_config(config, n_layer_override)
        if model_type.startswith("gemma"):
            if model_type.startswith("gemma3n"):
                # Gemma-3n checkpoints carry AltUp, LAuReL, and per-layer
                # input projections this builder does not implement —
                # routing them through the generic gemma path would
                # import with silently wrong logits.  (The reference's
                # "gemma 4" dims-only surface — kv-shared layers,
                # double-wide MLPs, per-type head dims — stays available
                # for configs without those mechanisms.)
                raise ValueError(
                    "gemma3n checkpoints are not supported (AltUp/LAuReL "
                    "architecture)")
            return _gemma_dsl_from_config(config, n_layer_override)
        if model_type in _LLAMA_FAMILY:
            return _llama_dsl_from_config(config, n_layer_override)
        if model_type == "gpt_neox":
            return _neox_dsl_from_config(config, n_layer_override)
        if model_type == "phi":
            return _phi_dsl_from_config(config, n_layer_override)
        if model_type == "olmo2":
            return _olmo2_dsl_from_config(config, n_layer_override)
        if model_type == "olmo":
            return _olmo_dsl_from_config(config, n_layer_override)
        if model_type == "stablelm":
            return _stablelm_dsl_from_config(config, n_layer_override)
        if model_type == "gptj":
            return _gptj_dsl_from_config(config, n_layer_override)
        if model_type == "falcon":
            return _falcon_dsl_from_config(config, n_layer_override)
        if model_type == "gpt_bigcode":
            return _bigcode_dsl_from_config(config, n_layer_override)
        if model_type == "opt":
            return _opt_dsl_from_config(config, n_layer_override)
        if model_type == "bloom":
            return _bloom_dsl_from_config(config, n_layer_override)
        if model_type == "mpt":
            return _mpt_dsl_from_config(config, n_layer_override)
        raise ValueError(f"Unsupported HuggingFace model type: {model_type}")

    # -- HF state-dict detection + remapping --------------------------------

    @staticmethod
    def detect_hf_n_layer(state_dict: dict) -> int:
        """Sniff the transformer layer count from state-dict key names
        (reference: mappers.py:276-302)."""
        import re
        pattern = re.compile(
            r"(?:transformer\.h|transformer\.blocks|gpt_neox\.layers"
            r"|model\.decoder\.layers"
            r"|model\.(?:language_model\.)?layers)\.(\d+)\.")
        n = 0
        for key in state_dict:
            m = pattern.match(key)
            if m:
                n = max(n, int(m.group(1)) + 1)
        return n

    @staticmethod
    def map_hf_state_dict_to_custom(state_dict: dict, n_layer: int,
                                    config=None) -> dict:
        """Remap an HF state dict (numpy arrays) onto our flat param keys
        (reference: mappers.py:304-448)."""
        if getattr(config, "model_type", "") == "gptj" or \
                "transformer.h.0.attn.q_proj.weight" in state_dict:
            return _map_gptj_state_dict(state_dict, n_layer, config)
        if getattr(config, "model_type", "") == "gpt_bigcode":
            # checked BEFORE the gpt2 key sniff: bigcode checkpoints also
            # carry transformer.wte.weight but use plain nn.Linear layouts
            return _map_bigcode_state_dict(state_dict, n_layer, config)
        if getattr(config, "model_type", "") == "mpt" or \
                "transformer.blocks.0.attn.Wqkv.weight" in state_dict:
            # also before the gpt2 sniff: MPT carries transformer.wte too
            return _map_mpt_state_dict(state_dict, n_layer, config)
        if "transformer.wte.weight" in state_dict:
            # Config-less safety sniff: GPT-2 Conv1D stores c_attn as
            # (d, 3d); gpt_bigcode/falcon-style nn.Linear layouts are
            # (out, in) and would be silently transposed into garbage by
            # the GPT-2 branch.  Refuse loudly instead of mis-mapping.
            w = state_dict.get("transformer.h.0.attn.c_attn.weight")
            if config is None and w is not None \
                    and w.shape[1] != 3 * w.shape[0]:
                raise ValueError(
                    "state dict has transformer.wte.weight but c_attn is "
                    f"not Conv1D-shaped ({tuple(w.shape)}); pass the HF "
                    "config so the family (gpt_bigcode/falcon/...) can be "
                    "dispatched correctly")
            return _map_gpt2_state_dict(state_dict, n_layer)
        if "gpt_neox.embed_in.weight" in state_dict:
            return _map_neox_state_dict(state_dict, n_layer, config)
        if getattr(config, "model_type", "") == "opt" or \
                "model.decoder.embed_tokens.weight" in state_dict:
            return _map_opt_state_dict(state_dict, n_layer, config)
        if getattr(config, "model_type", "") == "bloom" or \
                "transformer.word_embeddings_layernorm.weight" in state_dict:
            # the embedding LayerNorm is BLOOM-unique; plain
            # word_embeddings would also match Falcon checkpoints
            return _map_bloom_state_dict(state_dict, n_layer, config)
        if getattr(config, "model_type", "") == "phi":
            return _map_phi_state_dict(state_dict, n_layer, config)
        if getattr(config, "model_type", "") == "olmo2":
            return _map_olmo2_state_dict(state_dict, n_layer, config)
        if getattr(config, "model_type", "") == "olmo":
            return _map_olmo_state_dict(state_dict, n_layer, config)
        if getattr(config, "model_type", "") == "stablelm":
            return _map_stablelm_state_dict(state_dict, n_layer, config)
        if getattr(config, "model_type", "") == "falcon":
            return _map_falcon_state_dict(state_dict, n_layer, config)
        if getattr(config, "model_type", "") in _LLAMA_FAMILY:
            return _map_llama_state_dict(state_dict, n_layer, config)
        return _map_gemma_state_dict(state_dict, n_layer, config)


# ---------------------------------------------------------------------------
# GPT-2
# ---------------------------------------------------------------------------

def _gpt2_gelu_entry(activation: str) -> dict:
    if activation in ("gelu_new", "gelu_pytorch_tanh"):
        return {"gelu": {"approximate": "tanh"}}
    return {"gelu": {}}


def _gpt2_dsl_from_config(config, n_layer_override=None) -> list[dict]:
    """GPT-2 HF config → layer DSL (reference: mappers.py:121-176)."""
    d = int(config.n_embd)
    n = int(n_layer_override if n_layer_override else config.n_layer)
    heads = int(config.n_head)
    vocab = int(config.vocab_size)
    block = int(config.n_positions)
    attn_drop = float(getattr(config, "attn_pdrop", 0.0) or 0.0)
    resid_drop = float(getattr(config, "resid_pdrop", 0.0) or 0.0)
    embd_drop = float(getattr(config, "embd_pdrop", 0.0) or 0.0)
    gelu = _gpt2_gelu_entry(getattr(config, "activation_function", "gelu_new"))
    proj_std = 0.02 / math.sqrt(2 * n)

    layers: list[dict] = [
        {"summation": [
            {"embedding": {"num_embeddings": vocab, "embedding_dim": d},
             "normal": {"mean": 0.0, "std": 0.02}},
            {"position": {"num_embeddings": block, "embedding_dim": d},
             "normal": {"mean": 0.0, "std": 0.02}}]},
        {"dropout": {"p": embd_drop}},
    ]
    for _ in range(n):
        layers.append({"residual": [
            {"sequential": [
                {"layernorm": {"normalized_shape": d}},
                {"linear": {"in_features": d, "out_features": 3 * d},
                 "normal": {"mean": 0.0, "std": 0.02}, "zeros": {}},
                {"attention": {"num_heads": heads, "dropout": attn_drop}},
                {"linear": {"in_features": d, "out_features": d},
                 "normal": {"mean": 0.0, "std": proj_std}, "zeros": {}},
                {"dropout": {"p": resid_drop}}]},
            {"sequential": [
                {"layernorm": {"normalized_shape": d}},
                {"linear": {"in_features": d, "out_features": 4 * d},
                 "normal": {"mean": 0.0, "std": 0.02}, "zeros": {}},
                gelu,
                {"linear": {"in_features": 4 * d, "out_features": d},
                 "normal": {"mean": 0.0, "std": proj_std}, "zeros": {}},
                {"dropout": {"p": resid_drop}}]}]})
    layers += [
        {"layernorm": {"normalized_shape": d}},
        {"linear": {"in_features": d, "out_features": vocab, "bias": False},
         "normal": {"mean": 0.0, "std": 0.02}},
        {"softmaxlast": {"dim": -1}},
    ]
    return layers


def _bloom_dsl_from_config(config, n_layer_override=None) -> list[dict]:
    """BLOOM HF config → layer DSL: NO positional embedding at all —
    ALiBi linear logit biases carry position (attention ``alibi`` arg) —
    plus the embedding LayerNorm, pre-LN blocks with per-head-interleaved
    fused QKV (de-interleaved at import), and tanh-GELU MLPs."""
    d = int(config.hidden_size)
    n = int(n_layer_override if n_layer_override else config.n_layer)
    heads = int(config.n_head)
    vocab = int(config.vocab_size)
    if getattr(config, "apply_residual_connection_post_layernorm", False):
        # HF adds the post-LN output (not the block input) to the
        # residual for these checkpoints — structurally different blocks;
        # refuse instead of importing wrong logits.
        raise ValueError("BLOOM apply_residual_connection_post_layernorm="
                         "True is not supported")
    drop = float(getattr(config, "hidden_dropout", 0.0) or 0.0)
    attn_drop = float(getattr(config, "attention_dropout", 0.0) or 0.0)

    layers: list[dict] = [
        {"embedding": {"num_embeddings": vocab, "embedding_dim": d},
         "normal": {"mean": 0.0, "std": 0.02}},
        {"layernorm": {"normalized_shape": d}},  # word_embeddings_layernorm
    ]
    for _ in range(n):
        layers.append({"residual": [
            {"sequential": [
                {"layernorm": {"normalized_shape": d}},
                {"linear": {"in_features": d, "out_features": 3 * d},
                 "normal": {"mean": 0.0, "std": 0.02}, "zeros": {}},
                {"attention": {"num_heads": heads, "dropout": attn_drop,
                               "alibi": True}},
                {"linear": {"in_features": d, "out_features": d},
                 "normal": {"mean": 0.0, "std": 0.02}, "zeros": {}},
                {"dropout": {"p": drop}}]},
            {"sequential": [
                {"layernorm": {"normalized_shape": d}},
                {"linear": {"in_features": d, "out_features": 4 * d},
                 "normal": {"mean": 0.0, "std": 0.02}, "zeros": {}},
                {"gelu": {"approximate": "tanh"}},  # BloomGelu
                {"linear": {"in_features": 4 * d, "out_features": d},
                 "normal": {"mean": 0.0, "std": 0.02}, "zeros": {}},
                {"dropout": {"p": drop}}]}]})
    layers += [
        {"layernorm": {"normalized_shape": d}},
        {"linear": {"in_features": d, "out_features": vocab, "bias": False},
         "normal": {"mean": 0.0, "std": 0.02}},
        {"softmaxlast": {"dim": -1}},
    ]
    return layers


def _map_bloom_state_dict(sd: dict, n_layer: int, config=None) -> dict:
    """BLOOM HF keys → ours.  The fused ``query_key_value`` is PER-HEAD
    interleaved — rows grouped ``[h0: q,k,v | h1: q,k,v | …]`` as
    ``(H, 3, D, d)`` — while our attention expects ``[all q | all k |
    all v]``; the transpose happens here, at import, so no runtime
    layout variant exists."""
    pfx = "transformer"
    cfg = _llama_text_config(config)
    if cfg is None or getattr(cfg, "n_head", None) is None:
        # Mirror the GPT-2 Conv1D-sniff refusal: the key sniff
        # (word_embeddings_layernorm) dispatches here even config-less, but
        # the per-head QKV de-interleave needs n_head — dying later with a
        # bare AttributeError would hide what is actually missing.
        raise ValueError(
            "BLOOM import requires the HF config (n_head drives the "
            "per-head query_key_value de-interleave); pass the "
            "checkpoint's config to map_hf_state_dict_to_custom")
    heads = int(cfg.n_head)

    def deinterleave(arr):
        return _deinterleave_per_head(arr, heads)

    out = {
        "layers.0.weight": sd[f"{pfx}.word_embeddings.weight"],
        "layers.1.weight": sd[f"{pfx}.word_embeddings_layernorm.weight"],
        "layers.1.bias": sd[f"{pfx}.word_embeddings_layernorm.bias"],
    }
    for i in range(n_layer):
        src = f"{pfx}.h.{i}"
        dst = f"layers.{2 + i}"
        out[f"{dst}.0.0.weight"] = sd[f"{src}.input_layernorm.weight"]
        out[f"{dst}.0.0.bias"] = sd[f"{src}.input_layernorm.bias"]
        qkv = f"{src}.self_attention.query_key_value"
        out[f"{dst}.0.1.weight"] = deinterleave(sd[f"{qkv}.weight"])
        out[f"{dst}.0.1.bias"] = deinterleave(sd[f"{qkv}.bias"])
        out[f"{dst}.0.3.weight"] = sd[f"{src}.self_attention.dense.weight"]
        out[f"{dst}.0.3.bias"] = sd[f"{src}.self_attention.dense.bias"]
        out[f"{dst}.1.0.weight"] = \
            sd[f"{src}.post_attention_layernorm.weight"]
        out[f"{dst}.1.0.bias"] = sd[f"{src}.post_attention_layernorm.bias"]
        out[f"{dst}.1.1.weight"] = sd[f"{src}.mlp.dense_h_to_4h.weight"]
        out[f"{dst}.1.1.bias"] = sd[f"{src}.mlp.dense_h_to_4h.bias"]
        out[f"{dst}.1.3.weight"] = sd[f"{src}.mlp.dense_4h_to_h.weight"]
        out[f"{dst}.1.3.bias"] = sd[f"{src}.mlp.dense_4h_to_h.bias"]
    out[f"layers.{2 + n_layer}.weight"] = sd[f"{pfx}.ln_f.weight"]
    out[f"layers.{2 + n_layer}.bias"] = sd[f"{pfx}.ln_f.bias"]
    out[f"layers.{3 + n_layer}.weight"] = sd.get(
        "lm_head.weight", sd[f"{pfx}.word_embeddings.weight"])
    return out


def _mpt_dsl_from_config(config, n_layer_override=None) -> list[dict]:
    """MPT HF config → layer DSL: ALiBi attention (no positional
    embedding), weight-only LayerNorms, bias-free projections, fused
    ``Wqkv`` already in our [q|k|v] layout, exact-GELU 4× MLPs, optional
    ``clip_qkv`` clamp (the OLMo v1 mechanism).

    Refused loudly (wrong math otherwise): ``alibi=False`` checkpoints
    (learned-position MPTs), non-``multihead_attention`` attn types,
    ``qk_ln``, custom ``softmax_scale``, and non-power-of-two head
    counts — MPT's non-pow2 slope interleave differs from the standard
    ALiBi formula our attention computes."""
    import math as _math
    d = int(config.d_model)
    n = int(n_layer_override if n_layer_override else config.n_layers)
    heads = int(config.n_heads)
    vocab = int(config.vocab_size)
    eps = float(getattr(config, "layer_norm_epsilon", 1e-5))
    no_bias = bool(getattr(config, "no_bias", True))
    expansion = int(getattr(config, "expansion_ratio", 4))
    attn_cfg = getattr(config, "attn_config", None)
    get = (attn_cfg.get if isinstance(attn_cfg, dict)
           else lambda k, dflt=None: getattr(attn_cfg, k, dflt))
    if attn_cfg is None or not get("alibi", False):
        raise ValueError("MPT without alibi (learned-position variants) "
                         "is not supported")
    if get("attn_type", "multihead_attention") != "multihead_attention":
        raise ValueError(f"MPT attn_type {get('attn_type')!r} is not "
                         "supported (multihead_attention only)")
    if get("qk_ln", False):
        raise ValueError("MPT qk_ln is not supported")
    if get("softmax_scale") is not None:
        raise ValueError("MPT custom softmax_scale is not supported")
    if not _math.log2(heads).is_integer():
        raise ValueError(
            f"MPT with non-power-of-two heads ({heads}) is not supported: "
            "its slope interleave differs from the standard ALiBi formula")
    clip = get("clip_qkv")
    attn_drop = float(get("attn_pdrop", 0.0) or 0.0)

    layers: list[dict] = [
        {"embedding": {"num_embeddings": vocab, "embedding_dim": d},
         "normal": {"mean": 0.0, "std": 0.02}},
    ]
    for _ in range(n):
        attn_items = [
            {"layernorm": {"normalized_shape": d, "eps": eps,
                           "bias": False}},
            {"linear": {"in_features": d, "out_features": 3 * d,
                        "bias": not no_bias},
             "normal": {"mean": 0.0, "std": 0.02}, "zeros": {}},
        ]
        if clip is not None:
            attn_items.append({"clamp": {"min": -float(clip),
                                         "max": float(clip)}})
        attn_items += [
            # head_dim explicit: the optional clamp between the QKV
            # linear and the attention breaks adjacency-based inference
            {"attention": {"num_heads": heads, "dropout": attn_drop,
                           "alibi": True, "head_dim": d // heads}},
            {"linear": {"in_features": d, "out_features": d,
                        "bias": not no_bias},
             "normal": {"mean": 0.0, "std": 0.02}, "zeros": {}},
        ]
        layers.append({"residual": [
            {"sequential": attn_items},
            {"sequential": [
                {"layernorm": {"normalized_shape": d, "eps": eps,
                               "bias": False}},
                {"linear": {"in_features": d,
                            "out_features": expansion * d,
                            "bias": not no_bias},
                 "normal": {"mean": 0.0, "std": 0.02}, "zeros": {}},
                {"gelu": {}},  # MptMLP: nn.GELU(approximate="none")
                {"linear": {"in_features": expansion * d,
                            "out_features": d, "bias": not no_bias},
                 "normal": {"mean": 0.0, "std": 0.02}, "zeros": {}}]}]})
    layers += [
        {"layernorm": {"normalized_shape": d, "eps": eps, "bias": False}},
        {"linear": {"in_features": d, "out_features": vocab, "bias": False},
         "normal": {"mean": 0.0, "std": 0.02}},
        {"softmaxlast": {"dim": -1}},
    ]
    return layers


def _map_mpt_state_dict(sd: dict, n_layer: int, config=None) -> dict:
    """MPT HF keys → ours: straight copies — ``Wqkv`` is already fused in
    our [q|k|v] row order, the LayerNorms carry weights only, and the
    clamp entry (clip_qkv) shifts the attention branch's item indices
    exactly like OLMo v1."""
    cfg = _llama_text_config(config)
    attn_cfg = getattr(cfg, "attn_config", None) if cfg is not None else None
    get = (attn_cfg.get if isinstance(attn_cfg, dict)
           else lambda k, dflt=None: getattr(attn_cfg, k, dflt))
    has_clip = attn_cfg is not None and get("clip_qkv") is not None
    i_out = 4 if has_clip else 3  # [ln, qkv, (clamp,) attention, out]
    # Refuse-loudly contract: every HF MptConfig ships weight-only norms
    # (verified against transformers — even no_bias=False leaves them
    # bias-free), and the DSL hardcodes bias:False accordingly.  A future
    # variant shipping norm biases must fail here, not import silently
    # without them.
    norm_bias_keys = sorted(
        k for k in sd
        if k.endswith((".norm_1.bias", ".norm_2.bias"))
        or k == "transformer.norm_f.bias")
    if norm_bias_keys:
        raise ValueError(
            "MPT checkpoint carries LayerNorm biases "
            f"({norm_bias_keys[:3]}...); this importer maps MPT norms as "
            "weight-only (every released MptConfig) and refuses rather "
            "than dropping the biases")
    out = {"layers.0.weight": sd["transformer.wte.weight"]}
    for i in range(n_layer):
        src = f"transformer.blocks.{i}"
        dst = f"layers.{1 + i}"
        out[f"{dst}.0.0.weight"] = sd[f"{src}.norm_1.weight"]
        out[f"{dst}.0.1.weight"] = sd[f"{src}.attn.Wqkv.weight"]
        if f"{src}.attn.Wqkv.bias" in sd:
            out[f"{dst}.0.1.bias"] = sd[f"{src}.attn.Wqkv.bias"]
        out[f"{dst}.0.{i_out}.weight"] = sd[f"{src}.attn.out_proj.weight"]
        if f"{src}.attn.out_proj.bias" in sd:
            out[f"{dst}.0.{i_out}.bias"] = sd[f"{src}.attn.out_proj.bias"]
        out[f"{dst}.1.0.weight"] = sd[f"{src}.norm_2.weight"]
        out[f"{dst}.1.1.weight"] = sd[f"{src}.ffn.up_proj.weight"]
        out[f"{dst}.1.3.weight"] = sd[f"{src}.ffn.down_proj.weight"]
        if f"{src}.ffn.up_proj.bias" in sd:
            out[f"{dst}.1.1.bias"] = sd[f"{src}.ffn.up_proj.bias"]
            out[f"{dst}.1.3.bias"] = sd[f"{src}.ffn.down_proj.bias"]
    out[f"layers.{1 + n_layer}.weight"] = sd["transformer.norm_f.weight"]
    out[f"layers.{2 + n_layer}.weight"] = sd.get(
        "lm_head.weight", sd["transformer.wte.weight"])
    return out


def _opt_dsl_from_config(config, n_layer_override=None) -> list[dict]:
    """OPT HF config → layer DSL: GPT-2-shaped pre-LN blocks with
    separate-then-fused biased QKV, ReLU MLPs, and LEARNED positions
    whose +2 row offset (HF OPTLearnedPositionalEmbedding) is folded
    away at import time by dropping the table's first two rows — no
    runtime position hack survives.

    Refused loudly: ``do_layer_norm_before=False`` (OPT-350m post-norm
    ordering) and ``word_embed_proj_dim != hidden_size`` (the 350m
    in/out projections) — silently approximating either would import
    wrong logits.
    """
    d = int(config.hidden_size)
    n = int(n_layer_override if n_layer_override else
            config.num_hidden_layers)
    if not getattr(config, "do_layer_norm_before", True):
        raise ValueError("OPT do_layer_norm_before=False (350m post-norm "
                         "ordering) is not supported")
    proj_dim = getattr(config, "word_embed_proj_dim", d) or d
    if int(proj_dim) != d:
        raise ValueError("OPT word_embed_proj_dim != hidden_size "
                         "(embedding in/out projections) is not supported")
    heads = int(config.num_attention_heads)
    vocab = int(config.vocab_size)
    block = int(config.max_position_embeddings)
    ffn = int(getattr(config, "ffn_dim", 4 * d))
    bias = bool(getattr(config, "enable_bias", True))
    act = str(getattr(config, "activation_function", "relu"))
    act_entry = _gelu_entry(act, "opt")  # raises on unsupported strings
    # HF OPT applies `dropout` to the embedding and BOTH residual streams
    # and `attention_dropout` to the attention probabilities — distinct
    # knobs (opt-125m ships 0.1 / 0.0).
    drop = float(getattr(config, "dropout", 0.0) or 0.0)
    attn_drop = float(getattr(config, "attention_dropout", 0.0) or 0.0)

    layers: list[dict] = [
        {"summation": [
            {"embedding": {"num_embeddings": vocab, "embedding_dim": d},
             "normal": {"mean": 0.0, "std": 0.02}},
            {"position": {"num_embeddings": block, "embedding_dim": d},
             "normal": {"mean": 0.0, "std": 0.02}}]},
        {"dropout": {"p": drop}},
    ]
    for _ in range(n):
        layers.append({"residual": [
            {"sequential": [
                {"layernorm": {"normalized_shape": d}},
                {"linear": {"in_features": d, "out_features": 3 * d,
                            "bias": bias},
                 "normal": {"mean": 0.0, "std": 0.02}, "zeros": {}},
                {"attention": {"num_heads": heads, "dropout": attn_drop}},
                {"linear": {"in_features": d, "out_features": d,
                            "bias": bias},
                 "normal": {"mean": 0.0, "std": 0.02}, "zeros": {}},
                {"dropout": {"p": drop}}]},
            {"sequential": [
                {"layernorm": {"normalized_shape": d}},
                {"linear": {"in_features": d, "out_features": ffn,
                            "bias": bias},
                 "normal": {"mean": 0.0, "std": 0.02}, "zeros": {}},
                act_entry,
                {"linear": {"in_features": ffn, "out_features": d,
                            "bias": bias},
                 "normal": {"mean": 0.0, "std": 0.02}, "zeros": {}},
                {"dropout": {"p": drop}}]}]})
    layers += [
        {"layernorm": {"normalized_shape": d}},
        {"linear": {"in_features": d, "out_features": vocab, "bias": False},
         "normal": {"mean": 0.0, "std": 0.02}},
        {"softmaxlast": {"dim": -1}},
    ]
    return layers


def _map_opt_state_dict(sd: dict, n_layer: int, config=None) -> dict:
    """OPT HF keys → ours.  ``model.decoder.*`` layout, separate q/k/v
    fused by concatenation, and the learned position table's first two
    rows DROPPED (HF looks positions up at ``pos + 2``; with full
    attention masks that is exactly a 0-based lookup into ``table[2:]``,
    including cached decode where our offset is the cache length)."""
    dec = "model.decoder"
    out = {
        "layers.0.0.weight": sd[f"{dec}.embed_tokens.weight"],
        "layers.0.1.weight":
            np.asarray(sd[f"{dec}.embed_positions.weight"])[2:],
    }
    for i in range(n_layer):
        src = f"{dec}.layers.{i}"
        dst = f"layers.{2 + i}"
        _concat_qkv(sd, src, out, f"{dst}.0.1")
        out[f"{dst}.0.0.weight"] = sd[f"{src}.self_attn_layer_norm.weight"]
        out[f"{dst}.0.0.bias"] = sd[f"{src}.self_attn_layer_norm.bias"]
        out[f"{dst}.0.3.weight"] = sd[f"{src}.self_attn.out_proj.weight"]
        if f"{src}.self_attn.out_proj.bias" in sd:
            out[f"{dst}.0.3.bias"] = sd[f"{src}.self_attn.out_proj.bias"]
        out[f"{dst}.1.0.weight"] = sd[f"{src}.final_layer_norm.weight"]
        out[f"{dst}.1.0.bias"] = sd[f"{src}.final_layer_norm.bias"]
        out[f"{dst}.1.1.weight"] = sd[f"{src}.fc1.weight"]
        out[f"{dst}.1.3.weight"] = sd[f"{src}.fc2.weight"]
        if f"{src}.fc1.bias" in sd:
            out[f"{dst}.1.1.bias"] = sd[f"{src}.fc1.bias"]
            out[f"{dst}.1.3.bias"] = sd[f"{src}.fc2.bias"]
    out[f"layers.{2 + n_layer}.weight"] = \
        sd[f"{dec}.final_layer_norm.weight"]
    out[f"layers.{2 + n_layer}.bias"] = sd[f"{dec}.final_layer_norm.bias"]
    out[f"layers.{3 + n_layer}.weight"] = sd.get(
        "lm_head.weight", sd[f"{dec}.embed_tokens.weight"])
    return out


def _bigcode_dsl_from_config(config, n_layer_override=None) -> list[dict]:
    """GPT-BigCode (StarCoder/SantaCoder) HF config → layer DSL: the
    GPT-2 structure (learned positions, pre-LN sequential residual,
    biased projections) with MULTI-QUERY attention — the fused ``c_attn``
    is ``[all q, k, v]`` with one kv head, exactly our layout — and
    ``nn.Linear`` weights (no Conv1D transpose, unlike GPT-2).
    ``multi_query=False`` checkpoints keep all heads."""
    cfg = _llama_text_config(config)
    if not getattr(cfg, "scale_attn_weights", True):
        raise ValueError("scale_attn_weights=False gpt_bigcode "
                         "checkpoints are not supported; importing would "
                         "produce wrong logits")
    d = int(cfg.n_embd if hasattr(cfg, "n_embd") else cfg.hidden_size)
    n = int(n_layer_override if n_layer_override
            else getattr(cfg, "num_hidden_layers", None) or cfg.n_layer)
    heads = int(getattr(cfg, "num_attention_heads", None) or cfg.n_head)
    kv = 1 if bool(getattr(cfg, "multi_query", True)) else heads
    hd = d // heads
    vocab = int(cfg.vocab_size)
    block = int(getattr(cfg, "n_positions", None)
                or getattr(cfg, "max_position_embeddings", 1024))
    eps = float(getattr(cfg, "layer_norm_epsilon", 1e-5))
    attn_drop = float(getattr(cfg, "attn_pdrop", 0.0) or 0.0)
    resid_drop = float(getattr(cfg, "resid_pdrop", 0.0) or 0.0)
    embd_drop = float(getattr(cfg, "embd_pdrop", 0.0) or 0.0)
    inter = int(getattr(cfg, "n_inner", None) or 4 * d)
    gelu = _gelu_entry(getattr(cfg, "activation_function",
                               "gelu_pytorch_tanh"), "gpt_bigcode")

    layers: list[dict] = [
        {"summation": [
            {"embedding": {"num_embeddings": vocab, "embedding_dim": d},
             "normal": {"mean": 0.0, "std": 0.02}},
            {"position": {"num_embeddings": block, "embedding_dim": d},
             "normal": {"mean": 0.0, "std": 0.02}}]},
        {"dropout": {"p": embd_drop}},
    ]
    for _ in range(n):
        layers.append({"residual": [
            {"sequential": [
                {"layernorm": {"normalized_shape": d, "eps": eps}},
                {"linear": {"in_features": d,
                            "out_features": (heads + 2 * kv) * hd}},
                {"attention": {"num_heads": heads, "num_kv_heads": kv,
                               "dropout": attn_drop}},
                {"linear": {"in_features": heads * hd, "out_features": d}},
                {"dropout": {"p": resid_drop}}]},
            {"sequential": [
                {"layernorm": {"normalized_shape": d, "eps": eps}},
                {"linear": {"in_features": d, "out_features": inter}},
                gelu,
                {"linear": {"in_features": inter, "out_features": d}},
                {"dropout": {"p": resid_drop}}]}]})
    layers += [
        {"layernorm": {"normalized_shape": d, "eps": eps}},
        {"linear": {"in_features": d, "out_features": vocab, "bias": False}},
        {"softmaxlast": {"dim": -1}},
    ]
    return layers


def _map_bigcode_state_dict(sd: dict, n_layer: int, config=None) -> dict:
    """GPT-BigCode HF keys → ours: plain nn.Linear copies (no Conv1D
    transpose), tied head fallback.  The fused ``c_attn`` is [all q, k,
    v] under multi_query (our layout), but multi_query=False checkpoints
    store it PER-HEAD interleaved [q_h; k_h; v_h] (HF views it as
    (num_heads, 3·head_dim)) — the NeoX de-interleave reorders it."""
    cfg = _llama_text_config(config)
    multi_query = bool(getattr(cfg, "multi_query", True))
    heads = int(getattr(cfg, "num_attention_heads", None) or cfg.n_head)

    def fix_qkv(w):
        return w if multi_query else _neox_deinterleave_qkv(w, heads)

    out = {"layers.0.0.weight": sd["transformer.wte.weight"],
           "layers.0.1.weight": sd["transformer.wpe.weight"]}
    for i in range(n_layer):
        src = f"transformer.h.{i}"
        dst = f"layers.{2 + i}"
        for at, hf, fix in (
                (f"{dst}.0.0", "ln_1", None),
                (f"{dst}.0.1", "attn.c_attn", fix_qkv),
                (f"{dst}.0.3", "attn.c_proj", None),
                (f"{dst}.1.0", "ln_2", None),
                (f"{dst}.1.1", "mlp.c_fc", None),
                (f"{dst}.1.3", "mlp.c_proj", None)):
            w = sd[f"{src}.{hf}.weight"]
            out[f"{at}.weight"] = fix(w) if fix else w
            if f"{src}.{hf}.bias" in sd:
                b = sd[f"{src}.{hf}.bias"]
                out[f"{at}.bias"] = fix(b) if fix else b
    for name in ("weight", "bias"):
        out[f"layers.{2 + n_layer}.{name}"] = sd[f"transformer.ln_f.{name}"]
    out[f"layers.{3 + n_layer}.weight"] = sd.get(
        "lm_head.weight", sd["transformer.wte.weight"])
    return out


def _map_gpt2_state_dict(sd: dict, n_layer: int) -> dict:
    """GPT-2 HF keys → ours; Conv1D weights transposed, lm_head tied to wte
    (reference: mappers.py:333-352)."""
    out = {
        "layers.0.0.weight": sd["transformer.wte.weight"],
        "layers.0.1.weight": sd["transformer.wpe.weight"],
    }
    ln_map = {"ln_1": "0.0", "ln_2": "1.0"}
    conv1d_map = {"attn.c_attn": "0.1", "attn.c_proj": "0.3",
                  "mlp.c_fc": "1.1", "mlp.c_proj": "1.3"}
    for i in range(n_layer):
        src = f"transformer.h.{i}"
        dst = f"layers.{2 + i}"
        for hf_name, ours in ln_map.items():
            out[f"{dst}.{ours}.weight"] = sd[f"{src}.{hf_name}.weight"]
            out[f"{dst}.{ours}.bias"] = sd[f"{src}.{hf_name}.bias"]
        for hf_name, ours in conv1d_map.items():
            # HF Conv1D stores (in, out); our Linear stores (out, in).
            out[f"{dst}.{ours}.weight"] = \
                np.ascontiguousarray(sd[f"{src}.{hf_name}.weight"].T)
            out[f"{dst}.{ours}.bias"] = sd[f"{src}.{hf_name}.bias"]
    out[f"layers.{2 + n_layer}.weight"] = sd["transformer.ln_f.weight"]
    out[f"layers.{2 + n_layer}.bias"] = sd["transformer.ln_f.bias"]
    out[f"layers.{3 + n_layer}.weight"] = sd.get(
        "lm_head.weight", sd["transformer.wte.weight"])
    return out


# ---------------------------------------------------------------------------
# Gemma family
# ---------------------------------------------------------------------------

def _gemma_text_config(config):
    return getattr(config, "text_config", None) or config


def _gemma_rope_theta(cfg, layer_type: str) -> float:
    """Per-layer RoPE theta: Gemma-3's ``rope_local_base_freq`` for
    sliding layers, else prefer a matching per-layer-type
    ``rope_scaling`` entry, fall back to any entry, then to
    ``rope_theta`` (reference: mappers.py:198-222)."""
    if layer_type == "sliding_attention":
        local = getattr(cfg, "rope_local_base_freq", None)
        if local:
            return float(local)
    scaling = getattr(cfg, "rope_scaling", None)
    if isinstance(scaling, dict) and scaling:
        entry = scaling.get(layer_type)
        if not isinstance(entry, dict):
            entry = next(iter(scaling.values()))
        if isinstance(entry, dict) and "rope_theta" in entry:
            return float(entry["rope_theta"])
    theta = getattr(cfg, "rope_theta", None)
    return float(theta) if theta else 10000.0


def _gemma_dsl_from_config(config, n_layer_override=None) -> list[dict]:
    """Gemma 1/2/3/4 HF config → layer DSL, incl. GQA dims, per-layer
    heterogeneous ``layer_types`` and double-wide MLPs on KV-shared layers
    (reference: mappers.py:178-262)."""
    model_type = getattr(config, "model_type", "gemma")
    cfg = _gemma_text_config(config)
    d = int(cfg.hidden_size)
    n = int(n_layer_override if n_layer_override else cfg.num_hidden_layers)
    heads = int(cfg.num_attention_heads)
    vocab = int(cfg.vocab_size)
    eps = float(getattr(cfg, "rms_norm_eps", 1e-6))
    attn_drop = float(getattr(cfg, "attention_dropout", 0.0) or 0.0)
    activation = getattr(cfg, "hidden_activation", "gelu_pytorch_tanh")
    layer_types = list(getattr(cfg, "layer_types", None)
                       or ["full_attention"] * n)
    num_kv_shared = int(getattr(cfg, "num_kv_shared_layers", 0) or 0)
    double_wide = bool(getattr(cfg, "use_double_wide_mlp", False))
    # gemma (v1): no post-attn/post-mlp norms; gemma2: norms applied to the
    # branch output; gemma3+: norms applied to the residual sum
    # (reference: neural_net_layers.py:188-225 block variants).
    has_post_norms = model_type != "gemma"
    # HF Gemma3DecoderLayer norms the BRANCH OUTPUT before the residual
    # add, exactly like Gemma-2 (verified against modeling_gemma3); the
    # residual-sum placement is the later-variant convention the
    # reference's block switch models (neural_net_layers.py:188-225).
    post_norm_on_residual = model_type not in ("gemma", "gemma2",
                                               "gemma3", "gemma3_text")
    # Gemma-3 attention ALWAYS per-head-RMS-normalizes q and k (HF
    # Gemma3Attention q_norm/k_norm — zero-centered weights, +1 at
    # import) and its GLOBAL layers may carry linear rope scaling
    # ({'rope_type': 'linear', 'factor': 8.0} on the released >1B
    # configs); local layers rotate with rope_local_base_freq unscaled.
    gemma3 = model_type in ("gemma3", "gemma3_text")
    g3_scaling = None
    if gemma3:
        raw = getattr(cfg, "rope_scaling", None)
        if isinstance(raw, dict) and raw and (
                raw.get("rope_type") or raw.get("type")):
            g3_scaling = {"rope_type": (raw.get("rope_type")
                                        or raw.get("type")),
                          "factor": float(raw.get("factor", 1.0))}

    def head_dim_for(layer_type: str) -> int:
        if layer_type == "full_attention" and \
                getattr(cfg, "global_head_dim", None):
            return int(cfg.global_head_dim)
        return int(cfg.head_dim)

    def kv_heads_for(layer_type: str) -> int:
        if layer_type == "full_attention" and \
                getattr(cfg, "num_global_key_value_heads", None):
            return int(cfg.num_global_key_value_heads)
        return int(cfg.num_key_value_heads)

    layers: list[dict] = [
        {"scaledembedding": {"num_embeddings": vocab, "embedding_dim": d,
                             "scale": d ** 0.5},
         "normal": {"mean": 0.0, "std": 0.02}},
    ]
    for i in range(n):
        layer_type = layer_types[i] if i < len(layer_types) else "full_attention"
        hd = head_dim_for(layer_type)
        kv = kv_heads_for(layer_type)
        inter = int(cfg.intermediate_size)
        if double_wide and num_kv_shared and i >= n - num_kv_shared:
            inter *= 2
        block: dict[str, Any] = {
            "attn_block": {"sequential": [
                {"rmsnorm": {"normalized_shape": d, "eps": eps}},
                {"linear": {"in_features": d,
                            "out_features": (heads + 2 * kv) * hd,
                            "bias": False}},
                {"attention": dict(
                    {"num_heads": heads, "num_kv_heads": kv,
                     "rope_theta": _gemma_rope_theta(cfg, layer_type),
                     "head_dim": hd, "dropout": attn_drop},
                    # Gemma-2: score soft-capping + the
                    # query_pre_attn_scalar scale override (silently
                    # dropping either imports wrong logits on real
                    # checkpoints; tiny-model parity can't catch the cap
                    # because random logits sit far below it)
                    **({"logit_softcap": float(cfg.attn_logit_softcapping)}
                       if getattr(cfg, "attn_logit_softcapping", None)
                       else {}),
                    # omitted when it equals the default head_dim
                    # scaling (Gemma-2 9B, Gemma-3 released configs) so
                    # downstream non-default-scale handling stays off
                    **({"attn_scale":
                        float(cfg.query_pre_attn_scalar) ** -0.5}
                       if (getattr(cfg, "query_pre_attn_scalar", None)
                           and float(cfg.query_pre_attn_scalar) != hd)
                       else {}),
                    **({"qk_norm": True, "qk_norm_eps":
                        eps, "qk_norm_fp32_weight": True}
                       if gemma3 else {}),
                    **({"rope_scaling": g3_scaling}
                       if g3_scaling and layer_type == "full_attention"
                       else {}),
                    # sliding layers get REAL windowed attention (the
                    # reference keeps all attention full causal and maps
                    # layer_types to dims only, mappers.py:224-228)
                    **({"sliding_window": int(cfg.sliding_window)}
                       if layer_type == "sliding_attention"
                       and getattr(cfg, "sliding_window", None) else {}))},
                {"linear": {"in_features": heads * hd, "out_features": d,
                            "bias": False}}]},
            "mlp_block": {"sequential": [
                {"rmsnorm": {"normalized_shape": d, "eps": eps}},
                {"gatedmlp": {"in_features": d, "intermediate_size": inter,
                              "activation": activation}}]},
            "post_norm_on_residual": post_norm_on_residual,
        }
        if has_post_norms:
            block["post_attn_norm"] = {"rmsnorm": {"normalized_shape": d,
                                                   "eps": eps}}
            block["post_mlp_norm"] = {"rmsnorm": {"normalized_shape": d,
                                                  "eps": eps}}
        layers.append({"transformerblock": block})
    layers += [
        {"rmsnorm": {"normalized_shape": d, "eps": eps}},
        {"linear": {"in_features": d, "out_features": vocab, "bias": False}},
    ]
    final_cap = getattr(cfg, "final_logit_softcapping", None)
    if final_cap:
        # Gemma-2 caps the lm-head logits too (HF final_logit_softcapping)
        layers.append({"softcap": {"cap": float(final_cap)}})
    layers.append({"softmaxlast": {"dim": -1}})
    return layers


def _plus_one(arr):
    """RMSNorm weight offset: HF Gemma stores ``w`` and applies ``x*(1+w)``;
    our RMSNorm multiplies directly (reference: mappers.py:401,424-442)."""
    a = np.asarray(arr)
    return (a.astype(np.float32) + 1.0).astype(a.dtype)


def _map_gemma_state_dict(sd: dict, n_layer: int, config=None) -> dict:
    """Gemma HF keys → ours: QKV concat, +1 RMSNorm offset, KV-shared-layer
    copy from the reference layer, multimodal prefix (reference:
    mappers.py:356-448)."""
    prefix = "model"
    if any(k.startswith("model.language_model.") for k in sd):
        prefix = "model.language_model"
    model_type = getattr(config, "model_type", "gemma2") if config else "gemma2"
    cfg = _gemma_text_config(config) if config is not None else None
    has_post_norms = model_type != "gemma"
    num_kv_shared = int(getattr(cfg, "num_kv_shared_layers", 0) or 0) if cfg else 0
    layer_types = list(getattr(cfg, "layer_types", None) or []) if cfg else []

    out = {"layers.0.weight": sd[f"{prefix}.embed_tokens.weight"]}
    for i in range(n_layer):
        src = f"{prefix}.layers.{i}"
        dst = f"layers.{1 + i}"
        # KV-shared layers read K/V from the last same-type non-shared layer.
        kv_src_idx = i
        if num_kv_shared and i >= n_layer - num_kv_shared and layer_types:
            own_type = layer_types[i] if i < len(layer_types) else None
            for j in range(n_layer - num_kv_shared - 1, -1, -1):
                if j < len(layer_types) and layer_types[j] == own_type:
                    kv_src_idx = j
                    break
        kv_src = f"{prefix}.layers.{kv_src_idx}"
        out[f"{dst}.attn_block.1.weight"] = np.concatenate(
            [np.asarray(sd[f"{src}.self_attn.q_proj.weight"]),
             np.asarray(sd[f"{kv_src}.self_attn.k_proj.weight"]),
             np.asarray(sd[f"{kv_src}.self_attn.v_proj.weight"])], axis=0)
        out[f"{dst}.attn_block.0.weight"] = \
            _plus_one(sd[f"{src}.input_layernorm.weight"])
        if f"{src}.self_attn.q_norm.weight" in sd:
            # Gemma-3 per-head qk-norms (zero-centered like every gemma
            # RMSNorm); K comes from the KV-source layer on shared layers
            out[f"{dst}.attn_block.2.q_norm.weight"] = \
                _plus_one(sd[f"{src}.self_attn.q_norm.weight"])
            out[f"{dst}.attn_block.2.k_norm.weight"] = \
                _plus_one(sd[f"{kv_src}.self_attn.k_norm.weight"])
        out[f"{dst}.attn_block.3.weight"] = sd[f"{src}.self_attn.o_proj.weight"]
        if has_post_norms:
            out[f"{dst}.post_attn_norm.weight"] = \
                _plus_one(sd[f"{src}.post_attention_layernorm.weight"])
            out[f"{dst}.mlp_block.0.weight"] = \
                _plus_one(sd[f"{src}.pre_feedforward_layernorm.weight"])
            out[f"{dst}.post_mlp_norm.weight"] = \
                _plus_one(sd[f"{src}.post_feedforward_layernorm.weight"])
        else:
            # gemma1: the post-attention norm IS the pre-MLP norm.
            out[f"{dst}.mlp_block.0.weight"] = \
                _plus_one(sd[f"{src}.post_attention_layernorm.weight"])
        for proj in ("gate_proj", "up_proj", "down_proj"):
            out[f"{dst}.mlp_block.1.{proj}.weight"] = \
                sd[f"{src}.mlp.{proj}.weight"]
    out[f"layers.{1 + n_layer}.weight"] = _plus_one(sd[f"{prefix}.norm.weight"])
    out[f"layers.{2 + n_layer}.weight"] = sd.get(
        "lm_head.weight", sd[f"{prefix}.embed_tokens.weight"])
    return out


# ---------------------------------------------------------------------------
# Llama family (beyond reference parity: mappers.py covers GPT-2 + Gemma
# only; Llama/Mistral/Qwen2 reuse the same GQA+RoPE+RMSNorm+GatedMLP
# modules with pre-norm blocks, no +1 norm offset and no embedding scale)
# ---------------------------------------------------------------------------

_LLAMA_FAMILY = ("llama", "mistral", "mixtral", "phi3", "qwen2", "qwen3",
                 "qwen2_moe")


def _llama_text_config(config):
    get = getattr(config, "get_text_config", None)
    return get() if callable(get) else config


def _llama_moe_entry(model_type: str, cfg, d: int, n: int,
                     activation: str) -> dict:
    """Sparse-MoE MLP entry for the llama family.

    Mixtral: softmax over ALL experts → top-k → renormalize; dense
    dispatch reproduces HF bit-for-bit.  The aux coefficient is rescaled
    toward HF's load_balancing_loss_func (ONE loss from fractions pooled
    across layers with top-k-summed slots): coef × top_k / n_layers
    matches the coefficient SCALE; the per-layer-vs-pooled structural
    difference remains — the Switch formulation, not a bug.

    Qwen2-MoE: fine-grained experts with ``norm_topk_prob`` (default
    False — raw softmax mass on the selected experts) plus an always-on
    shared expert behind a sigmoid token gate.  Non-default
    ``decoder_sparse_step``/``mlp_only_layers`` (dense layers mixed into
    the stack) are refused loudly — importing them as sparse would be
    wrong math.
    """
    if model_type == "qwen2_moe":
        if int(getattr(cfg, "decoder_sparse_step", 1) or 1) != 1 or                 list(getattr(cfg, "mlp_only_layers", []) or []):
            raise ValueError(
                "qwen2_moe with decoder_sparse_step != 1 or non-empty "
                "mlp_only_layers (dense layers mixed into the stack) is "
                "not supported")
        return {"moe": {
            "in_features": d,
            "intermediate_size": int(cfg.moe_intermediate_size),
            "num_experts": int(cfg.num_experts),
            "top_k": int(cfg.num_experts_per_tok),
            "activation": activation,
            "norm_topk": bool(getattr(cfg, "norm_topk_prob", False)),
            "shared_expert_size":
                int(cfg.shared_expert_intermediate_size),
            "aux_loss_coef": (
                float(getattr(cfg, "router_aux_loss_coef", 0.0) or 0.0)
                * int(cfg.num_experts_per_tok) / n)}}
    return {"moe": {"in_features": d,
                    "intermediate_size": int(cfg.intermediate_size),
                    "num_experts": int(cfg.num_local_experts),
                    "top_k": int(cfg.num_experts_per_tok),
                    "activation": activation,
                    "aux_loss_coef": (
                        float(getattr(cfg, "router_aux_loss_coef",
                                      0.0) or 0.0)
                        * int(cfg.num_experts_per_tok) / n)}}


def _llama_biases(model_type: str, cfg) -> tuple[bool, bool]:
    """(qkv_bias, o_bias).  Qwen2 hardcodes qkv bias on / o bias off in its
    attention module; Llama/Mistral follow ``attention_bias`` (default
    False) for all four projections."""
    if model_type in ("qwen2", "qwen2_moe"):
        return True, False
    bias = bool(getattr(cfg, "attention_bias", False) or False)
    return bias, bias


def _llama_dsl_from_config(config, n_layer_override=None) -> list[dict]:
    """Llama/Mistral/Qwen2/Qwen3 HF config → layer DSL.

    ``rope_scaling`` with ``rope_type='llama3'`` (Llama 3.1+) is applied as
    an inverse-frequency rescale (ops/attention.rope_cos_sin); other active
    types (yarn, dynamic, ...) raise — importing with them ignored would
    produce silently wrong logits.  A sliding window (Mistral) becomes real
    windowed attention (ops/attention window masks) — beyond the reference,
    which keeps all attention full causal (mappers.py:224-228).
    """
    model_type = getattr(config, "model_type", "llama")
    cfg = _llama_text_config(config)
    scaling = getattr(cfg, "rope_scaling", None) or None
    if scaling:
        rope_type = (scaling.get("rope_type") or scaling.get("type")
                     or "default")
        if rope_type == "default":
            scaling = None
        elif rope_type != "llama3":
            raise ValueError(
                f"rope_scaling {rope_type!r} is not supported; importing "
                "would produce wrong logits")
        else:
            scaling = {"rope_type": "llama3", **{
                k: float(scaling[k]) for k in
                ("factor", "low_freq_factor", "high_freq_factor",
                 "original_max_position_embeddings") if k in scaling}}
    window = getattr(cfg, "sliding_window", None)
    window = int(window) if window else None
    # Per-layer gating: Qwen2's use_sliding_window/max_window_layers (and
    # any llama-family config with layer_types) window only the layers HF
    # marks 'sliding_attention'; Mistral windows every layer.
    layer_types = list(getattr(cfg, "layer_types", None) or [])

    def window_for(i: int):
        if window is None:
            return None
        if layer_types:
            lt = layer_types[i] if i < len(layer_types) else "full_attention"
            return window if lt == "sliding_attention" else None
        return window
    d = int(cfg.hidden_size)
    n = int(n_layer_override if n_layer_override else cfg.num_hidden_layers)
    heads = int(cfg.num_attention_heads)
    kv = int(getattr(cfg, "num_key_value_heads", None) or heads)
    hd = int(getattr(cfg, "head_dim", None) or d // heads)
    vocab = int(cfg.vocab_size)
    eps = float(getattr(cfg, "rms_norm_eps", 1e-6))
    rope = float(getattr(cfg, "rope_theta", 10000.0) or 10000.0)
    attn_drop = float(getattr(cfg, "attention_dropout", 0.0) or 0.0)
    activation = getattr(cfg, "hidden_act", "silu")
    qkv_bias, o_bias = _llama_biases(model_type, cfg)
    if getattr(cfg, "mlp_bias", False):
        raise ValueError("mlp_bias=True Llama checkpoints are not supported")

    attn_args = {"num_heads": heads, "num_kv_heads": kv, "rope_theta": rope,
                 "head_dim": hd, "dropout": attn_drop}
    rope_pct = float(getattr(cfg, "partial_rotary_factor", 1.0) or 1.0)
    if rope_pct < 1.0:
        # Phi-3-family configs (e.g. Phi-4-mini ships model_type 'phi3'
        # with 0.75) rotate only the first pct of each head's dims —
        # ignoring it would import with silently wrong logits.
        attn_args["rope_pct"] = rope_pct
    if scaling:
        attn_args["rope_scaling"] = scaling
    if model_type == "qwen3":
        # Qwen3 RMS-normalizes q and k per head before RoPE with learned
        # (head_dim,) weights (HF Qwen3Attention q_norm/k_norm).
        attn_args.update(qk_norm=True, qk_norm_eps=eps)
    layers: list[dict] = [
        {"embedding": {"num_embeddings": vocab, "embedding_dim": d},
         "normal": {"mean": 0.0, "std": 0.02}},
    ]
    for i in range(n):
        layer_attn = dict(attn_args)
        if window_for(i) is not None:
            layer_attn["sliding_window"] = window_for(i)
        layers.append({"transformerblock": {
            "attn_block": {"sequential": [
                {"rmsnorm": {"normalized_shape": d, "eps": eps}},
                {"linear": {"in_features": d,
                            "out_features": (heads + 2 * kv) * hd,
                            "bias": qkv_bias}},
                {"attention": layer_attn},
                {"linear": {"in_features": heads * hd, "out_features": d,
                            "bias": o_bias}}]},
            "mlp_block": {"sequential": [
                {"rmsnorm": {"normalized_shape": d, "eps": eps}},
                # Mixtral: sparse MoE MLP.  Routing math matches our
                # module exactly (HF MixtralSparseMoeBlock: softmax over
                # ALL experts -> top-k -> renormalize); dense dispatch
                # reproduces it bit-for-bit, capacity dispatch stays an
                # opt-in.  The aux coefficient is rescaled toward HF's
                # load_balancing_loss_func: HF computes ONE loss from
                # fractions POOLED across all layers with top-k-summed
                # slots (uniform minimum top_k); our Switch form divides
                # by top_k (minimum 1) and applies per layer.  coef ×
                # top_k / n_layers matches the coefficient SCALE (equal
                # when routing statistics are layer-uniform); the
                # per-layer-vs-pooled structural difference remains — the
                # Switch formulation, not a bug.
                (_llama_moe_entry(model_type, cfg, d, n, activation)
                 if model_type in ("mixtral", "qwen2_moe") else
                 {"gatedmlp": {"in_features": d,
                               "intermediate_size":
                                   int(cfg.intermediate_size),
                               "activation": activation}})]},
            "post_norm_on_residual": False,
        }})
    layers += [
        {"rmsnorm": {"normalized_shape": d, "eps": eps}},
        {"linear": {"in_features": d, "out_features": vocab, "bias": False}},
        {"softmaxlast": {"dim": -1}},
    ]
    return layers


def _gelu_entry(act: str, family: str) -> dict:
    """HF activation string → DSL entry (shared by the NeoX/Phi/GPT-J
    builders; GPT-2 keeps its own historical mapping)."""
    if act in ("gelu_new", "gelu_pytorch_tanh", "gelu_fast"):
        return {"gelu": {"approximate": "tanh"}}
    if act == "gelu":
        return {"gelu": {}}
    if act == "relu":
        return {"relu": {}}
    raise ValueError(f"Unsupported {family} activation: {act!r}")


def _neox_dsl_from_config(config, n_layer_override=None) -> list[dict]:
    """GPT-NeoX/Pythia HF config → layer DSL.

    Two capabilities beyond the other families: the ``parallelresidual``
    container (``use_parallel_residual``: attention and MLP branches both
    read the pre-block activations, HF ``modeling_gpt_neox`` forward) and
    partial rotary (``rotary_pct`` → the attention module's ``rope_pct``).
    ``use_parallel_residual=False`` checkpoints get the ordinary
    sequential-residual block.
    """
    cfg = _llama_text_config(config)
    scaling = getattr(cfg, "rope_scaling", None) or None
    if scaling and (scaling.get("rope_type") or scaling.get("type")
                    or "default") != "default":
        # Same guard as the llama builder: importing with an active scaling
        # silently ignored would produce wrong logits.
        raise ValueError(
            f"gpt_neox rope_scaling {scaling!r} is not supported; importing "
            "would produce wrong logits")
    d = int(cfg.hidden_size)
    n = int(n_layer_override if n_layer_override else cfg.num_hidden_layers)
    heads = int(cfg.num_attention_heads)
    vocab = int(cfg.vocab_size)
    eps = float(getattr(cfg, "layer_norm_eps", 1e-5))
    rope = float(getattr(cfg, "rope_theta", None)
                 or getattr(cfg, "rotary_emb_base", None) or 10000.0)
    rope_pct = getattr(cfg, "rotary_pct", None)
    rope_pct = 0.25 if rope_pct is None else float(rope_pct)
    attn_bias = bool(getattr(cfg, "attention_bias", True))
    attn_drop = float(getattr(cfg, "attention_dropout", 0.0) or 0.0)
    hidden_drop = float(getattr(cfg, "hidden_dropout", 0.0) or 0.0)
    act_entry = _gelu_entry(getattr(cfg, "hidden_act", "gelu"), "gpt_neox")
    parallel = bool(getattr(cfg, "use_parallel_residual", True))
    inter = int(getattr(cfg, "intermediate_size", None) or 4 * d)

    attn_args = {"num_heads": heads, "dropout": attn_drop}
    if rope_pct > 0.0:
        # rotary_pct=0.0 is a valid HF config (rotary_ndims=0, rope is a
        # no-op) — omit rope entirely rather than rotating dims the torch
        # original never rotated.
        attn_args.update(rope_theta=rope, rope_pct=rope_pct)
    layers: list[dict] = [
        {"embedding": {"num_embeddings": vocab, "embedding_dim": d},
         "normal": {"mean": 0.0, "std": 0.02}},
    ]
    for _ in range(n):
        attn_branch = {"sequential": [
            {"layernorm": {"normalized_shape": d, "eps": eps}},
            {"linear": {"in_features": d, "out_features": 3 * d,
                        "bias": attn_bias}},
            {"attention": dict(attn_args)},
            {"linear": {"in_features": d, "out_features": d,
                        "bias": attn_bias}}]
            + ([{"dropout": {"p": hidden_drop}}] if hidden_drop else [])}
        mlp_branch = {"sequential": [
            {"layernorm": {"normalized_shape": d, "eps": eps}},
            {"linear": {"in_features": d, "out_features": inter}},
            act_entry,
            {"linear": {"in_features": inter, "out_features": d}}]
            + ([{"dropout": {"p": hidden_drop}}] if hidden_drop else [])}
        container = "parallelresidual" if parallel else "residual"
        layers.append({container: [attn_branch, mlp_branch]})
    layers += [
        {"layernorm": {"normalized_shape": d, "eps": eps}},
        {"linear": {"in_features": d, "out_features": vocab, "bias": False}},
        {"softmaxlast": {"dim": -1}},
    ]
    return layers


def _phi_dsl_from_config(config, n_layer_override=None) -> list[dict]:
    """Phi-1/1.5/2 HF config → layer DSL.

    Phi blocks are parallel-residual with ONE shared input LayerNorm
    feeding both branches (HF ``modeling_phi`` forward: attention and MLP
    both read ``input_layernorm(x)`` and their outputs sum onto the
    residual — cf. NeoX, where each branch carries its own norm), so the
    block nests as ``residual([sequential([ln, summation([attn, mlp])])])``.
    Partial rotary via ``partial_rotary_factor`` (default 0.5), biases on
    every projection, biased final lm_head, LayerNorm (not RMSNorm).
    """
    cfg = _llama_text_config(config)
    if getattr(cfg, "qk_layernorm", False):
        raise ValueError("qk_layernorm Phi checkpoints are not supported")
    if getattr(cfg, "tie_word_embeddings", False):
        # HF drops tied weights on save, and the biased head the phi DSL
        # builds has no tied-bias analogue — reject with a clear message
        # instead of a KeyError mid-import.
        raise ValueError("tie_word_embeddings=True phi checkpoints are "
                         "not supported")
    d = int(cfg.hidden_size)
    n = int(n_layer_override if n_layer_override else cfg.num_hidden_layers)
    heads = int(cfg.num_attention_heads)
    kv = int(getattr(cfg, "num_key_value_heads", None) or heads)
    hd = d // heads
    vocab = int(cfg.vocab_size)
    eps = float(getattr(cfg, "layer_norm_eps", 1e-5))
    rope = float(getattr(cfg, "rope_theta", 10000.0) or 10000.0)
    rope_pct = getattr(cfg, "partial_rotary_factor", None)
    rope_pct = 0.5 if rope_pct is None else float(rope_pct)
    attn_drop = float(getattr(cfg, "attention_dropout", 0.0) or 0.0)
    resid_drop = float(getattr(cfg, "resid_pdrop", 0.0) or 0.0)
    embd_drop = float(getattr(cfg, "embd_pdrop", 0.0) or 0.0)
    inter = int(getattr(cfg, "intermediate_size", None) or 4 * d)
    act_entry = _gelu_entry(getattr(cfg, "hidden_act", "gelu_new"), "phi")

    attn_args = {"num_heads": heads, "num_kv_heads": kv, "dropout": attn_drop}
    if rope_pct > 0.0:
        # partial_rotary_factor=0.0 disables rope entirely (rotary_ndims=0
        # in the torch original) — rotating dims it never rotated would
        # silently diverge the logits.
        attn_args.update(rope_theta=rope, rope_pct=rope_pct)
    tail_drop = [{"dropout": {"p": resid_drop}}] if resid_drop else []
    layers: list[dict] = [
        {"embedding": {"num_embeddings": vocab, "embedding_dim": d},
         "normal": {"mean": 0.0, "std": 0.02}},
    ]
    if embd_drop:
        layers.append({"dropout": {"p": embd_drop}})
    for _ in range(n):
        attn_branch = {"sequential": [
            {"linear": {"in_features": d,
                        "out_features": (heads + 2 * kv) * hd}},
            {"attention": dict(attn_args)},
            {"linear": {"in_features": heads * hd, "out_features": d}}]
            + tail_drop}
        mlp_branch = {"sequential": [
            {"linear": {"in_features": d, "out_features": inter}},
            act_entry,
            {"linear": {"in_features": inter, "out_features": d}}]
            + tail_drop}
        layers.append({"residual": [{"sequential": [
            {"layernorm": {"normalized_shape": d, "eps": eps}},
            {"summation": [attn_branch, mlp_branch]}]}]})
    layers += [
        {"layernorm": {"normalized_shape": d, "eps": eps}},
        {"linear": {"in_features": d, "out_features": vocab, "bias": True}},
        {"softmaxlast": {"dim": -1}},
    ]
    return layers


def _map_phi_state_dict(sd: dict, n_layer: int, config=None) -> dict:
    """Phi HF keys → ours: QKV (+bias) concat like llama, the block's
    single input_layernorm lands inside the residual container
    (``layers.{i}.0.0``), branch projections under the summation
    (``layers.{i}.0.1.{branch}.{item}``), biased final head kept."""
    cfg = _llama_text_config(config)
    base = 1 + (1 if float(getattr(cfg, "embd_pdrop", 0.0) or 0.0) else 0)
    out = {"layers.0.weight": sd["model.embed_tokens.weight"]}
    for i in range(n_layer):
        src = f"model.layers.{i}"
        dst = f"layers.{base + i}.0"
        _concat_qkv(sd, src, out, f"{dst}.1.0.0")
        for name in ("weight", "bias"):
            out[f"{dst}.0.{name}"] = sd[f"{src}.input_layernorm.{name}"]
            out[f"{dst}.1.0.2.{name}"] = sd[f"{src}.self_attn.dense.{name}"]
            out[f"{dst}.1.1.0.{name}"] = sd[f"{src}.mlp.fc1.{name}"]
            out[f"{dst}.1.1.2.{name}"] = sd[f"{src}.mlp.fc2.{name}"]
    for name in ("weight", "bias"):
        out[f"layers.{base + n_layer}.{name}"] = \
            sd[f"model.final_layernorm.{name}"]
        out[f"layers.{base + n_layer + 1}.{name}"] = sd[f"lm_head.{name}"]
    return out


def _olmo2_dsl_from_config(config, n_layer_override=None) -> list[dict]:
    """OLMo-2 HF config → layer DSL.

    OLMo-2 blocks are POST-norm only (HF ``modeling_olmo2``: no input
    norm; ``post_attention_layernorm`` wraps the attention branch output
    and ``post_feedforward_layernorm`` the MLP's, each BEFORE the residual
    add), with flat q/k RMS normalization — ``Olmo2Attention`` normalizes
    the whole (H·hd) projection before the head split (``qk_norm_scope=
    'flat'``, unlike Qwen3's per-head norm).  Expressed with the generic
    residual container: each branch ends in its rmsnorm.
    """
    cfg = _llama_text_config(config)
    scaling = getattr(cfg, "rope_scaling", None) or None
    if scaling and (scaling.get("rope_type") or scaling.get("type")
                    or "default") != "default":
        # Same guard as the llama/neox builders: importing with an active
        # scaling silently ignored would produce wrong logits.
        raise ValueError(
            f"olmo2 rope_scaling {scaling!r} is not supported; importing "
            "would produce wrong logits")
    d = int(cfg.hidden_size)
    n = int(n_layer_override if n_layer_override else cfg.num_hidden_layers)
    heads = int(cfg.num_attention_heads)
    kv = int(getattr(cfg, "num_key_value_heads", None) or heads)
    hd = d // heads
    vocab = int(cfg.vocab_size)
    eps = float(getattr(cfg, "rms_norm_eps", 1e-6))
    rope = float(getattr(cfg, "rope_theta", 10000.0) or 10000.0)
    attn_drop = float(getattr(cfg, "attention_dropout", 0.0) or 0.0)
    bias = bool(getattr(cfg, "attention_bias", False) or False)
    inter = int(cfg.intermediate_size)
    activation = getattr(cfg, "hidden_act", "silu")

    layers: list[dict] = [
        {"embedding": {"num_embeddings": vocab, "embedding_dim": d},
         "normal": {"mean": 0.0, "std": 0.02}},
    ]
    for _ in range(n):
        layers.append({"residual": [
            {"sequential": [
                {"linear": {"in_features": d,
                            "out_features": (heads + 2 * kv) * hd,
                            "bias": bias}},
                {"attention": {"num_heads": heads, "num_kv_heads": kv,
                               "rope_theta": rope, "head_dim": hd,
                               "dropout": attn_drop, "qk_norm": True,
                               "qk_norm_scope": "flat",
                               "qk_norm_fp32_weight": True,
                               "qk_norm_eps": eps}},
                {"linear": {"in_features": heads * hd, "out_features": d,
                            "bias": bias}},
                {"rmsnorm": {"normalized_shape": d, "eps": eps}}]},
            {"sequential": [
                {"gatedmlp": {"in_features": d, "intermediate_size": inter,
                              "activation": activation}},
                {"rmsnorm": {"normalized_shape": d, "eps": eps}}]}]})
    layers += [
        {"rmsnorm": {"normalized_shape": d, "eps": eps}},
        {"linear": {"in_features": d, "out_features": vocab, "bias": False}},
        {"softmaxlast": {"dim": -1}},
    ]
    return layers


def _olmo_dsl_from_config(config, n_layer_override=None) -> list[dict]:
    """OLMo v1 HF config → layer DSL.

    Llama-like pre-norm blocks with two quirks: NON-PARAMETRIC LayerNorm
    (HF ``OlmoLayerNorm``: elementwise_affine=False, no weights at all)
    and optional ``clip_qkv`` — the fused QKV projection output clamps to
    ±clip before attention (a ``clamp`` DSL entry).
    """
    cfg = _llama_text_config(config)
    scaling = getattr(cfg, "rope_scaling", None) or None
    if scaling and (scaling.get("rope_type") or scaling.get("type")
                    or "default") != "default":
        raise ValueError(
            f"olmo rope_scaling {scaling!r} is not supported; importing "
            "would produce wrong logits")
    d = int(cfg.hidden_size)
    n = int(n_layer_override if n_layer_override else cfg.num_hidden_layers)
    heads = int(cfg.num_attention_heads)
    kv = int(getattr(cfg, "num_key_value_heads", None) or heads)
    hd = d // heads
    vocab = int(cfg.vocab_size)
    rope = float(getattr(cfg, "rope_theta", 10000.0) or 10000.0)
    attn_drop = float(getattr(cfg, "attention_dropout", 0.0) or 0.0)
    bias = bool(getattr(cfg, "attention_bias", False) or False)
    clip = getattr(cfg, "clip_qkv", None)
    inter = int(cfg.intermediate_size)
    activation = getattr(cfg, "hidden_act", "silu")
    ln = {"layernorm": {"normalized_shape": d, "eps": 1e-5,
                        "elementwise_affine": False}}

    layers: list[dict] = [
        {"embedding": {"num_embeddings": vocab, "embedding_dim": d},
         "normal": {"mean": 0.0, "std": 0.02}},
    ]
    for _ in range(n):
        attn_seq = [dict(ln),
                    {"linear": {"in_features": d,
                                "out_features": (heads + 2 * kv) * hd,
                                "bias": bias}}]
        if clip is not None:
            attn_seq.append({"clamp": {"min": -float(clip),
                                       "max": float(clip)}})
        attn_seq += [{"attention": {"num_heads": heads, "num_kv_heads": kv,
                                    "rope_theta": rope, "head_dim": hd,
                                    "dropout": attn_drop}},
                     {"linear": {"in_features": heads * hd,
                                 "out_features": d, "bias": bias}}]
        layers.append({"residual": [
            {"sequential": attn_seq},
            {"sequential": [dict(ln),
                            {"gatedmlp": {"in_features": d,
                                          "intermediate_size": inter,
                                          "activation": activation}}]}]})
    layers += [
        dict(ln),
        {"linear": {"in_features": d, "out_features": vocab, "bias": False}},
        {"softmaxlast": {"dim": -1}},
    ]
    return layers


def _stablelm_dsl_from_config(config, n_layer_override=None) -> list[dict]:
    """StableLM HF config → layer DSL: the llama block structure with
    LayerNorm (weight+bias) instead of RMSNorm, partial rotary
    (``partial_rotary_factor``, default 0.25), gated silu MLP, optional
    qkv bias (``use_qkv_bias``), untied-or-tied head.
    ``use_parallel_residual`` / ``qk_layernorm`` variants are refused
    rather than silently mis-structured."""
    cfg = _llama_text_config(config)
    if getattr(cfg, "use_parallel_residual", False):
        raise ValueError("use_parallel_residual StableLM checkpoints are "
                         "not supported")
    if getattr(cfg, "qk_layernorm", False):
        raise ValueError("qk_layernorm StableLM checkpoints are not "
                         "supported")
    scaling = getattr(cfg, "rope_scaling", None) or None
    if scaling and (scaling.get("rope_type") or scaling.get("type")
                    or "default") != "default":
        raise ValueError(
            f"stablelm rope_scaling {scaling!r} is not supported; "
            "importing would produce wrong logits")
    d = int(cfg.hidden_size)
    n = int(n_layer_override if n_layer_override else cfg.num_hidden_layers)
    heads = int(cfg.num_attention_heads)
    kv = int(getattr(cfg, "num_key_value_heads", None) or heads)
    hd = d // heads
    vocab = int(cfg.vocab_size)
    eps = float(getattr(cfg, "layer_norm_eps", 1e-5))
    rope = float(getattr(cfg, "rope_theta", 10000.0) or 10000.0)
    rope_pct = getattr(cfg, "partial_rotary_factor", None)
    rope_pct = 0.25 if rope_pct is None else float(rope_pct)
    attn_drop = float(getattr(cfg, "attention_dropout", 0.0) or 0.0)
    qkv_bias = bool(getattr(cfg, "use_qkv_bias", False))
    inter = int(cfg.intermediate_size)
    activation = getattr(cfg, "hidden_act", "silu")

    attn_args = {"num_heads": heads, "num_kv_heads": kv, "head_dim": hd,
                 "dropout": attn_drop}
    if rope_pct > 0.0:
        attn_args.update(rope_theta=rope, rope_pct=rope_pct)
    layers: list[dict] = [
        {"embedding": {"num_embeddings": vocab, "embedding_dim": d},
         "normal": {"mean": 0.0, "std": 0.02}},
    ]
    for _ in range(n):
        layers.append({"transformerblock": {
            "attn_block": {"sequential": [
                {"layernorm": {"normalized_shape": d, "eps": eps}},
                {"linear": {"in_features": d,
                            "out_features": (heads + 2 * kv) * hd,
                            "bias": qkv_bias}},
                {"attention": dict(attn_args)},
                {"linear": {"in_features": heads * hd, "out_features": d,
                            "bias": False}}]},
            "mlp_block": {"sequential": [
                {"layernorm": {"normalized_shape": d, "eps": eps}},
                {"gatedmlp": {"in_features": d, "intermediate_size": inter,
                              "activation": activation}}]},
            "post_norm_on_residual": False,
        }})
    layers += [
        {"layernorm": {"normalized_shape": d, "eps": eps}},
        {"linear": {"in_features": d, "out_features": vocab, "bias": False}},
        {"softmaxlast": {"dim": -1}},
    ]
    return layers


def _gptj_dsl_from_config(config, n_layer_override=None) -> list[dict]:
    """GPT-J HF config → layer DSL.

    Parallel attention+MLP branches sharing ONE ``ln_1`` per block (the
    Phi nesting: ``residual([sequential([ln, summation([attn, mlp])])])``
    — HF ``modeling_gptj`` forward sums both branch outputs onto the
    residual), bias-free q/k/v/out projections, biased fc_in/fc_out MLP
    with gelu_new, biased lm_head, and partial INTERLEAVED rotary
    (``rotary_dim`` dims, rotate-every-two pairs).  The interleave is
    handled entirely at import: the mapper de-interleaves each head's
    q/k projection rows into the half-split layout our rope uses — q·k
    dot products are invariant to a consistent feature permutation, so
    no runtime rope variant is needed.
    """
    cfg = _llama_text_config(config)
    if getattr(cfg, "tie_word_embeddings", False):
        # HF drops tied weights on save and the biased head the gptj DSL
        # builds has no tied analogue — reject with a clear message.
        raise ValueError("tie_word_embeddings=True gptj checkpoints are "
                         "not supported")
    d = int(cfg.hidden_size if hasattr(cfg, "hidden_size") else cfg.n_embd)
    n = int(n_layer_override if n_layer_override
            else getattr(cfg, "num_hidden_layers", None) or cfg.n_layer)
    heads = int(getattr(cfg, "num_attention_heads", None) or cfg.n_head)
    hd = d // heads
    vocab = int(cfg.vocab_size)
    eps = float(getattr(cfg, "layer_norm_epsilon", 1e-5))
    rotary_dim = int(getattr(cfg, "rotary_dim", None) or hd)
    attn_drop = float(getattr(cfg, "attn_pdrop", 0.0) or 0.0)
    resid_drop = float(getattr(cfg, "resid_pdrop", 0.0) or 0.0)
    embd_drop = float(getattr(cfg, "embd_pdrop", 0.0) or 0.0)
    inter = int(getattr(cfg, "n_inner", None) or 4 * d)
    act_entry = _gelu_entry(
        getattr(cfg, "activation_function", "gelu_new"), "gptj")

    attn_args = {"num_heads": heads, "dropout": attn_drop,
                 "rope_theta": 10000.0, "rope_dim": rotary_dim}
    tail_drop = [{"dropout": {"p": resid_drop}}] if resid_drop else []
    layers: list[dict] = [
        {"embedding": {"num_embeddings": vocab, "embedding_dim": d},
         "normal": {"mean": 0.0, "std": 0.02}},
    ]
    if embd_drop:
        layers.append({"dropout": {"p": embd_drop}})
    for _ in range(n):
        attn_branch = {"sequential": [
            {"linear": {"in_features": d, "out_features": 3 * d,
                        "bias": False}},
            {"attention": dict(attn_args)},
            {"linear": {"in_features": d, "out_features": d,
                        "bias": False}}] + tail_drop}
        mlp_branch = {"sequential": [
            {"linear": {"in_features": d, "out_features": inter}},
            act_entry,
            {"linear": {"in_features": inter, "out_features": d}}]
            + tail_drop}
        layers.append({"residual": [{"sequential": [
            {"layernorm": {"normalized_shape": d, "eps": eps}},
            {"summation": [attn_branch, mlp_branch]}]}]})
    layers += [
        {"layernorm": {"normalized_shape": d, "eps": eps}},
        {"linear": {"in_features": d, "out_features": vocab, "bias": True}},
        {"softmaxlast": {"dim": -1}},
    ]
    return layers


def _gptj_deinterleave(w: np.ndarray, heads: int, rotary_dim: int
                       ) -> np.ndarray:
    """Per head, reorder the first ``rotary_dim`` projection rows from
    GPT-J's interleaved pair layout (x0,x1),(x2,x3)… to the half-split
    layout (x_even… then x_odd…) our rope rotates; pass-through rows stay
    put.  Works for (d, d) weights (row-major per-head blocks)."""
    w = np.asarray(w)
    hd = w.shape[0] // heads
    out = w.copy()
    for h in range(heads):
        base = h * hd
        rot = w[base:base + rotary_dim]
        out[base:base + rotary_dim] = np.concatenate([rot[0::2], rot[1::2]])
    return out


def _falcon_arch(cfg) -> tuple[bool, int]:
    """(new_decoder_architecture, effective num_kv_heads) — HF
    ``FalconAttention``: kv heads are ``num_kv_heads`` under the new
    architecture (or when multi_query is off), else 1 (MQA)."""
    new_arch = bool(getattr(cfg, "new_decoder_architecture", False))
    if new_arch or not getattr(cfg, "multi_query", True):
        kv = int(getattr(cfg, "num_kv_heads", None)
                 or cfg.num_attention_heads)
    else:
        kv = 1
    return new_arch, kv


def _falcon_dsl_from_config(config, n_layer_override=None) -> list[dict]:
    """Falcon HF config → layer DSL, both decoder architectures:

    - 40B-style (``new_decoder_architecture``): two norms feed PARALLEL
      attention/MLP branches (``ln_attn``/``ln_mlp``) — the NeoX
      ``parallelresidual`` container; GQA via ``num_kv_heads``.
    - 7B-style (``multi_query`` + ``parallel_attn``): ONE
      ``input_layernorm`` shared by both parallel branches (the Phi
      nesting) with MQA (1 kv head).

    Full NeoX-style rotary, bias-free projections (``bias``), erf gelu
    MLP, tied head by default.  Alibi, non-rotary, sequential
    (``parallel_attn=False``) and single-ln-new-arch
    (``num_ln_in_parallel_attn=1``) variants are refused loudly.
    """
    cfg = _llama_text_config(config)
    if getattr(cfg, "alibi", False):
        # falcon-rw shape: ALiBi + sequential pre-LN blocks + per-head-
        # interleaved fused QKV (BLOOM's layout).  Other alibi combos
        # (parallel branches, MQA/GQA) have no released checkpoints —
        # refused rather than guessed.
        if (getattr(cfg, "new_decoder_architecture", False)
                or getattr(cfg, "multi_query", True)
                or getattr(cfg, "parallel_attn", True)):
            raise ValueError(
                "alibi Falcon is supported only in the falcon-rw shape "
                "(multi_query=False, parallel_attn=False, classic "
                "decoder architecture)")
        return _falcon_rw_dsl(cfg, n_layer_override)
    scaling = getattr(cfg, "rope_scaling", None) or None
    if scaling and (scaling.get("rope_type") or scaling.get("type")
                    or "default") != "default":
        raise ValueError(
            f"falcon rope_scaling {scaling!r} is not supported; importing "
            "would produce wrong logits")
    if not getattr(cfg, "rotary", True):
        raise ValueError("non-rotary Falcon checkpoints are not supported")
    new_arch, kv = _falcon_arch(cfg)
    if not new_arch and not getattr(cfg, "parallel_attn", True):
        raise ValueError("sequential (parallel_attn=False) Falcon "
                         "checkpoints are not supported")
    if new_arch and getattr(cfg, "num_ln_in_parallel_attn", None) == 1:
        raise ValueError("num_ln_in_parallel_attn=1 Falcon checkpoints "
                         "are not supported")
    d = int(cfg.hidden_size)
    n = int(n_layer_override if n_layer_override else cfg.num_hidden_layers)
    heads = int(cfg.num_attention_heads)
    hd = d // heads
    vocab = int(cfg.vocab_size)
    eps = float(getattr(cfg, "layer_norm_epsilon", 1e-5))
    rope = float(getattr(cfg, "rope_theta", 10000.0) or 10000.0)
    attn_drop = float(getattr(cfg, "attention_dropout", 0.0) or 0.0)
    hidden_drop = float(getattr(cfg, "hidden_dropout", 0.0) or 0.0)
    bias = bool(getattr(cfg, "bias", False))
    ffn = int(getattr(cfg, "ffn_hidden_size", None) or 4 * d)
    act_entry = _gelu_entry(getattr(cfg, "activation", "gelu"), "falcon")

    attn_args = {"num_heads": heads, "num_kv_heads": kv, "head_dim": hd,
                 "dropout": attn_drop, "rope_theta": rope}
    tail_drop = [{"dropout": {"p": hidden_drop}}] if hidden_drop else []
    qkv = {"linear": {"in_features": d,
                      "out_features": (heads + 2 * kv) * hd, "bias": bias}}
    dense = {"linear": {"in_features": heads * hd, "out_features": d,
                        "bias": bias}}
    h4h = {"linear": {"in_features": d, "out_features": ffn, "bias": bias}}
    fh = {"linear": {"in_features": ffn, "out_features": d, "bias": bias}}
    ln = {"layernorm": {"normalized_shape": d, "eps": eps}}

    layers: list[dict] = [
        {"embedding": {"num_embeddings": vocab, "embedding_dim": d},
         "normal": {"mean": 0.0, "std": 0.02}},
    ]
    for _ in range(n):
        if new_arch:
            layers.append({"parallelresidual": [
                {"sequential": [dict(ln), qkv, {"attention": dict(attn_args)},
                                dense] + tail_drop},
                {"sequential": [dict(ln), h4h, dict(act_entry), fh]
                 + tail_drop}]})
        else:
            layers.append({"residual": [{"sequential": [
                dict(ln),
                {"summation": [
                    {"sequential": [qkv, {"attention": dict(attn_args)},
                                    dense] + tail_drop},
                    {"sequential": [h4h, dict(act_entry), fh]
                     + tail_drop}]},
            ]}]})
    layers += [
        dict(ln),
        {"linear": {"in_features": d, "out_features": vocab, "bias": False}},
        {"softmaxlast": {"dim": -1}},
    ]
    return layers


def _falcon_defuse_qkv(w: np.ndarray, heads: int, kv: int, new_arch: bool,
                       multi_query: bool) -> np.ndarray:
    """Falcon fused query_key_value → our [all q; all k; all v] layout.

    - new architecture: per-kv-group blocks [q_0..q_{g-1}, k, v];
    - old MQA: already [all q, k, v] (kv=1) — identity;
    - old non-MQA (falcon-rw): per-head [q, k, v] — NeoX interleave.
    Works for weights (rows, d) and biases (rows,)."""
    w = np.asarray(w)
    if new_arch:
        group = heads // kv
        hd = w.shape[0] // (kv * (group + 2))
        blk = w.reshape((kv, group + 2, hd) + w.shape[1:])
        q = blk[:, :group].reshape((heads * hd,) + w.shape[1:])
        k = blk[:, group].reshape((kv * hd,) + w.shape[1:])
        v = blk[:, group + 1].reshape((kv * hd,) + w.shape[1:])
        return np.concatenate([q, k, v])
    if multi_query:
        return w
    return _neox_deinterleave_qkv(w, heads)


def _falcon_rw_dsl(cfg, n_layer_override=None) -> list[dict]:
    """falcon-rw (RefinedWeb) config → layer DSL: ALiBi attention, the
    standard sequential pre-LN block, biased projections, exact-GELU
    MLPs — structurally BLOOM minus the embedding LayerNorm."""
    d = int(cfg.hidden_size)
    n = int(n_layer_override if n_layer_override else cfg.num_hidden_layers)
    heads = int(cfg.num_attention_heads)
    vocab = int(cfg.vocab_size)
    eps = float(getattr(cfg, "layer_norm_epsilon", 1e-5))
    attn_drop = float(getattr(cfg, "attention_dropout", 0.0) or 0.0)
    hidden_drop = float(getattr(cfg, "hidden_dropout", 0.0) or 0.0)
    bias = bool(getattr(cfg, "bias", False))
    ffn = int(getattr(cfg, "ffn_hidden_size", None) or 4 * d)
    act_entry = _gelu_entry(getattr(cfg, "activation", "gelu"), "falcon")

    layers: list[dict] = [
        {"embedding": {"num_embeddings": vocab, "embedding_dim": d},
         "normal": {"mean": 0.0, "std": 0.02}},
    ]
    for _ in range(n):
        layers.append({"residual": [
            {"sequential": [
                {"layernorm": {"normalized_shape": d, "eps": eps}},
                {"linear": {"in_features": d, "out_features": 3 * d,
                            "bias": bias},
                 "normal": {"mean": 0.0, "std": 0.02}, "zeros": {}},
                {"attention": {"num_heads": heads, "dropout": attn_drop,
                               "alibi": True}},
                {"linear": {"in_features": d, "out_features": d,
                            "bias": bias},
                 "normal": {"mean": 0.0, "std": 0.02}, "zeros": {}},
                {"dropout": {"p": hidden_drop}}]},
            {"sequential": [
                {"layernorm": {"normalized_shape": d, "eps": eps}},
                {"linear": {"in_features": d, "out_features": ffn,
                            "bias": bias},
                 "normal": {"mean": 0.0, "std": 0.02}, "zeros": {}},
                act_entry,
                {"linear": {"in_features": ffn, "out_features": d,
                            "bias": bias},
                 "normal": {"mean": 0.0, "std": 0.02}, "zeros": {}},
                {"dropout": {"p": hidden_drop}}]}]})
    layers += [
        {"layernorm": {"normalized_shape": d, "eps": eps}},
        {"linear": {"in_features": d, "out_features": vocab, "bias": False},
         "normal": {"mean": 0.0, "std": 0.02}},
        {"softmaxlast": {"dim": -1}},
    ]
    return layers


def _deinterleave_per_head(arr, heads: int):
    """BLOOM/falcon fused-QKV de-interleave: rows grouped per head as
    ``[h0: q,k,v | h1: q,k,v | …]`` → our ``[all q | all k | all v]``."""
    a = np.asarray(arr)
    if a.ndim == 2:
        h3d, d_in = a.shape
        hd = h3d // 3 // heads
        return a.reshape(heads, 3, hd, d_in).transpose(1, 0, 2, 3) \
                .reshape(h3d, d_in)
    hd = a.shape[0] // 3 // heads
    return a.reshape(heads, 3, hd).transpose(1, 0, 2).reshape(-1)


def _map_falcon_rw_state_dict(sd: dict, n_layer: int, heads: int) -> dict:
    """falcon-rw HF keys → ours (sequential blocks, interleaved QKV)."""
    pfx = "transformer"
    out = {"layers.0.weight": sd[f"{pfx}.word_embeddings.weight"]}
    for i in range(n_layer):
        src = f"{pfx}.h.{i}"
        dst = f"layers.{1 + i}"
        out[f"{dst}.0.0.weight"] = sd[f"{src}.input_layernorm.weight"]
        out[f"{dst}.0.0.bias"] = sd[f"{src}.input_layernorm.bias"]
        qkv = f"{src}.self_attention.query_key_value"
        out[f"{dst}.0.1.weight"] = _deinterleave_per_head(
            sd[f"{qkv}.weight"], heads)
        if f"{qkv}.bias" in sd:
            out[f"{dst}.0.1.bias"] = _deinterleave_per_head(
                sd[f"{qkv}.bias"], heads)
        out[f"{dst}.0.3.weight"] = sd[f"{src}.self_attention.dense.weight"]
        if f"{src}.self_attention.dense.bias" in sd:
            out[f"{dst}.0.3.bias"] = sd[f"{src}.self_attention.dense.bias"]
        out[f"{dst}.1.0.weight"] = \
            sd[f"{src}.post_attention_layernorm.weight"]
        out[f"{dst}.1.0.bias"] = sd[f"{src}.post_attention_layernorm.bias"]
        out[f"{dst}.1.1.weight"] = sd[f"{src}.mlp.dense_h_to_4h.weight"]
        out[f"{dst}.1.3.weight"] = sd[f"{src}.mlp.dense_4h_to_h.weight"]
        if f"{src}.mlp.dense_h_to_4h.bias" in sd:
            out[f"{dst}.1.1.bias"] = sd[f"{src}.mlp.dense_h_to_4h.bias"]
            out[f"{dst}.1.3.bias"] = sd[f"{src}.mlp.dense_4h_to_h.bias"]
    out[f"layers.{1 + n_layer}.weight"] = sd[f"{pfx}.ln_f.weight"]
    out[f"layers.{1 + n_layer}.bias"] = sd[f"{pfx}.ln_f.bias"]
    out[f"layers.{2 + n_layer}.weight"] = sd.get(
        "lm_head.weight", sd[f"{pfx}.word_embeddings.weight"])
    return out


def _map_falcon_state_dict(sd: dict, n_layer: int, config=None) -> dict:
    """Falcon HF keys → ours: fused QKV de-fused per architecture, the
    norm layout following the block nesting (parallelresidual for the new
    architecture, the shared-norm Phi nesting for 7B-style), tied head."""
    cfg = _llama_text_config(config) if config is not None else None
    if cfg is not None and getattr(cfg, "alibi", False):
        return _map_falcon_rw_state_dict(
            sd, n_layer, int(cfg.num_attention_heads))
    cfg = _llama_text_config(config)
    new_arch, kv = _falcon_arch(cfg)
    heads = int(cfg.num_attention_heads)
    multi_query = bool(getattr(cfg, "multi_query", True))
    out = {"layers.0.weight": sd["transformer.word_embeddings.weight"]}
    for i in range(n_layer):
        src = f"transformer.h.{i}"
        dst = f"layers.{1 + i}"
        qkv_w = _falcon_defuse_qkv(
            sd[f"{src}.self_attention.query_key_value.weight"], heads, kv,
            new_arch, multi_query)
        qkv_b = None
        if f"{src}.self_attention.query_key_value.bias" in sd:
            qkv_b = _falcon_defuse_qkv(
                sd[f"{src}.self_attention.query_key_value.bias"], heads, kv,
                new_arch, multi_query)
        if new_arch:
            attn, mlp = f"{dst}.0", f"{dst}.1"
            for name in ("weight", "bias"):
                out[f"{attn}.0.{name}"] = sd[f"{src}.ln_attn.{name}"]
                out[f"{mlp}.0.{name}"] = sd[f"{src}.ln_mlp.{name}"]
            qkv_at, dense_at, h4h_at, fh_at = (f"{attn}.1", f"{attn}.3",
                                               f"{mlp}.1", f"{mlp}.3")
        else:
            for name in ("weight", "bias"):
                out[f"{dst}.0.0.{name}"] = \
                    sd[f"{src}.input_layernorm.{name}"]
            qkv_at, dense_at, h4h_at, fh_at = (f"{dst}.0.1.0.0",
                                               f"{dst}.0.1.0.2",
                                               f"{dst}.0.1.1.0",
                                               f"{dst}.0.1.1.2")
        out[f"{qkv_at}.weight"] = qkv_w
        if qkv_b is not None:
            out[f"{qkv_at}.bias"] = qkv_b
        for at, hf in ((dense_at, "self_attention.dense"),
                       (h4h_at, "mlp.dense_h_to_4h"),
                       (fh_at, "mlp.dense_4h_to_h")):
            out[f"{at}.weight"] = sd[f"{src}.{hf}.weight"]
            if f"{src}.{hf}.bias" in sd:
                out[f"{at}.bias"] = sd[f"{src}.{hf}.bias"]
    for name in ("weight", "bias"):
        out[f"layers.{1 + n_layer}.{name}"] = sd[f"transformer.ln_f.{name}"]
    out[f"layers.{2 + n_layer}.weight"] = sd.get(
        "lm_head.weight", sd["transformer.word_embeddings.weight"])
    return out


def _map_gptj_state_dict(sd: dict, n_layer: int, config=None) -> dict:
    """GPT-J HF keys → ours: q/k rows de-interleaved into half-split
    rotary layout (see ``_gptj_dsl_from_config``), v untouched, shared
    ``ln_1`` re-keyed under the residual/summation nesting, biased head
    kept."""
    cfg = _llama_text_config(config)
    d = int(cfg.hidden_size if hasattr(cfg, "hidden_size") else cfg.n_embd)
    heads = int(getattr(cfg, "num_attention_heads", None) or cfg.n_head)
    rotary_dim = int(getattr(cfg, "rotary_dim", None) or d // heads)
    base = 1 + (1 if float(getattr(cfg, "embd_pdrop", 0.0) or 0.0) else 0)
    out = {"layers.0.weight": sd["transformer.wte.weight"]}
    for i in range(n_layer):
        src = f"transformer.h.{i}"
        dst = f"layers.{base + i}.0"
        for name in ("weight", "bias"):
            out[f"{dst}.0.{name}"] = sd[f"{src}.ln_1.{name}"]
            out[f"{dst}.1.1.0.{name}"] = sd[f"{src}.mlp.fc_in.{name}"]
            out[f"{dst}.1.1.2.{name}"] = sd[f"{src}.mlp.fc_out.{name}"]
        out[f"{dst}.1.0.0.weight"] = np.concatenate(
            [_gptj_deinterleave(sd[f"{src}.attn.q_proj.weight"], heads,
                                rotary_dim),
             _gptj_deinterleave(sd[f"{src}.attn.k_proj.weight"], heads,
                                rotary_dim),
             np.asarray(sd[f"{src}.attn.v_proj.weight"])], axis=0)
        out[f"{dst}.1.0.2.weight"] = sd[f"{src}.attn.out_proj.weight"]
    for name in ("weight", "bias"):
        out[f"layers.{base + n_layer}.{name}"] = \
            sd[f"transformer.ln_f.{name}"]
        out[f"layers.{base + n_layer + 1}.{name}"] = sd[f"lm_head.{name}"]
    return out


def _map_stablelm_state_dict(sd: dict, n_layer: int, config=None) -> dict:
    """StableLM HF keys → ours: the llama mapping verbatim (same block
    key layout) plus the LayerNorm biases llama's RMSNorms don't have."""
    out = _map_llama_state_dict(sd, n_layer, config)
    for i in range(n_layer):
        src = f"model.layers.{i}"
        dst = f"layers.{1 + i}"
        out[f"{dst}.attn_block.0.bias"] = sd[f"{src}.input_layernorm.bias"]
        out[f"{dst}.mlp_block.0.bias"] = \
            sd[f"{src}.post_attention_layernorm.bias"]
    out[f"layers.{1 + n_layer}.bias"] = sd["model.norm.bias"]
    return out


def _map_olmo_state_dict(sd: dict, n_layer: int, config=None) -> dict:
    """OLMo v1 HF keys → ours.  The non-parametric LayerNorms carry no
    weights, so only projections and embeddings map; the clamp entry
    shifts the attention branch's item indices when clip_qkv is set."""
    cfg = _llama_text_config(config)
    has_clip = getattr(cfg, "clip_qkv", None) is not None
    # attn branch items: [ln, qkv, (clamp,) attention, o_proj]
    i_attn_out = 4 if has_clip else 3
    out = {"layers.0.weight": sd["model.embed_tokens.weight"]}
    for i in range(n_layer):
        src = f"model.layers.{i}"
        dst = f"layers.{1 + i}"
        _concat_qkv(sd, src, out, f"{dst}.0.1")
        out[f"{dst}.0.{i_attn_out}.weight"] = \
            sd[f"{src}.self_attn.o_proj.weight"]
        if f"{src}.self_attn.o_proj.bias" in sd:
            out[f"{dst}.0.{i_attn_out}.bias"] = \
                sd[f"{src}.self_attn.o_proj.bias"]
        for proj in ("gate_proj", "up_proj", "down_proj"):
            out[f"{dst}.1.1.{proj}.weight"] = sd[f"{src}.mlp.{proj}.weight"]
    out[f"layers.{2 + n_layer}.weight"] = sd.get(
        "lm_head.weight", sd["model.embed_tokens.weight"])
    return out


def _map_olmo2_state_dict(sd: dict, n_layer: int, config=None) -> dict:
    """OLMo-2 HF keys → ours: QKV concat, flat q/k-norm weights onto the
    attention module, branch-tail norms from post_attention/
    post_feedforward_layernorm, tied-or-untied lm_head."""
    out = {"layers.0.weight": sd["model.embed_tokens.weight"]}
    for i in range(n_layer):
        src = f"model.layers.{i}"
        dst = f"layers.{1 + i}"
        _concat_qkv(sd, src, out, f"{dst}.0.0")
        out[f"{dst}.0.1.q_norm.weight"] = sd[f"{src}.self_attn.q_norm.weight"]
        out[f"{dst}.0.1.k_norm.weight"] = sd[f"{src}.self_attn.k_norm.weight"]
        out[f"{dst}.0.2.weight"] = sd[f"{src}.self_attn.o_proj.weight"]
        if f"{src}.self_attn.o_proj.bias" in sd:
            out[f"{dst}.0.2.bias"] = sd[f"{src}.self_attn.o_proj.bias"]
        out[f"{dst}.0.3.weight"] = sd[f"{src}.post_attention_layernorm.weight"]
        for proj in ("gate_proj", "up_proj", "down_proj"):
            out[f"{dst}.1.0.{proj}.weight"] = sd[f"{src}.mlp.{proj}.weight"]
        out[f"{dst}.1.1.weight"] = \
            sd[f"{src}.post_feedforward_layernorm.weight"]
    out[f"layers.{1 + n_layer}.weight"] = sd["model.norm.weight"]
    out[f"layers.{2 + n_layer}.weight"] = sd.get(
        "lm_head.weight", sd["model.embed_tokens.weight"])
    return out


def _neox_deinterleave_qkv(w: np.ndarray, heads: int) -> np.ndarray:
    """GPT-NeoX fuses QKV per head ([q_h; k_h; v_h] stacked head-major,
    HF ``modeling_gpt_neox`` view (H, 3, hd, ...)); our attention expects
    [all q; all k; all v].  Works for (3d, d) weights and (3d,) biases."""
    w = np.asarray(w)
    hd3 = w.shape[0] // heads
    return (w.reshape((heads, 3, hd3 // 3) + w.shape[1:])
            .swapaxes(0, 1)
            .reshape((w.shape[0],) + w.shape[1:]))


def _map_neox_state_dict(sd: dict, n_layer: int, config=None) -> dict:
    """GPT-NeoX HF keys → ours: per-head-interleaved QKV de-interleaved,
    LayerNorms with biases copied straight, untied ``embed_out`` head."""
    heads = int(getattr(_llama_text_config(config), "num_attention_heads"))
    out = {"layers.0.weight": sd["gpt_neox.embed_in.weight"]}
    for i in range(n_layer):
        src = f"gpt_neox.layers.{i}"
        dst = f"layers.{1 + i}"
        for name in ("weight", "bias"):
            out[f"{dst}.0.0.{name}"] = sd[f"{src}.input_layernorm.{name}"]
            # attention_bias=False checkpoints carry no qkv/dense biases —
            # the DSL builds bias-free linears for them (attn_bias above).
            if f"{src}.attention.query_key_value.{name}" in sd:
                out[f"{dst}.0.1.{name}"] = _neox_deinterleave_qkv(
                    sd[f"{src}.attention.query_key_value.{name}"], heads)
            if f"{src}.attention.dense.{name}" in sd:
                out[f"{dst}.0.3.{name}"] = sd[f"{src}.attention.dense.{name}"]
            out[f"{dst}.1.0.{name}"] = \
                sd[f"{src}.post_attention_layernorm.{name}"]
            out[f"{dst}.1.1.{name}"] = sd[f"{src}.mlp.dense_h_to_4h.{name}"]
            out[f"{dst}.1.3.{name}"] = sd[f"{src}.mlp.dense_4h_to_h.{name}"]
    out[f"layers.{1 + n_layer}.weight"] = sd["gpt_neox.final_layer_norm.weight"]
    out[f"layers.{1 + n_layer}.bias"] = sd["gpt_neox.final_layer_norm.bias"]
    out[f"layers.{2 + n_layer}.weight"] = sd.get(
        "embed_out.weight", sd["gpt_neox.embed_in.weight"])
    return out


def _concat_qkv(sd: dict, src: str, out: dict, dst_key: str,
                q="q_proj", k="k_proj", v="v_proj") -> None:
    """Fuse separate q/k/v projections onto our single QKV linear:
    weights (and biases when present) concatenate on the output dim."""
    attn = f"{src}.self_attn"
    out[f"{dst_key}.weight"] = np.concatenate(
        [np.asarray(sd[f"{attn}.{q}.weight"]),
         np.asarray(sd[f"{attn}.{k}.weight"]),
         np.asarray(sd[f"{attn}.{v}.weight"])], axis=0)
    if f"{attn}.{q}.bias" in sd:
        out[f"{dst_key}.bias"] = np.concatenate(
            [np.asarray(sd[f"{attn}.{q}.bias"]),
             np.asarray(sd[f"{attn}.{k}.bias"]),
             np.asarray(sd[f"{attn}.{v}.bias"])], axis=0)


def _map_llama_state_dict(sd: dict, n_layer: int, config=None) -> dict:
    """Llama/Mistral/Qwen2 HF keys → ours: QKV (+bias) concat, straight
    RMSNorm copy (no Gemma +1 offset), tied-or-untied lm_head."""
    prefix = "model"
    if any(k.startswith("model.language_model.") for k in sd):
        prefix = "model.language_model"
    out = {"layers.0.weight": sd[f"{prefix}.embed_tokens.weight"]}
    for i in range(n_layer):
        src = f"{prefix}.layers.{i}"
        dst = f"layers.{1 + i}"
        out[f"{dst}.attn_block.0.weight"] = sd[f"{src}.input_layernorm.weight"]
        if f"{src}.self_attn.qkv_proj.weight" in sd:
            # Phi-3 stores qkv pre-fused in [q; k; v] order — our layout.
            out[f"{dst}.attn_block.1.weight"] = \
                sd[f"{src}.self_attn.qkv_proj.weight"]
            if f"{src}.self_attn.qkv_proj.bias" in sd:
                out[f"{dst}.attn_block.1.bias"] = \
                    sd[f"{src}.self_attn.qkv_proj.bias"]
        else:
            _concat_qkv(sd, src, out, f"{dst}.attn_block.1")
        out[f"{dst}.attn_block.3.weight"] = sd[f"{src}.self_attn.o_proj.weight"]
        if f"{src}.self_attn.o_proj.bias" in sd:
            out[f"{dst}.attn_block.3.bias"] = sd[f"{src}.self_attn.o_proj.bias"]
        if f"{src}.self_attn.q_norm.weight" in sd:  # qwen3 per-head qk-norm
            out[f"{dst}.attn_block.2.q_norm.weight"] = \
                sd[f"{src}.self_attn.q_norm.weight"]
            out[f"{dst}.attn_block.2.k_norm.weight"] = \
                sd[f"{src}.self_attn.k_norm.weight"]
        out[f"{dst}.mlp_block.0.weight"] = \
            sd[f"{src}.post_attention_layernorm.weight"]
        if f"{src}.mlp.gate_up_proj.weight" in sd:
            # Phi-3 fuses [gate; up] on the output dim; split in half.
            gu = np.asarray(sd[f"{src}.mlp.gate_up_proj.weight"])
            half = gu.shape[0] // 2
            out[f"{dst}.mlp_block.1.gate_proj.weight"] = gu[:half]
            out[f"{dst}.mlp_block.1.up_proj.weight"] = gu[half:]
            out[f"{dst}.mlp_block.1.down_proj.weight"] = \
                sd[f"{src}.mlp.down_proj.weight"]
        elif f"{src}.block_sparse_moe.gate.weight" in sd:
            # Mixtral sparse MoE: per-expert w1/w3/w2 stack onto our
            # leading-E gate/up/down layout; router gate copies straight.
            out[f"{dst}.mlp_block.1.router.weight"] = \
                sd[f"{src}.block_sparse_moe.gate.weight"]
            # Sized from config, not key-probing: a truncated checkpoint
            # missing expert e then fails on its precise absent key
            # instead of a downstream shape mismatch.
            n_exp = int(getattr(_llama_text_config(config),
                                "num_local_experts"))
            for ours, theirs in (("gate_proj", "w1"), ("up_proj", "w3"),
                                 ("down_proj", "w2")):
                out[f"{dst}.mlp_block.1.experts.{ours}.weight"] = np.stack(
                    [np.asarray(sd[f"{src}.block_sparse_moe.experts."
                                   f"{e}.{theirs}.weight"])
                     for e in range(n_exp)])
        elif f"{src}.mlp.gate.weight" in sd:
            # Qwen2-MoE: fine experts + always-on shared expert.
            out[f"{dst}.mlp_block.1.router.weight"] = \
                sd[f"{src}.mlp.gate.weight"]
            n_exp = int(getattr(_llama_text_config(config), "num_experts"))
            for proj in ("gate_proj", "up_proj", "down_proj"):
                out[f"{dst}.mlp_block.1.experts.{proj}.weight"] = np.stack(
                    [np.asarray(sd[f"{src}.mlp.experts.{e}.{proj}.weight"])
                     for e in range(n_exp)])
                out[f"{dst}.mlp_block.1.shared_expert.{proj}.weight"] = \
                    sd[f"{src}.mlp.shared_expert.{proj}.weight"]
            out[f"{dst}.mlp_block.1.shared_expert_gate.weight"] = \
                sd[f"{src}.mlp.shared_expert_gate.weight"]
        else:
            for proj in ("gate_proj", "up_proj", "down_proj"):
                out[f"{dst}.mlp_block.1.{proj}.weight"] = \
                    sd[f"{src}.mlp.{proj}.weight"]
    out[f"layers.{1 + n_layer}.weight"] = sd[f"{prefix}.norm.weight"]
    out[f"layers.{2 + n_layer}.weight"] = sd.get(
        "lm_head.weight", sd[f"{prefix}.embed_tokens.weight"])
    return out
