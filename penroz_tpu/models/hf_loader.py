"""Torch-free HuggingFace weight loading.

Loads model weights straight from safetensors files — single-file
``model.safetensors`` or sharded via ``model.safetensors.index.json`` —
into float32 numpy arrays.  No torch anywhere in this path: the
reference loads through torch because it *is* torch
(reference: neural_net_model.py:200-206); on TPU the natural load is
safetensors → numpy → jnp pytree (SURVEY §2.3).  bf16 tensors come out
as ml_dtypes.bfloat16 numpy arrays and are upcast to float32 for the
mapper's transpose/concat work (the model casts back to bf16 on load).

Torch ``pytorch_model.bin`` checkpoints are handled only as a fallback
when the repo ships no safetensors AND torch happens to be importable.
"""
from __future__ import annotations

import json
import logging
import os
from typing import Optional

import numpy as np

log = logging.getLogger(__name__)

_SAFETENSORS_PATTERNS = ["*.safetensors", "*.safetensors.index.json",
                         "config.json", "generation_config.json"]
_BIN_PATTERNS = ["pytorch_model*.bin", "pytorch_model.bin.index.json"]


def resolve_checkpoint_dir(repo_or_path: str,
                           revision: Optional[str] = None) -> str:
    """Local directory containing the checkpoint: a path is used as-is,
    anything else is fetched from the HF hub (config + weights only).
    Safetensors are fetched first; torch ``pytorch_model*.bin`` only when
    the repo ships no safetensors (avoids doubling the transfer for repos
    carrying both formats)."""
    if os.path.isdir(repo_or_path):
        return repo_or_path
    from huggingface_hub import snapshot_download
    local = snapshot_download(repo_or_path, revision=revision,
                              allow_patterns=_SAFETENSORS_PATTERNS)
    # Walk the whole snapshot: repos storing weights under a subfolder
    # would otherwise trigger the redundant second download that also
    # pulls pytorch_model*.bin — the exact double transfer avoided here.
    has_safetensors = any(
        f.endswith(".safetensors")
        for _, _, files in os.walk(local) for f in files)
    if not has_safetensors:
        local = snapshot_download(repo_or_path, revision=revision,
                                  allow_patterns=_SAFETENSORS_PATTERNS
                                  + _BIN_PATTERNS)
    return local


def _load_safetensors_file(path: str) -> dict:
    from safetensors.numpy import load_file
    return load_file(path)


def _to_f32(sd: dict) -> dict:
    out = {}
    for key, value in sd.items():
        arr = np.asarray(value)
        if arr.dtype != np.float32 and arr.dtype.kind in ("f", "V"):
            # 'V' covers ml_dtypes custom dtypes (bfloat16, fp8) seen as
            # void by older numpy introspection; astype handles both.
            arr = arr.astype(np.float32)
        out[key] = arr
    return out


def load_state_dict(local_dir: str) -> dict:
    """Checkpoint dir → {name: float32 numpy array}.

    Preference order: sharded safetensors index, single
    ``model.safetensors``, any ``*.safetensors`` files, then the torch
    fallback (requires torch; loads ``*.bin``)."""
    index = os.path.join(local_dir, "model.safetensors.index.json")
    if os.path.exists(index):
        with open(index) as f:
            weight_map = json.load(f)["weight_map"]
        shards = sorted(set(weight_map.values()))
    elif os.path.exists(os.path.join(local_dir, "model.safetensors")):
        shards = ["model.safetensors"]
    else:
        shards = sorted(f for f in os.listdir(local_dir)
                        if f.endswith(".safetensors"))
        if not shards:
            # Weights may live in a subfolder (resolve_checkpoint_dir's
            # safetensors detection walks recursively, so loading must
            # too, or detection outpaces what this function can consume).
            # A subfolder index wins over loose nested files.
            for root, _, files in os.walk(local_dir):
                if root != local_dir and "model.safetensors.index.json" \
                        in files:
                    with open(os.path.join(
                            root, "model.safetensors.index.json")) as f:
                        weight_map = json.load(f)["weight_map"]
                    rel = os.path.relpath(root, local_dir)
                    shards = sorted({os.path.join(rel, v)
                                     for v in weight_map.values()})
                    break
            else:
                shards = sorted(
                    os.path.relpath(os.path.join(root, f), local_dir)
                    for root, _, files in os.walk(local_dir)
                    for f in files if f.endswith(".safetensors"))
    if not shards:
        return _normalize(_load_torch_fallback(local_dir))
    sd = {}
    for shard in shards:
        # convert per shard so the bf16 copy is freed before the next load
        sd.update(_to_f32(_load_safetensors_file(
            os.path.join(local_dir, shard))))
    return _normalize(sd)


def _normalize(sd: dict) -> dict:
    """Canonicalize raw-checkpoint key layouts to the ForCausalLM naming
    the mapper dispatches on.  The original ``gpt2`` hub checkpoints were
    saved from the bare base model, so their keys lack the
    ``transformer.`` prefix (``wte.weight``, ``h.0.ln_1.weight``, no
    ``lm_head``); transformers' from_pretrained papers over that with
    base_model_prefix retrying — we do the same normalization here."""
    if "wte.weight" in sd and "transformer.wte.weight" not in sd:
        sd = {(k if k.startswith("lm_head.") else f"transformer.{k}"): v
              for k, v in sd.items()}
    return sd


def _load_torch_fallback(local_dir: str) -> dict:
    # Only weight files — a bare *.bin glob would also pick up non-weight
    # pickles like training_args.bin and fail under weights_only=True.
    bin_index = os.path.join(local_dir, "pytorch_model.bin.index.json")
    if os.path.exists(bin_index):
        with open(bin_index) as f:
            bins = sorted(set(json.load(f)["weight_map"].values()))
    else:
        bins = sorted(f for f in os.listdir(local_dir)
                      if f.startswith("pytorch_model") and
                      f.endswith(".bin"))
    if not bins:
        raise FileNotFoundError(
            f"no safetensors or pytorch_model*.bin weight files in "
            f"{local_dir}")
    try:
        import torch
    except ImportError as e:
        raise RuntimeError(
            f"{local_dir} has only torch .bin weights and torch is not "
            f"installed; re-export the checkpoint as safetensors") from e
    log.warning("No safetensors in %s — falling back to torch .bin load",
                local_dir)
    sd = {}
    for name in bins:
        blob = torch.load(os.path.join(local_dir, name), map_location="cpu",
                          weights_only=True)
        for key, value in blob.items():
            sd[key] = value.detach().cpu().float().numpy()
    return _to_f32(sd)
