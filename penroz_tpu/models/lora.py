"""Multi-tenant LoRA adapters: low-rank per-tenant fine-tunes of a shared
base model (arXiv:2106.09685 applied to this serving stack).

The north-star workload — millions of users — implies many tenants wanting
per-tenant behavior without N× copies of the base weights.  A LoRA adapter
is a pair of low-rank factors per targeted Linear projection
(``ΔW = (alpha/r)·B·A``, A: (r, in) and B: (out, r), B zero-initialized so
a fresh adapter is exactly the base model), a few-hundred-KB artifact per
tenant against a multi-GB base.

Two application modes, both implemented in ``ops/modules.Linear``:

- **Bound** (one adapter, whole batch): :func:`bind_model` merges
  ``<prefix>.lora_A/B/scale`` keys into the flat param dict, and every
  existing compiled program (legacy generate, one-shot prefill, the
  training forward) picks the delta up through the ordinary
  ``Ctx.params`` path — no new program families.
- **Stacked** (mixed adapters, one shared decode batch): :func:`build_pack`
  stacks up to ``PENROZ_LORA_MAX_LIVE`` live adapters into static
  ``[L+1, R, ·]`` tensors (rank-padded to ``PENROZ_LORA_MAX_RANK``, the
  trailing slot all-zero for base rows) and a per-row slot-index vector
  gathers each row's factors inside the forward (BGMV-style einsum) — rows
  with different adapters (or none) share ONE decode step.  Static shapes
  keep the compiled-program set bounded: the program retraces only when
  the set of targeted projections changes, never per adapter.

Training (:func:`train_adapter`) freezes the base params — gradients flow
only into the adapter tree (``jax.value_and_grad`` over argument 0; the
parameter-subset analog of the pjit training recipe in PAPERS.md) — and
writes an adapter-only checkpoint (utils/checkpoint.py container, CRC32
streams) loadable straight into the serving registry
(serve/adapters.py).

Knobs::

    PENROZ_LORA_MAX_LIVE   adapters stacked per engine batch (default 4)
    PENROZ_LORA_MAX_RANK   rank ceiling / stack padding (default 16)
"""

from __future__ import annotations

import copy
import logging
import os
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from penroz_tpu.ops import modules as M
from penroz_tpu.utils import checkpoint

log = logging.getLogger(__name__)

MAX_LIVE_ENV = "PENROZ_LORA_MAX_LIVE"
MAX_RANK_ENV = "PENROZ_LORA_MAX_RANK"


def _env_int(name: str, default: int) -> int:
    try:
        return max(1, int(os.environ.get(name, str(default))))
    except ValueError:
        log.warning("Unparseable %s=%r; using default %d", name,
                    os.environ.get(name), default)
        return default


def max_live() -> int:
    """Adapters stackable into one engine batch (``PENROZ_LORA_MAX_LIVE``)."""
    return _env_int(MAX_LIVE_ENV, 4)


def max_rank() -> int:
    """Rank ceiling and stack padding width (``PENROZ_LORA_MAX_RANK``)."""
    return _env_int(MAX_RANK_ENV, 16)


def validate_config(config: dict) -> dict:
    """Normalize an adapter config dict ``{rank, alpha, targets}``;
    ValueError (→ HTTP 400) on a rank outside [1, PENROZ_LORA_MAX_RANK]."""
    rank = int(config.get("rank", 8))
    if rank < 1 or rank > max_rank():
        raise ValueError(
            f"adapter rank {rank} outside [1, {max_rank()}] "
            f"(raise {MAX_RANK_ENV} to allow larger ranks)")
    alpha = config.get("alpha")
    alpha = float(alpha) if alpha is not None else 2.0 * rank
    targets = config.get("targets") or None
    if targets is not None:
        targets = [str(t) for t in targets]
    return {"rank": rank, "alpha": alpha, "targets": targets}


def scale(config: dict) -> float:
    return float(config["alpha"]) / float(config["rank"])


def target_linears(arch, targets: Optional[list] = None) -> list[tuple]:
    """(prefix, in_features, out_features) of every targeted Linear.

    ``targets`` is a list of substring matchers against the module's flat
    param prefix (``layers.2.0.1`` style); None/empty targets every Linear
    in the stack — attention QKV/output projections and MLP projections
    alike (GatedMLP children are Linears and match through the same walk).
    """
    out = []
    for mod in arch.mods:
        for sub in mod.walk():
            if type(sub) is not M.Linear:
                continue
            if targets and not any(t in sub.prefix for t in targets):
                continue
            out.append((sub.prefix, sub.in_features, sub.out_features))
    if not out:
        raise ValueError(
            f"adapter targets {targets!r} match no Linear projection in "
            f"this model")
    return out


def init_params(arch, config: dict, seed: int = 0,
                init: str = "zeros") -> dict:
    """Fresh adapter tree: A ~ N(0, 1/sqrt(in)) per target, B zeros — a
    new adapter serves as an exact identity until trained.  ``init=
    'random'`` also randomizes B (benchmarks/tests that need a non-trivial
    delta without a training run)."""
    rng = np.random.default_rng(seed)
    r = config["rank"]
    params = {}
    for prefix, din, dout in target_linears(arch, config["targets"]):
        params[f"{prefix}.lora_A"] = (
            rng.standard_normal((r, din)) / np.sqrt(din)).astype(np.float32)
        if init == "random":
            params[f"{prefix}.lora_B"] = (
                rng.standard_normal((dout, r)) / np.sqrt(r)
            ).astype(np.float32)
        else:
            params[f"{prefix}.lora_B"] = np.zeros((dout, r), np.float32)
    return params


def bind_model(model, adapter_params: dict, config: dict):
    """Shallow model copy with the adapter factors bound into the flat
    param dict — every compiled program applies ``base + (alpha/r)·B·A·x``
    for the targeted projections through the ordinary ``Ctx.params`` path
    (jit retraces once per bound structure; the arch's program cache is
    shared with the unbound model)."""
    bound = copy.copy(model)
    extra = {k: jnp.asarray(v) for k, v in adapter_params.items()}
    s = jnp.asarray(scale(config), jnp.float32)
    for key in adapter_params:
        if key.endswith(".lora_A"):
            extra[key[:-len("lora_A")] + "lora_scale"] = s
    bound.params = {**model.params, **extra}
    return bound


def build_pack(slot_params: list, slot_configs: list, n_slots: int) -> dict:
    """Stack per-slot adapter trees into the static mixed-batch pack.

    ``slot_params[i]`` / ``slot_configs[i]`` describe slot ``i`` (None =
    empty slot).  Returns ``{prefix: {a: (n_slots+1, R, in), b: (n_slots+1,
    out, R), scale: (n_slots+1,)}}`` over the UNION of targeted prefixes,
    rank-padded to ``PENROZ_LORA_MAX_RANK`` — zero-padded rows/slots
    contribute an exactly-zero delta, and the trailing slot is the
    always-zero base-row slot.  Returns None when no slot is live.
    """
    R = max_rank()
    shapes: dict = {}
    for params in slot_params:
        if params is None:
            continue
        for key, v in params.items():
            if key.endswith(".lora_A"):
                prefix = key[:-len(".lora_A")]
                b = params[f"{prefix}.lora_B"]
                shapes[prefix] = (v.shape[1], b.shape[0])  # (in, out)
    if not shapes:
        return None
    pack = {}
    for prefix, (din, dout) in shapes.items():
        a = np.zeros((n_slots + 1, R, din), np.float32)
        b = np.zeros((n_slots + 1, dout, R), np.float32)
        s = np.zeros((n_slots + 1,), np.float32)
        for i, (params, cfg) in enumerate(zip(slot_params, slot_configs)):
            if params is None:
                continue
            ak = params.get(f"{prefix}.lora_A")
            if ak is None:  # this slot's adapter doesn't target the prefix
                continue
            r = ak.shape[0]
            a[i, :r] = ak
            b[i, :, :r] = params[f"{prefix}.lora_B"]
            s[i] = scale(cfg)
        pack[prefix] = {"a": jnp.asarray(a), "b": jnp.asarray(b),
                        "scale": jnp.asarray(s)}
    return pack


def merge_weights(base_params: dict, adapter_params: dict,
                  config: dict) -> dict:
    """Base params with every targeted weight replaced by ``W +
    (alpha/r)·B·A`` — the offline-merge oracle used by tests."""
    out = dict(base_params)
    s = scale(config)
    for key, a in adapter_params.items():
        if not key.endswith(".lora_A"):
            continue
        prefix = key[:-len(".lora_A")]
        b = adapter_params[f"{prefix}.lora_B"]
        w = np.asarray(out[f"{prefix}.weight"], np.float32)
        out[f"{prefix}.weight"] = jnp.asarray(
            w + s * (np.asarray(b, np.float32) @ np.asarray(a, np.float32)))
    return out


# ---------------------------------------------------------------------------
# Adapter checkpoints
# ---------------------------------------------------------------------------

def save_adapter(adapter_id: str, model_id: str, config: dict,
                 params: dict, status: dict, progress: list | None = None,
                 sync_flush: bool = False):
    checkpoint.save_adapter(adapter_id, {
        "adapter_id": adapter_id,
        "model_id": model_id,
        "config": config,
        "params": {k: np.asarray(v) for k, v in params.items()},
        "status": status,
        "progress": progress or [],
    }, sync_flush=sync_flush)


def create_adapter(adapter_id: str, model, config: dict, seed: int = 0,
                   init: str = "zeros") -> dict:
    """Initialize + persist a fresh adapter for ``model`` (POST /adapters/
    and the train path's create-on-first-train).  Returns the blob tree."""
    config = validate_config(config)
    params = init_params(model.arch, config, seed=seed, init=init)
    save_adapter(adapter_id, model.model_id, config, params,
                 {"code": "Created", "message": "Adapter created"},
                 sync_flush=True)
    return {"adapter_id": adapter_id, "model_id": model.model_id,
            "config": config, "params": params}


# ---------------------------------------------------------------------------
# Training: freeze the base, descend only the adapter tree
# ---------------------------------------------------------------------------

def train_adapter(model, adapter_id: str, config: dict, dataset_id: str,
                  shard: int = 0, epochs: int = 1, batch_size: int = 1,
                  block_size: int = 1024, step_size: int = 1):
    """API-driven adapter fine-tuning: ``POST /train/`` with an ``adapter``
    config lands here instead of :meth:`NeuralNetworkModel.train_model`.

    The base params are FROZEN — ``value_and_grad`` differentiates only
    the adapter tree, so the optimizer state is adapter-sized (KBs, not
    the base model's moments) and the checkpoint written every ~10 s and
    at completion is adapter-only, loadable straight into the serving
    registry.  Reference loader semantics match the base trainer: every
    micro-step consumes a full ``(batch_size, block_size)`` buffer and
    ``num_steps = buffer // (step_size · block)`` micro-steps accumulate
    into one update.  An existing adapter checkpoint with the same config
    resumes from its params (continued fine-tuning); a config mismatch is
    a ValueError.
    """
    from penroz_tpu.data.loaders import Loader
    from penroz_tpu.models import dsl
    import optax

    config = validate_config(config)
    model_id = model.model_id
    try:
        existing = checkpoint.load_adapter(adapter_id)
    except KeyError:
        existing = None
    if existing is not None:
        if existing.get("model_id") != model_id:
            raise ValueError(
                f"adapter {adapter_id!r} belongs to model "
                f"{existing.get('model_id')!r}, not {model_id!r}")
        prev = validate_config(existing.get("config") or {})
        if (prev["rank"], prev["targets"]) != (config["rank"],
                                               config["targets"]):
            raise ValueError(
                f"adapter {adapter_id!r} exists with rank="
                f"{prev['rank']} targets={prev['targets']}; retrain with "
                f"the same shape or DELETE /adapters/ first")
        lora_params = {k: jnp.asarray(v)
                       for k, v in existing["params"].items()}
    else:
        lora_params = {k: jnp.asarray(v) for k, v in
                       init_params(model.arch, config).items()}

    arch = model.arch
    progress: list = []

    def persist(status, sync=False):
        save_adapter(adapter_id, model_id, config, lora_params, status,
                     progress, sync_flush=sync)

    persist({"code": "Training",
             "message": f"Training adapter on {dataset_id}"})
    try:
        buffer_size = batch_size * block_size
        num_steps = max(1, buffer_size // (step_size * block_size))
        loader = Loader(dataset_id, begin_shard=shard, begin_idx=0,
                        buffer_size=buffer_size, idx_offset=buffer_size)
        optimizer = dsl.build_optimizer(model.optimizer_config)
        opt_state = optimizer.init(lora_params)
        platform = model._platform
        s = jnp.asarray(scale(config), jnp.float32)
        scale_keys = {k[:-len("lora_A")] + "lora_scale"
                      for k in lora_params if k.endswith(".lora_A")}

        def loss_fn(lp, base, bufs, x, y, rng):
            params = {**base, **lp}
            for key in scale_keys:
                params[key] = s
            _, cost, _, _ = arch.forward(params, bufs, x, y, training=True,
                                         rng=rng, skip_softmax=True,
                                         platform=platform)
            return cost

        grad_fn = jax.value_and_grad(loss_fn)

        def epoch_fn(lp, opt_st, base, bufs, xs, ys, rng):
            def micro(carry, batch):
                grads_acc, cost_acc, i = carry
                x, y = batch
                cost, grads = grad_fn(lp, base, bufs, x, y,
                                      jax.random.fold_in(rng, i))
                grads_acc = jax.tree.map(
                    lambda a, g: a + g.astype(jnp.float32), grads_acc,
                    grads)
                return (grads_acc, cost_acc + cost, i + 1), None

            zeros = jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), lp)
            (grads, cost_sum, _), _ = jax.lax.scan(
                micro, (zeros, jnp.zeros((), jnp.float32), 0), (xs, ys))
            inv = 1.0 / num_steps
            grads = jax.tree.map(lambda g, p: (g * inv).astype(p.dtype),
                                 grads, lp)
            updates, opt_st = optimizer.update(grads, opt_st, lp)
            return optax.apply_updates(lp, updates), opt_st, cost_sum * inv

        fn = jax.jit(epoch_fn, donate_argnums=(0, 1))
        rng = jax.random.key(0)
        last_save = time.monotonic()
        for epoch in range(epochs):
            t0 = time.monotonic()
            xs, ys = [], []
            for _ in range(num_steps):
                x, y = loader.next_batch()
                xs.append(x.reshape(batch_size, block_size))
                ys.append(y.reshape(batch_size, block_size))
            lora_params, opt_state, cost = fn(
                lora_params, opt_state, model.params, model.buffers,
                np.stack(xs), np.stack(ys), jax.random.fold_in(rng, epoch))
            cost = float(cost)
            duration = time.monotonic() - t0
            progress.append({"epoch": epoch + 1, "cost": cost,
                             "durationInSecs": duration})
            log.info("Adapter %s epoch %d: cost=%.4f", adapter_id,
                     epoch + 1, cost)
            if time.monotonic() - last_save >= 10:
                persist({"code": "Training",
                         "message": f"Training adapter on {dataset_id}"})
                last_save = time.monotonic()
        persist({"code": "Trained",
                 "message": f"Trained {epochs} epoch(s)"}, sync=True)
        log.info("Adapter %s training completed (%d epochs)", adapter_id,
                 epochs)
    except Exception as e:  # noqa: BLE001 — record, then surface
        try:
            persist({"code": "Error", "message": str(e)}, sync=True)
        except Exception:  # noqa: BLE001
            log.exception("Failed to persist adapter error status")
        raise
    return lora_params
