"""Ready-made layer-DSL configs for the benchmark/baseline model families.

The reference embeds exactly one config — the GPT-2-124M `/model/` OpenAPI
example (reference main.py:53-93); these builders generate that same DSL
shape for the whole GPT-2 size ladder (BASELINE.md's gpt2-124M/xl train
configs) plus the makemore-style char-level MLP (BASELINE.md's CPU-parity
config).  All return plain JSON-able DSL lists accepted by ``POST /model/``
and :class:`penroz_tpu.models.dsl.Mapper`.
"""

from __future__ import annotations

GPT2_SIZES = {
    # name: (d_model, heads, depth)
    "gpt2": (768, 12, 12),          # 124M
    "gpt2-medium": (1024, 16, 24),  # 350M
    "gpt2-large": (1280, 20, 36),   # 774M
    "gpt2-xl": (1600, 25, 48),      # 1.5B
}

ADAMW = {"adamw": {"lr": 6e-4, "betas": [0.9, 0.95], "eps": 1e-8}}


def gpt2(size: str = "gpt2", vocab: int = 50304, block: int = 1024,
         dropout: float = 0.0) -> list:
    """GPT-2 style DSL (the reference's /model/ example, main.py:53-84) at
    any ladder size.  ``vocab`` defaults to the 64-padded 50304 the nanoGPT
    lineage uses for MXU-friendly lm-head matmuls."""
    if size not in GPT2_SIZES:
        raise ValueError(f"unknown gpt2 size {size!r}; "
                         f"one of {sorted(GPT2_SIZES)}")
    d, heads, depth = GPT2_SIZES[size]
    return gpt2_custom(d=d, heads=heads, depth=depth, vocab=vocab,
                       block=block, dropout=dropout)


def gpt2_custom(d: int, heads: int, depth: int, vocab: int = 50304,
                block: int = 1024, dropout: float = 0.0) -> list:
    """GPT-2-shaped DSL at arbitrary dimensions — the single source for the
    ladder sizes above, the driver contract's flagship config
    (``__graft_entry__._gpt2_dsl``), and the scaling bench's shrunken stack.
    (The HF-config→DSL builder in models/dsl.py stays separate: it is
    table-driven against the reference's ``mappers.py:121-176`` field
    mapping, which is its own parity contract.)"""
    std = 0.02
    proj_std = std / (2 * depth) ** 0.5
    return ([{"summation": [
                {"embedding": {"num_embeddings": vocab, "embedding_dim": d},
                 "normal": {"mean": 0.0, "std": std}},
                {"position": {"num_embeddings": block, "embedding_dim": d},
                 "normal": {"mean": 0.0, "std": std}}]},
             {"dropout": {"p": dropout}}]
            + [{"residual": [
                {"sequential": [
                    {"layernorm": {"normalized_shape": d}},
                    {"linear": {"in_features": d, "out_features": 3 * d},
                     "normal": {"mean": 0.0, "std": std}, "zeros": {}},
                    {"attention": {"num_heads": heads, "dropout": dropout}},
                    {"linear": {"in_features": d, "out_features": d},
                     "normal": {"mean": 0.0, "std": proj_std}, "zeros": {}},
                    {"dropout": {"p": dropout}}]},
                {"sequential": [
                    {"layernorm": {"normalized_shape": d}},
                    {"linear": {"in_features": d, "out_features": 4 * d},
                     "normal": {"mean": 0.0, "std": std}, "zeros": {}},
                    {"gelu": {"approximate": "tanh"}},
                    {"linear": {"in_features": 4 * d, "out_features": d},
                     "normal": {"mean": 0.0, "std": proj_std}, "zeros": {}},
                    {"dropout": {"p": dropout}}]}]} for _ in range(depth)]
            + [{"layernorm": {"normalized_shape": d}},
               {"linear": {"in_features": d, "out_features": vocab,
                           "bias": False}},
               {"softmaxlast": {"dim": -1}}])


def _ssm_block(d: int, heads: int, head_dim: int, value_dim: int,
               proj_std: float, dropout: float) -> dict:
    """One gated-SSM residual block: LN → fused qkvg projection → O(1)
    recurrent mix → output projection.  The fused linear emits
    ``heads * (2*head_dim + value_dim + 1)`` features — [q | k | v | gate]
    in :class:`penroz_tpu.ops.modules.GatedSSM`'s split order."""
    std = 0.02
    fused = heads * (2 * head_dim + value_dim + 1)
    return {"residual": [
        {"sequential": [
            {"layernorm": {"normalized_shape": d}},
            {"linear": {"in_features": d, "out_features": fused},
             "normal": {"mean": 0.0, "std": std}, "zeros": {}},
            {"ssm": {"num_heads": heads, "head_dim": head_dim,
                     "value_dim": value_dim}},
            {"linear": {"in_features": heads * value_dim, "out_features": d},
             "normal": {"mean": 0.0, "std": proj_std}, "zeros": {}},
            {"dropout": {"p": dropout}}]},
        {"sequential": [
            {"layernorm": {"normalized_shape": d}},
            {"linear": {"in_features": d, "out_features": 4 * d},
             "normal": {"mean": 0.0, "std": std}, "zeros": {}},
            {"gelu": {"approximate": "tanh"}},
            {"linear": {"in_features": 4 * d, "out_features": d},
             "normal": {"mean": 0.0, "std": proj_std}, "zeros": {}},
            {"dropout": {"p": dropout}}]}]}


def hybrid_custom(d: int, heads: int, depth: int, vocab: int = 50304,
                  block: int = 1024, dropout: float = 0.0,
                  ssm_every: int = 2) -> list:
    """Hybrid attention/SSM stack: every ``ssm_every``-th residual block is a
    gated-SSM block (O(1) per-row state), the rest stay full attention
    (O(T) KV rows).  ``ssm_every=1`` yields a pure-SSM model with no KV
    cache at all — both extremes serve through the unified scheduler."""
    base = gpt2_custom(d=d, heads=heads, depth=depth, vocab=vocab,
                       block=block, dropout=dropout)
    proj_std = 0.02 / (2 * depth) ** 0.5
    head_dim = d // heads
    # Blocks occupy base[2:2+depth]; replace the selected ones in place.
    for i in range(depth):
        if i % ssm_every == 0:
            base[2 + i] = _ssm_block(d, heads, head_dim, head_dim,
                                     proj_std, dropout)
    return base


def makemore_mlp(vocab: int = 27, d_embed: int = 10,
                 d_hidden: int = 200) -> list:
    """Char-level MLP in the makemore style (BASELINE.md CPU-parity config):
    per-position embedding → tanh MLP → softmax CE.  Runs the single-process
    CPU path end-to-end (tests/test_model.py::test_mlp_training_per_position
    is the executable spec)."""
    return [
        {"embedding": {"num_embeddings": vocab, "embedding_dim": d_embed},
         "normal": {"mean": 0.0, "std": 0.02}},
        {"linear": {"in_features": d_embed, "out_features": d_hidden},
         "normal": {"mean": 0.0, "std": 0.02}, "zeros": {}},
        {"tanh": {}},
        {"linear": {"in_features": d_hidden, "out_features": vocab},
         "normal": {"mean": 0.0, "std": 0.02}, "zeros": {}},
        {"softmaxlast": {"dim": -1}},
    ]


def param_count(layers: list, optimizer: dict = ADAMW) -> int:
    """Total parameter count of a DSL config without allocating it:
    ``jax.eval_shape`` traces the initializer to ShapeDtypeStructs, so even
    gpt2-xl counts in milliseconds."""
    import jax
    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import CompiledArch
    mapper = Mapper(layers, optimizer)
    arch = CompiledArch.get(mapper.layers)
    import math
    params, _ = jax.eval_shape(lambda: mapper.init_params(arch.mods, seed=0))
    return sum(math.prod(v.shape) for v in params.values())
