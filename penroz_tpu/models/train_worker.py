"""Training-worker subprocess entry (``PENROZ_TRAIN_WORKER=1``).

The serving parent spawns ``python -m penroz_tpu.models.train_worker
'<json args>'`` so a native crash in training (XLA CHECK-abort, OOM kill,
accelerator runtime segfault) kills THIS process, never the API server —
the reference's containment shape (``/root/reference/main.py:461-464``
forks an ``mp.Process`` per training run).  All state flows through the
checkpoint stream: the trainer serializes every ~10 s and on completion;
the parent post-mortems the final status
(``NeuralNetworkModel._train_in_worker_process``).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time


def _watch_parent(parent_pid: int) -> None:
    """Exit when the serving parent dies (reparented to init/subreaper).

    The parent's atexit sweep covers clean shutdowns; this covers the
    SIGKILLed server: an orphaned worker would keep serializing status
    'Training' every ~10 s and race checkpoint writes against a
    restarted server's orphan sweep (status flip-flop, torn files)."""
    while True:
        if os.getppid() != parent_pid:
            print("train_worker: parent died; exiting", file=sys.stderr,
                  flush=True)
            os._exit(1)
        time.sleep(2.0)


def main(argv: list[str]) -> int:
    args = json.loads(argv[0])
    threading.Thread(target=_watch_parent, args=(os.getppid(),),
                     daemon=True).start()
    from penroz_tpu.models.model import NeuralNetworkModel
    adapter = args.get("adapter")
    model = NeuralNetworkModel.train_model_on_device(
        args["model_id"], args["device"], args["dataset_id"], args["shard"],
        args["epochs"], args["batch_size"], args["block_size"],
        args["step_size"], adapter=adapter)
    # In-process training records failures as status Error and returns;
    # propagate that as a nonzero exit so the parent logs the death even
    # when it was a clean Python-level failure.  Adapter runs key the exit
    # code off the ADAPTER blob's status — the base model's status is
    # untouched by a LoRA fine-tune.
    if adapter is not None:
        from penroz_tpu.utils import checkpoint
        status = (checkpoint.peek_adapter_tree(adapter["adapter_id"])
                  .get("status") or {})
        return 0 if status.get("code") == "Trained" else 1
    return 0 if model.status.get("code") == "Trained" else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
