"""Model layer: JSON layer/optimizer DSL (dsl.py) and the model runtime
(model.py) — the TPU-native equivalents of the reference's mappers.py and
neural_net_model.py."""
