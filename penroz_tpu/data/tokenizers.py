"""Tokenizer facade over four backends (reference: gpt_tokenizers.py:8-22):

- ``byte`` — raw UTF-8 bytes + an EOT id (offline, dependency-free);
- ``tiktoken/<name>`` — tiktoken encodings (``encode_ordinary`` + eot);
- ``bpe:<path>`` — our native byte-BPE model files (data/bpe.py);
- anything else — a HuggingFace ``AutoTokenizer`` name
  (``add_special_tokens=False`` + eos).

All backends are imported lazily so offline paths never touch hub code.
"""

from __future__ import annotations

BYTE_EOT = 256


class Tokenizer:
    def __init__(self, encoding: str):
        self.encoding = encoding
        if encoding == "byte":
            self._kind = "byte"
        elif encoding.startswith("tiktoken/"):
            import tiktoken
            self._enc = tiktoken.get_encoding(encoding.split("/", 1)[1])
            self._kind = "tiktoken"
        elif encoding.startswith("bpe:"):
            from penroz_tpu.data.bpe import ByteBPE
            self._enc = ByteBPE.load(encoding.split(":", 1)[1])
            self._kind = "bpe"
        else:
            from transformers import AutoTokenizer
            self._enc = AutoTokenizer.from_pretrained(encoding)
            self._kind = "hf"

    def tokenize(self, text: str) -> list[int]:
        if self._kind == "byte":
            return list(text.encode()) + [BYTE_EOT]
        if self._kind == "tiktoken":
            return list(self._enc.encode_ordinary(text)) + [self._enc.eot_token]
        if self._kind == "bpe":
            return self._enc.encode(text) + [self._enc.eot_token]
        tokens = list(self._enc.encode(text, add_special_tokens=False))
        if self._enc.eos_token_id is not None:
            tokens.append(self._enc.eos_token_id)
        return tokens

    def decode(self, tokens) -> str:
        if self._kind == "byte":
            return bytes(t for t in tokens if 0 <= t < 256).decode(
                "utf-8", errors="replace")
        return self._enc.decode(tokens)
