"""Byte-level BPE tokenizer backed by the native C++ core.

The reference consumes BPE through tiktoken's Rust extension
(gpt_tokenizers.py:10); this is the framework's own native equivalent
(native/penroz_bpe.cpp), compiled on demand with g++ as a CPython extension
(no pybind11).  A pure-Python implementation of the identical algorithm is
both the fallback when the toolchain is unavailable and the correctness
oracle for the native core's tests.

Scheme ("penroz-bpe"): byte symbols 0..255; words pre-split as
``{optional leading space}letters | digits | single other byte``; training
merges the highest-count adjacent pair (ties: smallest pair) until the target
vocab or no pair repeats; encoding greedily applies the lowest-rank merge.
"""

from __future__ import annotations

import json
import logging
import os
from collections import Counter, defaultdict

log = logging.getLogger(__name__)

FORMAT = "penroz-bpe"

def _load_native():
    from penroz_tpu.utils import native_build
    out_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "_native")
    return native_build.load_extension("penroz_bpe", out_dir)


# ---------------------------------------------------------------------------
# Pure-Python oracle (mirrors native/penroz_bpe.cpp exactly)
# ---------------------------------------------------------------------------

def _is_letter(c: int) -> bool:
    return 97 <= c <= 122 or 65 <= c <= 90 or c >= 0x80


def _is_digit(c: int) -> bool:
    return 48 <= c <= 57


def split_words(data: bytes) -> list[bytes]:
    """Pre-split bytes into BPE words: [space]letters+ | digits+ | other."""
    words = []
    i, n = 0, len(data)
    while i < n:
        start = i
        j = i
        if data[j] == 0x20 and j + 1 < n and _is_letter(data[j + 1]):
            j += 1
        if j < n and _is_letter(data[j]):
            while j < n and _is_letter(data[j]):
                j += 1
            words.append(data[start:j])
            i = j
        elif j < n and _is_digit(data[j]):
            while j < n and _is_digit(data[j]):
                j += 1
            words.append(data[start:j])
            i = j
        else:
            words.append(data[start:start + 1])
            i = start + 1
    return words


def _py_train(corpus: bytes, num_merges: int) -> list[tuple[int, int]]:
    """Train merges — byte-exact oracle for the native trainer."""
    word_counts = Counter(split_words(corpus))
    words = [[list(w), c] for w, c in word_counts.items()]

    pair_counts: Counter = Counter()
    pair_words: defaultdict = defaultdict(set)
    for wi, (syms, count) in enumerate(words):
        for k in range(len(syms) - 1):
            pair = (syms[k], syms[k + 1])
            pair_counts[pair] += count
            pair_words[pair].add(wi)

    merges: list[tuple[int, int]] = []
    next_id = 256
    for _ in range(num_merges):
        best = None
        best_count = 0
        for pair, count in pair_counts.items():
            if count > best_count or (count == best_count and best is not None
                                      and pair < best):
                best = pair
                best_count = count
        if best_count < 2:
            break
        new_id = next_id
        next_id += 1
        merges.append(best)
        for wi in list(pair_words[best]):
            syms, wc = words[wi]
            for k in range(len(syms) - 1):
                pair = (syms[k], syms[k + 1])
                if pair in pair_counts:
                    pair_counts[pair] -= wc
                    if pair_counts[pair] <= 0:
                        del pair_counts[pair]
                if pair in pair_words:
                    pair_words[pair].discard(wi)
            out = []
            k = 0
            while k < len(syms):
                if (k + 1 < len(syms) and syms[k] == best[0]
                        and syms[k + 1] == best[1]):
                    out.append(new_id)
                    k += 2
                else:
                    out.append(syms[k])
                    k += 1
            words[wi][0] = out
            for k in range(len(out) - 1):
                pair = (out[k], out[k + 1])
                pair_counts[pair] += wc
                pair_words[pair].add(wi)
    return merges


class _PyEncoder:
    """Greedy lowest-rank BPE encoder — oracle for the native Encoder."""

    def __init__(self, merges):
        self.ranks = {tuple(p): i for i, p in enumerate(merges)}
        self.pair_ids = {tuple(p): 256 + i for i, p in enumerate(merges)}
        self.vocab = [bytes([b]) for b in range(256)]
        for a, b in merges:
            self.vocab.append(self.vocab[a] + self.vocab[b])

    def _encode_word(self, word: bytes) -> list[int]:
        syms = list(word)
        while len(syms) >= 2:
            best_rank = None
            best_pos = 0
            for k in range(len(syms) - 1):
                rank = self.ranks.get((syms[k], syms[k + 1]))
                if rank is not None and (best_rank is None or rank < best_rank):
                    best_rank = rank
                    best_pos = k
            if best_rank is None:
                break
            pair = (syms[best_pos], syms[best_pos + 1])
            syms[best_pos:best_pos + 2] = [self.pair_ids[pair]]
        return syms

    def encode(self, data: bytes) -> list[int]:
        ids: list[int] = []
        for word in split_words(data):
            ids.extend(self._encode_word(word))
        return ids

    def decode(self, ids) -> bytes:
        return b"".join(self.vocab[i] for i in ids
                        if 0 <= i < len(self.vocab))


# ---------------------------------------------------------------------------
# Public facade
# ---------------------------------------------------------------------------

class ByteBPE:
    """Trained byte-BPE model: merges + (native or Python) encoder."""

    def __init__(self, merges):
        self.merges = [tuple(int(a) for a in m) for m in merges]
        native = _load_native()
        if native is not None:
            self._enc = native.Encoder(self.merges)
            self.native = True
        else:
            self._enc = _PyEncoder(self.merges)
            self.native = False

    @property
    def vocab_size(self) -> int:
        return 256 + len(self.merges)

    @property
    def eot_token(self) -> int:
        """End-of-text id — one past the merge vocabulary."""
        return self.vocab_size

    @classmethod
    def train_from_text(cls, text: str, vocab_size: int = 512) -> "ByteBPE":
        num_merges = max(0, int(vocab_size) - 256)
        data = text.encode()
        native = _load_native()
        if native is not None:
            merges = [tuple(m) for m in native.train(data, num_merges)]
        else:
            merges = _py_train(data, num_merges)
        return cls(merges)

    def encode(self, text: str) -> list[int]:
        return [int(t) for t in self._enc.encode(text.encode())]

    def decode(self, ids) -> str:
        raw = self._enc.decode([int(t) for t in ids])
        return bytes(raw).decode("utf-8", errors="replace")

    def save(self, path: str):
        with open(path, "w") as f:
            json.dump({"format": FORMAT,
                       "merges": [list(m) for m in self.merges]}, f)

    @classmethod
    def load(cls, path: str) -> "ByteBPE":
        with open(path) as f:
            data = json.load(f)
        if data.get("format") != FORMAT:
            raise ValueError(f"Not a {FORMAT} model file: {path}")
        return cls(data["merges"])
