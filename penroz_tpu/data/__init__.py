"""Data layer: tokenizers (incl. the native BPE core), dataset download/
sharding, and rank-strided shard loading — the TPU-native equivalents of the
reference's gpt_tokenizers.py and loaders.py."""
