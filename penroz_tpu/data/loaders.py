"""Dataset download/sharding and rank-strided shard loading.

The TPU-native equivalent of the reference's ``loaders.py``:

- ``Downloader`` — HF ``datasets`` → tokenize → fixed-size uint16 ``.npy``
  shards named ``{dataset_id}_{idx:06d}`` (reference: loaders.py:16-41).
  Tokenization fans out over a thread pool (tiktoken/HF tokenizers release
  the GIL in native code; the reference forks a process pool instead,
  loaders.py:29-32, which would fight the JAX runtime here).
- ``Loader`` — stateful ``next_batch`` over the sorted shard sequence with
  shard wraparound/concatenation and rank-strided indexing via
  ``begin_idx``/``idx_offset`` (reference: loaders.py:45-87); targets are the
  input shifted by ``target_offset`` (0 → no targets, for separate target
  datasets in /evaluate/).
"""

from __future__ import annotations

import glob
import os
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from penroz_tpu.data.tokenizers import Tokenizer

DATA_FOLDER = "data"


class Loader:
    def __init__(self, dataset_id: str, begin_shard: int = 0,
                 begin_idx: int = 0, buffer_size: int = 1024,
                 idx_offset: int | None = None):
        self.dataset_id = dataset_id
        self.shard = begin_shard
        self.idx = begin_idx
        self.buffer_size = int(buffer_size)
        self.idx_offset = int(idx_offset if idx_offset is not None
                              else buffer_size)
        self._cache: dict[int, np.ndarray] = {}

    def _files(self) -> list[str]:
        pattern = os.path.join(DATA_FOLDER, f"{self.dataset_id}_*.npy")
        return sorted(os.path.basename(p) for p in glob.glob(pattern))

    def list(self) -> list[str]:
        return self._files()

    def delete(self):
        for name in self._files():
            os.remove(os.path.join(DATA_FOLDER, name))
        self._cache.clear()

    def _shard_data(self, files: list[str], shard_idx: int) -> np.ndarray:
        shard_idx %= len(files)
        data = self._cache.get(shard_idx)
        if data is None:
            # keep at most two shards resident (current + wraparound peek)
            if len(self._cache) > 1:
                self._cache.clear()
            data = np.load(os.path.join(DATA_FOLDER, files[shard_idx]))
            self._cache[shard_idx] = data
        return data

    def next_batch(self, target_offset: int = 1):
        """(input, target) flat int32 arrays of ``buffer_size`` tokens;
        target is input shifted by ``target_offset`` (None when 0)."""
        files = self._files()
        if not files:
            raise ValueError(f"Dataset {self.dataset_id} has no shards")
        need = self.buffer_size + target_offset
        self.shard %= len(files)
        data = self._shard_data(files, self.shard)
        while self.idx >= len(data):
            self.idx -= len(data)
            self.shard = (self.shard + 1) % len(files)
            data = self._shard_data(files, self.shard)
        buf = data[self.idx:self.idx + need]
        peek = self.shard
        while len(buf) < need:
            peek = (peek + 1) % len(files)
            extra = self._shard_data(files, peek)
            buf = np.concatenate([buf, extra[:need - len(buf)]])
        x = buf[:self.buffer_size].astype(np.int32)
        y = (buf[target_offset:target_offset + self.buffer_size]
             .astype(np.int32) if target_offset else None)
        self.idx += self.idx_offset
        return x, y


class Downloader:
    def __init__(self, dataset_id: str, shard_size: int = 2 ** 24,
                 encoding: str = "tiktoken/gpt2"):
        self.dataset_id = dataset_id
        self.shard_size = int(shard_size)
        self.tokenizer = Tokenizer(encoding)

    def download(self, path: str, name: str | None = None,
                 split: str = "train"):
        """Download + tokenize + write fixed-size uint16 shards (the final
        partial shard is also flushed)."""
        import datasets
        ds = datasets.load_dataset(path, name, split=split)
        os.makedirs(DATA_FOLDER, exist_ok=True)
        buffer = np.empty(self.shard_size, np.uint16)
        fill = 0
        shard_idx = 0

        def flush(upto: int):
            nonlocal shard_idx
            np.save(os.path.join(
                DATA_FOLDER, f"{self.dataset_id}_{shard_idx:06d}"),
                buffer[:upto])
            shard_idx += 1

        workers = max(1, (os.cpu_count() or 2) // 2)
        with ThreadPoolExecutor(max_workers=workers) as pool:
            for tokens in pool.map(self.tokenizer.tokenize, ds["text"],
                                   chunksize=16):
                arr = np.asarray(tokens, np.uint16)
                pos = 0
                while pos < len(arr):
                    take = min(len(arr) - pos, self.shard_size - fill)
                    buffer[fill:fill + take] = arr[pos:pos + take]
                    fill += take
                    pos += take
                    if fill == self.shard_size:
                        flush(fill)
                        fill = 0
        if fill:
            flush(fill)
