"""Dataset download/sharding and rank-strided shard loading.

The TPU-native equivalent of the reference's ``loaders.py``:

- ``Downloader`` — HF ``datasets`` → tokenize → fixed-size uint16 ``.npy``
  shards named ``{dataset_id}_{idx:06d}`` (reference: loaders.py:16-41).
  Tokenization fans out over a thread pool (tiktoken/HF tokenizers release
  the GIL in native code; the reference forks a process pool instead,
  loaders.py:29-32, which would fight the JAX runtime here).
- ``Loader`` — stateful ``next_batch`` over the sorted shard sequence with
  shard wraparound/concatenation and rank-strided indexing via
  ``begin_idx``/``idx_offset`` (reference: loaders.py:45-87); targets are the
  input shifted by ``target_offset`` (0 → no targets, for separate target
  datasets in /evaluate/).
"""

from __future__ import annotations

import glob
import logging
import os
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from penroz_tpu.data.tokenizers import Tokenizer

log = logging.getLogger(__name__)

DATA_FOLDER = "data"
NATIVE_LOADER_ENV = "PENROZ_NATIVE_LOADER"


def _native_loader_module():
    if os.environ.get(NATIVE_LOADER_ENV, "1") == "0":
        return None
    from penroz_tpu.utils import native_build
    out_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "_native")
    return native_build.load_extension("penroz_loader", out_dir)


def _npy_payload(path: str):
    """(byte offset, token count) of a uint16 1-D .npy payload, or None."""
    m = np.load(path, mmap_mode="r")
    if m.dtype != np.uint16 or m.ndim != 1:
        return None
    return int(m.offset), int(m.shape[0])


class Loader:
    def __init__(self, dataset_id: str, begin_shard: int = 0,
                 begin_idx: int = 0, buffer_size: int = 1024,
                 idx_offset: int | None = None):
        self.dataset_id = dataset_id
        self.shard = begin_shard
        self.idx = begin_idx
        self.buffer_size = int(buffer_size)
        self.idx_offset = int(idx_offset if idx_offset is not None
                              else buffer_size)
        self._cache: dict[int, np.ndarray] = {}
        self._stream = None          # native mmap stream (penroz_loader)
        self._stream_sig: list[tuple] = []   # (name, size, mtime_ns) per shard
        self._prefix: list[int] = []

    def _files(self) -> list[str]:
        pattern = os.path.join(DATA_FOLDER, f"{self.dataset_id}_*.npy")
        return sorted(os.path.basename(p) for p in glob.glob(pattern))

    def list(self) -> list[str]:
        return self._files()

    def delete(self):
        for name in self._files():
            os.remove(os.path.join(DATA_FOLDER, name))
        self._cache.clear()
        # Drop the mmap stream too: a re-download reusing the same shard
        # filenames must not serve the deleted files' pages.
        self._stream, self._stream_sig, self._prefix = None, [], []

    def _shard_data(self, files: list[str], shard_idx: int) -> np.ndarray:
        shard_idx %= len(files)
        data = self._cache.get(shard_idx)
        if data is None:
            # keep at most two shards resident (current + wraparound peek)
            if len(self._cache) > 1:
                self._cache.clear()
            data = np.load(os.path.join(DATA_FOLDER, files[shard_idx]))
            self._cache[shard_idx] = data
        return data

    def _native_stream(self, files: list[str]):
        """mmap-backed token stream over ``files``; None → numpy fallback.

        Rebuilt whenever any shard's (name, size, mtime) changes — new
        shards from a concurrent Downloader, or same-name rewrites after a
        delete + re-download."""
        try:
            sig = [(name, st.st_size, st.st_mtime_ns) for name, st in
                   ((n, os.stat(os.path.join(DATA_FOLDER, n)))
                    for n in files)]
        except OSError:
            return None
        if sig == self._stream_sig:
            return self._stream
        self._stream, self._stream_sig = None, sig
        module = _native_loader_module()
        if module is None:
            return None
        shards, prefix, total = [], [], 0
        try:
            for name in files:
                path = os.path.join(DATA_FOLDER, name)
                payload = _npy_payload(path)
                if payload is None:
                    return None  # non-uint16 shard: numpy path handles it
                prefix.append(total)
                total += payload[1]
                shards.append((path, payload[0], payload[1]))
            self._stream = module.Stream(shards)
            self._prefix = prefix
        except Exception as e:  # noqa: BLE001
            log.warning("Native loader failed (%s); using numpy path", e)
            self._stream = None
        return self._stream

    def next_batch(self, target_offset: int = 1):
        """(input, target) flat int32 arrays of ``buffer_size`` tokens;
        target is input shifted by ``target_offset`` (None when 0)."""
        files = self._files()
        if not files:
            raise ValueError(f"Dataset {self.dataset_id} has no shards")
        need = self.buffer_size + target_offset
        stream = self._native_stream(files)
        if stream is not None:
            # (shard, idx) → linear stream position, then fold the state
            # back to normalized (shard, idx) exactly as the fallback's
            # shard-walk would — both paths must hold identical state so a
            # mid-run path switch or shard-list change never shifts the
            # window (ranks on different toolchains read the same data).
            pos = (self._prefix[self.shard % len(files)]
                   + self.idx) % stream.total_tokens
            self.shard = max(i for i, p in enumerate(self._prefix)
                             if p <= pos)
            self.idx = pos - self._prefix[self.shard]
            buf = np.empty(need, np.int32)
            stream.gather_into(buf, pos, need)
            x = buf[:self.buffer_size]
            # y copies: x and y must not alias one buffer (the fallback
            # returns independent arrays; mutation semantics must match).
            y = (buf[target_offset:target_offset + self.buffer_size].copy()
                 if target_offset else None)
            self.idx += self.idx_offset
            stream.prefetch(pos + self.idx_offset, need)
            return x, y
        self.shard %= len(files)
        data = self._shard_data(files, self.shard)
        while self.idx >= len(data):
            self.idx -= len(data)
            self.shard = (self.shard + 1) % len(files)
            data = self._shard_data(files, self.shard)
        buf = data[self.idx:self.idx + need]
        peek = self.shard
        while len(buf) < need:
            peek = (peek + 1) % len(files)
            extra = self._shard_data(files, peek)
            buf = np.concatenate([buf, extra[:need - len(buf)]])
        x = buf[:self.buffer_size].astype(np.int32)
        y = (buf[target_offset:target_offset + self.buffer_size]
             .astype(np.int32) if target_offset else None)
        self.idx += self.idx_offset
        return x, y


class Downloader:
    def __init__(self, dataset_id: str, shard_size: int = 2 ** 24,
                 encoding: str = "tiktoken/gpt2"):
        self.dataset_id = dataset_id
        self.shard_size = int(shard_size)
        self.tokenizer = Tokenizer(encoding)

    def download(self, path: str, name: str | None = None,
                 split: str = "train"):
        """Download + tokenize + write fixed-size uint16 shards (the final
        partial shard is also flushed).  One attempt — bounded retry with
        backoff lives in the API layer (serve/app.py download task), which
        also surfaces terminal failure to clients."""
        from penroz_tpu.utils import faults
        faults.check("data.download")
        import datasets
        ds = datasets.load_dataset(path, name, split=split)
        os.makedirs(DATA_FOLDER, exist_ok=True)
        buffer = np.empty(self.shard_size, np.uint16)
        fill = 0
        shard_idx = 0

        def flush(upto: int):
            nonlocal shard_idx
            # Atomic publish: write to a temp name and os.replace.  A
            # re-download must never truncate a shard inode that a live
            # Loader has mmapped (penroz_loader) — replace swaps the
            # directory entry and the old inode stays valid until unmapped.
            final = os.path.join(DATA_FOLDER,
                                 f"{self.dataset_id}_{shard_idx:06d}.npy")
            tmp = final + ".tmp"
            with open(tmp, "wb") as f:  # np.save on a file object: no
                np.save(f, buffer[:upto])  # surprise .npy suffix appended
            os.replace(tmp, final)
            shard_idx += 1

        workers = max(1, (os.cpu_count() or 2) // 2)
        with ThreadPoolExecutor(max_workers=workers) as pool:
            for tokens in pool.map(self.tokenizer.tokenize, ds["text"],
                                   chunksize=16):
                arr = np.asarray(tokens, np.uint16)
                pos = 0
                while pos < len(arr):
                    take = min(len(arr) - pos, self.shard_size - fill)
                    buffer[fill:fill + take] = arr[pos:pos + take]
                    fill += take
                    pos += take
                    if fill == self.shard_size:
                        flush(fill)
                        fill = 0
        if fill:
            flush(fill)
