"""Resumable token streams: seq-numbered replay rings over /generate/.

A dropped TCP connection used to cancel the generation outright — every
token already decoded was thrown away with it.  This module makes the
stream a RESUMABLE view over the request instead of being the request:

- Every stream event gets a **monotone sequence number** the moment the
  scheduler emits it, and the last ``PENROZ_STREAM_REPLAY`` events stay
  in a bounded per-request replay ring.
- A client disconnect **detaches** the stream instead of cancelling it
  for ``PENROZ_STREAM_DETACH_MS`` (decode keeps running); the default 0
  keeps the pre-existing cancel-on-disconnect behavior byte-for-byte.
- ``GET /generate/{request_id}/stream?from_seq=N`` reattaches: events
  ``>= N`` replay from the ring under the same lock that orders live
  publishes, so the seam is **exactly-once** — no duplicate and no
  missing sequence number, even across a router failover (the registry
  is process-wide; every replica publishes into it).
- When the grace window expires with no reconnect, the ordinary
  cancellation path fires unchanged (``req.cancelled`` is flipped; the
  engine retires the row at its next emission, pages unwound through
  the audited ledger seam).

The ring holds tokens, not KV: its memory cost is a few hundred ints
per in-flight stream.  A reconnect that asks for sequence numbers older
than the ring (slow client, tiny ring) is a typed error — the client
re-issues the request instead of silently skipping tokens.

Fault site: ``stream.resume`` fires at the top of every reattach
(utils/faults.py) — an injected failure surfaces as the HTTP error and
leaves the generation running and the ledger audit clean.
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import time

from penroz_tpu.utils import faults

log = logging.getLogger(__name__)

REPLAY_ENV = "PENROZ_STREAM_REPLAY"          # ring capacity (events)
DETACH_MS_ENV = "PENROZ_STREAM_DETACH_MS"    # disconnect grace; 0 = cancel
_LINGER_S = 60.0        # terminal sessions stay reattachable this long
_TERMINAL = ("done", "error", "timeout")


def replay_capacity() -> int:
    try:
        return max(1, int(os.environ.get(REPLAY_ENV, "256")))
    except ValueError:
        return 256


def detach_grace_ms() -> float:
    try:
        return max(0.0, float(os.environ.get(DETACH_MS_ENV, "0")))
    except ValueError:
        return 0.0


class ReplayGapError(ValueError):
    """``from_seq`` asked for events the bounded ring no longer holds —
    resuming would silently skip tokens, so the client must restart."""


class StreamSession:
    """One request's event ring + the (at most one) attached consumer.

    ``publish`` runs on the engine worker thread; attach/detach run on
    the event loop.  One lock orders them, which is what makes the
    replay-then-live seam exactly-once: a publish either lands in the
    ring snapshot the reattach replays or in the queue it subscribes —
    never both, never neither."""

    def __init__(self, request_id: str, req):
        self.request_id = request_id
        self.req = req
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(
            maxlen=replay_capacity())
        self._next_seq = 0
        self._consumer = None           # (loop, asyncio.Queue) | None
        self._timer: threading.Timer | None = None
        self.detached_at: float | None = None
        self.terminal = False
        self.done_at: float | None = None
        self.expired = False
        self.resumes = 0

    # -- producer side (engine worker thread) -------------------------------

    def publish(self, kind: str, value) -> None:
        with self._lock:
            seq = self._next_seq
            self._next_seq += 1
            self._ring.append((seq, kind, value))
            if kind in _TERMINAL:
                self.terminal = True
                self.done_at = time.monotonic()
                self._cancel_timer_locked()
            consumer = self._consumer
        if consumer is not None:
            loop, queue = consumer
            try:
                loop.call_soon_threadsafe(queue.put_nowait,
                                          (seq, kind, value))
            except RuntimeError:
                pass    # loop closed mid-shutdown; ring still has the event

    # -- consumer side (event loop) ------------------------------------------

    def attach_initial(self, loop, queue) -> None:
        """Bind the original /generate/ handler's queue (seq 0 onward;
        nothing published yet, so no replay needed)."""
        with self._lock:
            self._consumer = (loop, queue)

    def resume(self, loop, queue, from_seq: int) -> list:
        """Reattach at ``from_seq``: returns the ring backlog to deliver
        first, with the queue subscribed for everything after it —
        atomically, so no event is duplicated or lost across the seam.

        :raises ReplayGapError: the ring has already evicted events
            ``>= from_seq`` (client fell further behind than
            ``PENROZ_STREAM_REPLAY``)."""
        faults.check("stream.resume")
        with self._lock:
            if self.expired:
                raise ReplayGapError(
                    f"stream {self.request_id!r} already expired its "
                    f"detach grace and was cancelled")
            oldest_needed = from_seq
            if self._ring and oldest_needed < self._ring[0][0]:
                raise ReplayGapError(
                    f"from_seq={from_seq} is older than the replay ring "
                    f"(oldest retained seq {self._ring[0][0]}; raise "
                    f"{REPLAY_ENV} or restart the request)")
            if not self._ring and from_seq < self._next_seq:
                raise ReplayGapError(
                    f"from_seq={from_seq} predates the replay ring")
            backlog = [e for e in self._ring if e[0] >= from_seq]
            self._consumer = (loop, queue)
            self.detached_at = None
            self._cancel_timer_locked()
            self.resumes += 1
        from penroz_tpu.serve import metrics as serve_metrics
        serve_metrics.STREAM_RESUMES.inc()
        STREAMS.note("resumes")
        return backlog

    def try_detach(self) -> bool:
        """Client vanished: keep decoding for the grace window instead of
        cancelling.  Returns False (caller runs the ordinary cancel
        path) when the grace knob is 0 or the stream already ended."""
        grace_ms = detach_grace_ms()
        with self._lock:
            if grace_ms <= 0 or self.terminal or self.expired:
                return False
            self._consumer = None
            self.detached_at = time.monotonic()
            self._cancel_timer_locked()
            self._timer = threading.Timer(grace_ms / 1000.0, self._expire)
            self._timer.daemon = True
            self._timer.start()
        from penroz_tpu.serve import metrics as serve_metrics
        serve_metrics.STREAM_DETACHES.inc()
        STREAMS.note("detaches")
        return True

    def release(self) -> None:
        """Consumer finished reading (terminal event delivered) — drop
        the subscription; the ring lingers for late reconnects."""
        with self._lock:
            self._consumer = None

    def _expire(self):
        with self._lock:
            if self.terminal or self.detached_at is None:
                return
            self.expired = True
            self.detached_at = None
        # The pre-existing cancellation path, deferred by the grace
        # window: the engine observes it at the next emission and
        # retires the row through the audited seam.
        self.req.cancelled = True
        from penroz_tpu.serve import metrics as serve_metrics
        serve_metrics.STREAM_EXPIRED.inc()
        STREAMS.note("expired")
        log.info("stream %s: detach grace expired; generation cancelled",
                 self.request_id)

    def _cancel_timer_locked(self):
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None

    def snapshot(self) -> dict:
        with self._lock:
            return {"request_id": self.request_id,
                    "next_seq": self._next_seq,
                    "ring": len(self._ring),
                    "attached": self._consumer is not None,
                    "detached": self.detached_at is not None,
                    "terminal": self.terminal,
                    "expired": self.expired,
                    "resumes": self.resumes}


class StreamRegistry:
    """Process-wide ``request_id`` → :class:`StreamSession` map.  Shared
    by every replica (engines are in-process), so a reconnect lands on
    the right ring no matter which replica the router steered the
    original request to — the failover case in the acceptance tests."""

    def __init__(self):
        self._lock = threading.Lock()
        self._sessions: dict[str, StreamSession] = {}
        self.detaches = 0
        self.resumes = 0
        self.expired = 0

    def register(self, request_id: str, req) -> StreamSession:
        sess = StreamSession(request_id, req)
        with self._lock:
            self._purge_locked()
            self._sessions[request_id] = sess
        return sess

    def get(self, request_id: str) -> StreamSession | None:
        with self._lock:
            return self._sessions.get(request_id)

    def discard(self, request_id: str) -> None:
        with self._lock:
            sess = self._sessions.pop(request_id, None)
        if sess is not None:
            with sess._lock:
                sess._cancel_timer_locked()

    def _purge_locked(self):
        now = time.monotonic()
        for rid in [rid for rid, s in self._sessions.items()
                    if (s.terminal and s.done_at is not None
                        and now - s.done_at > _LINGER_S) or s.expired]:
            del self._sessions[rid]

    def detached_count(self) -> int:
        with self._lock:
            return sum(1 for s in self._sessions.values()
                       if s.detached_at is not None)

    def stats(self) -> dict:
        with self._lock:
            sessions = list(self._sessions.values())
        detached = sum(1 for s in sessions if s.detached_at is not None)
        return {"active": len(sessions),
                "detached": detached,
                "detaches": self.detaches,
                "resumes": self.resumes,
                "expired": self.expired,
                "replay_capacity": replay_capacity(),
                "detach_grace_ms": detach_grace_ms()}

    def note(self, what: str):
        with self._lock:
            setattr(self, what, getattr(self, what) + 1)

    def reset(self):
        with self._lock:
            for s in self._sessions.values():
                with s._lock:
                    s._cancel_timer_locked()
            self._sessions.clear()
            self.detaches = 0
            self.resumes = 0
            self.expired = 0


STREAMS = StreamRegistry()


def reset() -> None:
    STREAMS.reset()
