"""Write-ahead journal for the serving state plane.

Everything the serving stack must remember across a process restart —
which sessions are hibernated where (serve/tierstore.py), which tenant
quota overrides were PUT, which LoRA adapters were registered — is tiny
host metadata, but until now it lived only in Python dicts: a plain
``kill -9`` turned every CRC-checked disk blob PR 17 wrote into an
unreachable orphan.  This module is the durability substrate: an
append-only, CRC-framed record log that the mutating paths write
*through* and that startup recovery replays.

Wire format (one frame per record, no file header)::

    u32 payload_len (LE) | u32 crc32(payload) | payload (UTF-8 JSON)

A torn tail — the frame a crash interrupted mid-write — fails the
length or CRC check on replay; replay truncates the file at the first
bad frame (everything before it is intact by construction, everything
after it is unordered garbage) and counts what it dropped.  Corruption
is therefore bounded data loss of the most recent record(s), never a
crash and never a wrong replay.

Knobs:

- ``PENROZ_JOURNAL_PATH`` — the log file.  Unset = journaling disabled
  (every hook is a cheap no-op; the stack behaves exactly as before).
- ``PENROZ_JOURNAL_FSYNC`` — ``always`` fsyncs every append (durable to
  the platter, slowest), ``batch`` (default) fsyncs every
  ``_BATCH_EVERY`` records or ``_BATCH_MS`` ms (bounded loss window),
  ``off`` only flushes to the OS page cache (fastest; loss window is
  the kernel writeback interval).
- ``PENROZ_JOURNAL_COMPACT_RATIO`` — rewrite the log (temp file +
  ``os.replace``, same discipline as checkpoint blobs) when dead
  records exceed this fraction of the file (default 0.5, min
  ``_COMPACT_MIN`` records so tiny logs never churn).

Fault sites: ``journal.append`` fires before each frame write,
``journal.replay`` before replay begins — both injectable via
``PENROZ_FAULT_INJECT`` (utils/faults.py).  An append failure (injected
or real ENOSPC) is *contained*: the record is dropped and counted
(``append_errors``), the caller keeps serving — a degraded journal
degrades restart recovery, never live traffic.
"""

from __future__ import annotations

import io
import json
import logging
import os
import struct
import threading
import time
import zlib

from penroz_tpu.utils import faults

log = logging.getLogger(__name__)

PATH_ENV = "PENROZ_JOURNAL_PATH"
FSYNC_ENV = "PENROZ_JOURNAL_FSYNC"          # always | batch | off
COMPACT_RATIO_ENV = "PENROZ_JOURNAL_COMPACT_RATIO"

_FRAME = struct.Struct("<II")               # payload_len, crc32(payload)
_BATCH_EVERY = 64                           # batch policy: fsync every N appends
_BATCH_MS = 100.0                           # ... or this many ms, whichever first
_COMPACT_MIN = 64                           # never compact logs smaller than this


def journal_path() -> str | None:
    return os.environ.get(PATH_ENV) or None


def fsync_policy() -> str:
    pol = os.environ.get(FSYNC_ENV, "batch").strip().lower()
    return pol if pol in ("always", "batch", "off") else "batch"


def _compact_ratio() -> float:
    try:
        return min(1.0, max(0.0, float(
            os.environ.get(COMPACT_RATIO_ENV, 0.5))))
    except ValueError:
        return 0.5


def _encode(record: dict) -> bytes:
    payload = json.dumps(record, separators=(",", ":"),
                         sort_keys=True).encode("utf-8")
    return _FRAME.pack(len(payload), zlib.crc32(payload)) + payload


class Journal:
    """The process-wide write-ahead log.  Thread-safe: engine workers
    (hibernation lifecycle) and API threads (quota/adapter PUTs)
    interleave; one lock serializes frame writes so frames never tear
    each other (a *crash* can still tear the last frame — that is what
    replay truncation is for)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._fh: io.BufferedWriter | None = None
        self._fh_path: str | None = None
        self._pending = 0               # appends since last fsync (batch)
        self._last_fsync = 0.0
        self.records_total = 0          # frames in the current file
        self.appended = 0               # lifetime appends (this process)
        self.append_errors = 0
        self.bad_records = 0            # frames dropped by replay truncation
        self.truncated_bytes = 0
        self.compactions = 0
        self.replay_ms = 0.0

    # -- append path ---------------------------------------------------------

    def enabled(self) -> bool:
        return journal_path() is not None

    def _open_locked(self) -> io.BufferedWriter | None:
        path = journal_path()
        if path is None:
            return None
        if self._fh is not None and self._fh_path == path:
            return self._fh
        self._close_locked()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._fh = open(path, "ab")
        self._fh_path = path
        return self._fh

    def append(self, kind: str, **fields) -> bool:
        """Durably record one state change.  Returns False (and counts)
        instead of raising on any failure — journaling must never make
        serving worse, only restarts better."""
        if not self.enabled():
            return False
        record = dict(fields)
        record["t"] = kind
        record["ts"] = time.time()
        try:
            faults.check("journal.append")
            frame = _encode(record)
            with self._lock:
                fh = self._open_locked()
                if fh is None:
                    return False
                fh.write(frame)
                fh.flush()
                self._fsync_locked(fh)
                self.records_total += 1
                self.appended += 1
        except Exception:  # noqa: BLE001 — contained by design (see docstring)
            with self._lock:
                self.append_errors += 1
            log.warning("journal append failed for %r record (dropped)",
                        kind, exc_info=True)
            from penroz_tpu.serve import metrics as serve_metrics
            serve_metrics.JOURNAL_ERRORS.inc()
            return False
        from penroz_tpu.serve import metrics as serve_metrics
        serve_metrics.JOURNAL_APPENDS.inc()
        return True

    def _fsync_locked(self, fh):
        pol = fsync_policy()
        if pol == "off":
            return
        now = time.monotonic()
        if pol == "always":
            os.fsync(fh.fileno())
            self._last_fsync = now
            self._pending = 0
            return
        self._pending += 1
        if (self._pending >= _BATCH_EVERY
                or (now - self._last_fsync) * 1000.0 >= _BATCH_MS):
            os.fsync(fh.fileno())
            self._last_fsync = now
            self._pending = 0

    def _close_locked(self):
        if self._fh is not None:
            try:
                self._fh.flush()
                if fsync_policy() != "off":
                    os.fsync(self._fh.fileno())
            except OSError:
                pass
            try:
                self._fh.close()
            except OSError:
                pass
        self._fh = None
        self._fh_path = None

    def close(self):
        with self._lock:
            self._close_locked()

    # -- replay path ---------------------------------------------------------

    def replay(self) -> list[dict]:
        """Read every intact record, truncating the file at the first
        bad frame (torn tail / flipped bits).  Raises only for injected
        ``journal.replay`` faults or a filesystem that cannot be read at
        all — the caller treats that as "no journal" and recovers to an
        empty registry."""
        path = journal_path()
        if path is None or not os.path.exists(path):
            return []
        faults.check("journal.replay")
        t0 = time.monotonic()
        records: list[dict] = []
        good_end = 0
        bad = 0
        with self._lock:
            self._close_locked()           # replay owns the file exclusively
            size = os.path.getsize(path)
            with open(path, "rb") as fh:
                while True:
                    head = fh.read(_FRAME.size)
                    if not head:
                        break
                    if len(head) < _FRAME.size:
                        bad += 1
                        break
                    length, crc = _FRAME.unpack(head)
                    payload = fh.read(length)
                    if len(payload) < length or zlib.crc32(payload) != crc:
                        bad += 1
                        break
                    try:
                        records.append(json.loads(payload.decode("utf-8")))
                    except (ValueError, UnicodeDecodeError):
                        bad += 1
                        break
                    good_end = fh.tell()
            if good_end < size:
                # Torn tail: drop it on the floor *in the file too*, so
                # the next append starts a clean frame boundary.
                with open(path, "r+b") as fh:
                    fh.truncate(good_end)
                self.truncated_bytes += size - good_end
                self.bad_records += max(1, bad)
                from penroz_tpu.serve import metrics as serve_metrics
                serve_metrics.JOURNAL_BAD.inc(max(1, bad))
                log.warning(
                    "journal replay: truncated %d torn byte(s) at offset %d "
                    "of %s (%d bad frame(s) dropped)",
                    size - good_end, good_end, path, max(1, bad))
            self.records_total = len(records)
            self.replay_ms = (time.monotonic() - t0) * 1000.0
        return records

    # -- compaction ----------------------------------------------------------

    def should_compact(self, live_records: int) -> bool:
        """Dead-ratio trigger: worth rewriting once more than
        ``PENROZ_JOURNAL_COMPACT_RATIO`` of the frames describe state
        that no longer exists (dropped sessions, superseded quota rows)."""
        with self._lock:
            total = self.records_total
        if total < _COMPACT_MIN or not self.enabled():
            return False
        dead = max(0, total - live_records)
        return dead / total > _compact_ratio()

    def compact(self, live_records: list[dict]) -> bool:
        """Rewrite the log to exactly ``live_records`` via temp file +
        ``os.replace`` — a crash mid-compaction leaves the old log
        intact (plus a swept-at-startup temp file), never a half log."""
        path = journal_path()
        if path is None:
            return False
        tmp = f"{path}.compact.tmp"
        try:
            with self._lock:
                self._close_locked()
                with open(tmp, "wb") as fh:
                    for rec in live_records:
                        fh.write(_encode(rec))
                    fh.flush()
                    if fsync_policy() != "off":
                        os.fsync(fh.fileno())
                os.replace(tmp, path)
                self.records_total = len(live_records)
                self.compactions += 1
        except OSError:
            log.warning("journal compaction failed (keeping old log)",
                        exc_info=True)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return False
        from penroz_tpu.serve import metrics as serve_metrics
        serve_metrics.JOURNAL_COMPACTIONS.inc()
        return True

    # -- introspection / tests ----------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled(),
                "fsync": fsync_policy(),
                "records": self.records_total,
                "appended": self.appended,
                "append_errors": self.append_errors,
                "bad_records": self.bad_records,
                "truncated_bytes": self.truncated_bytes,
                "compactions": self.compactions,
                "replay_ms": round(self.replay_ms, 3),
            }

    def reset(self):
        """Test/bench hook: close the handle and zero counters.  Does
        NOT delete the file — tests that want a clean log point
        ``PENROZ_JOURNAL_PATH`` at a fresh tmp path instead."""
        with self._lock:
            self._close_locked()
            self._pending = 0
            self._last_fsync = 0.0
            self.records_total = 0
            self.appended = 0
            self.append_errors = 0
            self.bad_records = 0
            self.truncated_bytes = 0
            self.compactions = 0
            self.replay_ms = 0.0


JOURNAL = Journal()


def reset() -> None:
    JOURNAL.reset()
