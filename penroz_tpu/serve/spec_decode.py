"""Draft-free speculative decoding: prompt-lookup drafts + acceptance.

Every decode step without speculation advances a row by exactly one token
— inter-token latency is pinned to one full forward dispatch per token no
matter how predictable the text is.  Prompt lookup (the "n-gram copy"
drafter: arXiv:2304.04487-adjacent, no second model) attacks the highly
predictable case directly: if the trailing ``n``-gram of a row's context
(prompt + generated tokens) occurred earlier in that same context, the
tokens that followed it are proposed as a draft, and the scheduler's
**verify step** runs ONE forward over the K+1 candidate positions
(``NeuralNetworkModel.decode_verify_row``), accepting the longest
target-matching prefix plus the model's bonus token.  Rejections roll the
row's KV length back (``KVState.rollback_row``), so a wrong draft costs
one multi-token forward instead of wrong output — results are
token-identical to speculation off by construction.

Sampling (temperature > 0) is exact rejection sampling, for free: the
unified engine samples every packed slot with a **positional key**
(``fold_in(fold_in(rng, row), position)`` —
``CompiledArch._sample_packed``), so the target token at (row, position)
is one deterministic draw ``t ~ p_target`` no matter which dispatch
carries the slot.  Prompt-lookup drafts are point masses (q = δ_draft),
and for a point-mass proposal the textbook accept/resample rule
["accept ``d`` w.p. min(1, p(d)/q(d)); else resample the residual
max(0, p − q)/Z"] collapses to exactly "sample ``t ~ p``, accept iff
``t == d``, else emit ``t``": acceptance probability is p(d), and the
rejected-slot token is distributed p(t)/(1 − p(d)) on t ≠ d — the
residual.  That is precisely the longest-matching-prefix comparison the
greedy path already runs, applied to the positionally-keyed samples
instead of the argmax.  Spec-on therefore emits the byte-identical
stream to spec-off at any temperature (pinned by the seeded parity
test); the greedy path's argmax comparison is untouched.  The legacy
phased engine keeps the greedy-only gate — it samples with
dispatch-order keys, which verify dispatches would perturb.

Knobs::

    PENROZ_SPEC_DECODE=1   enable (greedy engines; non-greedy too on
                           the unified ragged scheduler path)
    PENROZ_SPEC_K          max draft tokens per verify step (default 4)
    PENROZ_SPEC_NGRAM      trailing-match length (default 3)

This module is pure host-side policy (which tokens to propose, how many
matched); the device work lives in models/model.py (verify dispatch) and
ops/kv_cache.py (multi-token appends + rollback).
"""

from __future__ import annotations

import logging
import os

log = logging.getLogger(__name__)

ENABLE_ENV = "PENROZ_SPEC_DECODE"
K_ENV = "PENROZ_SPEC_K"
NGRAM_ENV = "PENROZ_SPEC_NGRAM"


def enabled() -> bool:
    return os.environ.get(ENABLE_ENV, "0") == "1"


def _env_int(name: str, default: int) -> int:
    try:
        return max(1, int(os.environ.get(name, str(default))))
    except ValueError:
        log.warning("Unparseable %s=%r; using default %d", name,
                    os.environ.get(name), default)
        return default


def draft_k() -> int:
    """Max draft tokens proposed per verify step (``PENROZ_SPEC_K``)."""
    return _env_int(K_ENV, 4)


def ngram() -> int:
    """Trailing-n-gram match length (``PENROZ_SPEC_NGRAM``)."""
    return _env_int(NGRAM_ENV, 3)


def propose(history, k: int, n: int) -> list[int]:
    """Up to ``k`` draft tokens for the next positions of ``history``
    (prompt + generated so far) by prompt lookup: find the most recent
    *earlier* occurrence of the trailing ``n``-gram and propose the
    tokens that followed it.  Returns ``[]`` when nothing matched.

    The draft is truncated to a power-of-two length so the jitted
    verify-program set stays bounded (T = len+1 ∈ {2, 3, 5, 9, …} per
    cache type), mirroring the prefill chunk-plan bucketing.  The scan is
    O(len(history) · n) per call — host-side, off the device hot path,
    and bounded by block_size at serving scale.
    """
    L = len(history)
    if k < 1 or L <= n:
        return []
    pattern = list(history[-n:])
    for i in range(L - n - 1, -1, -1):
        if list(history[i:i + n]) == pattern:
            cont = history[i + n:i + n + k]
            if not cont:
                return []
            keep = 1 << (len(cont).bit_length() - 1)
            return [int(t) for t in cont[:keep]]
    return []


def accept_length(draft, out) -> int:
    """Number of draft tokens accepted: ``draft[j]`` is accepted iff it
    equals ``out[j]`` — the model's (greedy) token after consuming
    positions ≤ j — and every earlier draft token was accepted.  The
    scheduler then emits ``out[:accepted + 1]``: the accepted tokens plus
    the model's bonus token at the first divergent position, exactly the
    sequence ``accepted + 1`` plain decode steps would have produced."""
    a = 0
    for d, o in zip(draft, out):
        if int(d) != int(o):
            break
        a += 1
    return a
