"""Hierarchical KV tier store: session hibernation below the HBM radix cache.

The radix prefix cache (ops/kv_cache.py) keeps whole-page KV for *recent*
prompts in a reserved region of the paged pool — but HBM is the scarcest
tier there is, and production chat traffic is millions of sessions that
are idle between turns.  This module generalizes that cache into a
three-tier page store:

    HBM radix cache  →  pinned host-RAM blob cache  →  disk/shm blob store
    (reserved pool       (``PENROZ_TIER_HOST_MB``)      (``PENROZ_TIER_DISK_PATH``
     region, fast                                        / ``PENROZ_TIER_DISK_MB``)
     aliasing)

Lifecycle of a hibernated session (serve/decode_scheduler.py drives it):

1. **Hibernate** — a retirement carrying a ``session_id`` inserts the row's
   full prompt+generated history into the radix cache (the preempt-to-
   prefix-cache template) and *pins* the chain under a hibernation hold;
   the ledger counts those pages ``hibernating``.  Registration here is
   cheap host bookkeeping — the retirement hot path never exports.
2. **Demote** (async, off the hot path) — the engine worker drains its
   demotion queue at loop boundaries: pages are exported to a host blob
   (``export_pages``), the hold is unpinned (the pages stay radix-resident
   and *evictable*, so resume is still HBM-fast until LRU pressure takes
   them), and the session's tier becomes ``host``.  Host-cap overflow
   spills LRU host blobs to the disk tier (CRC container via
   utils/checkpoint.py); disk-cap overflow drops LRU sessions entirely.
3. **Promote on match** — an admission whose prompt's page fingerprints
   hit a hibernated session imports the blob's pages into freshly
   ``insert()``-created radix slots (``import_pages``) and aliases them
   like a normal radix hit; the un-hibernated suffix chunk-prefills as
   usual.  Content-addressed: no ``session_id`` needed to wake, so a
   session hibernated on one replica wakes on any other — and, for the
   disk tier, across ``decode_scheduler.reset()`` / engine restarts.

The store is PROCESS-WIDE (one instance, like qos.QUOTAS): every engine
replica registers into and promotes from the same tiers.  A session
hibernated by a breaker-open or since-reset replica therefore stays
wakeable as long as its blob has left HBM.  Model reloads are fenced by a
per-session checkpoint stamp — a stale session is dropped at match time,
never served.

Corruption policy: a disk blob that fails CRC/container validation is a
*miss* (``penroz_tier_corrupt_blobs_total``), never an error or wrong
tokens — the admission recomputes.

Per-tenant residency quotas ride the QoS machinery
(``PENROZ_QOS_TENANT_TIER_MB`` + ``PUT /tenants/{id}/quota`` overrides):
a hibernation that would put the tenant over cap evicts that tenant's LRU
sessions first and is refused if the new session alone cannot fit.
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import time

log = logging.getLogger(__name__)

HOST_MB_ENV = "PENROZ_TIER_HOST_MB"
DISK_MB_ENV = "PENROZ_TIER_DISK_MB"

_DEFAULT_HOST_MB = 64.0
_DEFAULT_DISK_MB = 256.0

TIERS_ALL = ("hbm", "host", "disk")

#: Promotion outcomes (the ``penroz_tier_promotions_total`` outcome label
#: values): ``ok`` full wake, ``partial`` radix alloc exhausted mid-import,
#: ``stale`` model stamp changed since hibernation, ``corrupt`` disk blob
#: failed CRC, ``miss`` blob vanished.
OUTCOMES = ("ok", "partial", "stale", "corrupt", "miss")


def _env_mb(name: str, default: float) -> float:
    try:
        return max(0.0, float(os.environ.get(name, default)))
    except ValueError:
        return float(default)


def host_cap_bytes() -> int:
    return int(_env_mb(HOST_MB_ENV, _DEFAULT_HOST_MB) * 1e6)


def disk_cap_bytes() -> int:
    return int(_env_mb(DISK_MB_ENV, _DEFAULT_DISK_MB) * 1e6)


class _Session:
    """One hibernated session's residency record.  ``tier`` names the
    DEEPEST copy ("hbm" = pinned radix pages awaiting demotion, "host" =
    blob in the host cache, "disk" = blob on disk); the radix cache may
    still hold the pages after demotion, which just makes resume cheaper.
    ``owner`` identifies the engine holding the pinned pages while tier
    is "hbm" (``id(engine)``) — a crash/reset of that engine drops the
    record via :meth:`TierStore.drop_owner` because the pages died with
    the pool."""

    __slots__ = ("session_id", "tenant", "model_id", "model_stamp",
                 "tokens", "kv_len", "page_size", "quantized", "nbytes",
                 "tier", "owner", "replica", "created", "last_use", "fps")

    def __init__(self, session_id, tenant, model_id, model_stamp, tokens,
                 kv_len, page_size, quantized, nbytes, owner, replica, fps):
        self.session_id = session_id
        self.tenant = tenant
        self.model_id = model_id
        self.model_stamp = model_stamp
        self.tokens = tokens
        self.kv_len = int(kv_len)
        self.page_size = int(page_size)
        self.quantized = bool(quantized)
        self.nbytes = int(nbytes)
        self.tier = "hbm"
        self.owner = owner
        self.replica = replica
        self.created = time.time()
        self.last_use = self.created
        self.fps = fps

    @property
    def pages(self) -> int:
        return self.kv_len // self.page_size


def _fingerprints(tokens, page_size: int, max_pages: int) -> list:
    """Rolling page-aligned prefix fingerprints, shortest first —
    ``fps[k-1]`` covers the first ``k`` full pages.  Same hash chain as
    the router's affinity index (serve/router.py), so both indexes agree
    on what "the same prefix" means."""
    fps, h = [], 0
    for k in range(min(max_pages, len(tokens) // page_size)):
        h = hash((h, tuple(int(t) for t in
                           tokens[k * page_size:(k + 1) * page_size])))
        fps.append(h)
    return fps


class TierStore:
    """Process-wide registry of hibernated sessions + the host/disk blob
    tiers.  Thread-safe: engine workers (register/demote/promote) and API
    threads (list/delete) interleave freely.  Holds no engine references
    — engines push state in and look content up, so the store survives
    any engine's crash, reload, or ``decode_scheduler.reset()``."""

    def __init__(self):
        self._lock = threading.RLock()
        # session_id -> _Session, LRU order (move_to_end on touch)
        self._sessions: collections.OrderedDict = collections.OrderedDict()
        # session_id -> host-tier blob dict (pinned host RAM)
        self._host: dict = {}
        # (model_id, page_size, quantized, fp) -> {session_id: depth}
        # One entry per covered page depth per session: a prompt that
        # agrees with a session for only k of its pages still finds it.
        self._index: dict = {}
        self.hibernated = 0              # lifetime registrations
        self.demotions = collections.Counter()    # tier -> count
        self.promotions = collections.Counter()   # (tier, outcome) -> count
        self.corrupt_blobs = 0
        self.drops = collections.Counter()        # reason -> count

    # -- registration / demotion --------------------------------------------

    def _index_add(self, rec: _Session):
        for depth, fp in enumerate(rec.fps, start=1):
            key = (rec.model_id, rec.page_size, rec.quantized, fp)
            self._index.setdefault(key, {})[rec.session_id] = depth

    def _index_remove(self, rec: _Session):
        for fp in rec.fps:
            key = (rec.model_id, rec.page_size, rec.quantized, fp)
            bucket = self._index.get(key)
            if bucket is not None:
                bucket.pop(rec.session_id, None)
                if not bucket:
                    del self._index[key]

    def _tenant_bytes_locked(self, tenant: str) -> int:
        return sum(r.nbytes for r in self._sessions.values()
                   if r.tenant == tenant)

    def register(self, session_id: str, *, tenant, model_id, model_stamp,
                 tokens, kv_len, page_size, quantized, nbytes, owner,
                 replica) -> bool:
        """Record a freshly hibernated session (tier "hbm": the engine
        still holds its pinned radix pages).  Re-registering an existing
        ``session_id`` replaces it — a multi-turn session's next
        retirement supersedes the previous hibernation.  Enforces the
        tenant's tier quota by evicting that tenant's LRU sessions;
        returns False (nothing registered) when even that cannot fit the
        new session."""
        from penroz_tpu.serve import qos
        tokens = tuple(int(t) for t in tokens)
        pages = int(kv_len) // int(page_size)
        if pages < 1:
            return False
        fps = _fingerprints(tokens, int(page_size), pages)
        with self._lock:
            old = self._sessions.get(session_id)
            if old is not None:
                self._drop_locked(old, "replaced")
            cap = qos.QUOTAS.tier_bytes_for(tenant)
            if cap > 0:
                if int(nbytes) > cap:
                    self.drops["quota_refused"] += 1
                    return False
                while (self._tenant_bytes_locked(tenant) + int(nbytes) > cap):
                    victim = next((r for r in self._sessions.values()
                                   if r.tenant == tenant), None)
                    if victim is None:
                        break
                    self._drop_locked(victim, "quota")
            rec = _Session(session_id, tenant, model_id, model_stamp,
                           tokens, kv_len, page_size, quantized, nbytes,
                           owner, replica, fps)
            self._sessions[session_id] = rec
            self._index_add(rec)
            self.hibernated += 1
        from penroz_tpu.serve import metrics as serve_metrics
        serve_metrics.SESSIONS_HIBERNATED.inc()
        return True

    def demote_to_host(self, session_id: str, blob: dict) -> bool:
        """Land a demoted session's blob in the host tier (the engine
        worker just ran ``export_pages`` off the hot path) and rebalance
        the lower tiers: host-cap overflow spills LRU host blobs to disk,
        disk-cap overflow drops LRU disk sessions."""
        from penroz_tpu.serve import metrics as serve_metrics
        from penroz_tpu.utils import checkpoint
        with self._lock:
            rec = self._sessions.get(session_id)
            if rec is None or rec.tier != "hbm":
                return False
            rec.tier = "host"
            rec.owner = None
            rec.nbytes = checkpoint.page_blob_nbytes(blob)
            self._host[session_id] = blob
            self.demotions["host"] += 1
            serve_metrics.TIER_DEMOTIONS.inc(tier="host")
            self._enforce_caps_locked()
        return True

    def _tier_bytes_locked(self, tier: str) -> int:
        return sum(r.nbytes for r in self._sessions.values()
                   if r.tier == tier)

    def _lru_locked(self, tier: str):
        return next((r for r in self._sessions.values() if r.tier == tier),
                    None)

    def _enforce_caps_locked(self):
        from penroz_tpu.serve import metrics as serve_metrics
        from penroz_tpu.utils import checkpoint
        host_cap = host_cap_bytes()
        while self._tier_bytes_locked("host") > host_cap:
            rec = self._lru_locked("host")
            if rec is None:
                break
            blob = self._host.pop(rec.session_id)
            try:
                checkpoint.save_tier_blob(rec.session_id, blob)
            except OSError:
                log.warning("disk-tier write failed; dropping session %s",
                            rec.session_id, exc_info=True)
                self._drop_locked(rec, "disk_write_failed")
                continue
            rec.tier = "disk"
            rec.nbytes = checkpoint.tier_blob_nbytes(rec.session_id)
            self.demotions["disk"] += 1
            serve_metrics.TIER_DEMOTIONS.inc(tier="disk")
        disk_cap = disk_cap_bytes()
        while self._tier_bytes_locked("disk") > disk_cap:
            rec = self._lru_locked("disk")
            if rec is None:
                break
            self._drop_locked(rec, "disk_cap")

    # -- lookup / promotion --------------------------------------------------

    def match(self, tokens, *, model_id, model_stamp, page_size, quantized,
              min_pages: int = 1):
        """Deepest hibernated session agreeing with ``tokens``' whole-page
        prefix: returns ``(record, depth_pages)`` or ``(None, 0)``.  The
        usable token count is capped at ``len(tokens) - 1`` (the radix
        match rule: one real token must remain to produce first-sample
        logits).  Sessions hibernated under a different model stamp
        (weights reloaded since) are dropped on sight — stale KV is never
        served.  Fingerprint candidates are verified token-for-token, so
        a hash collision degrades to a miss, not a wrong alias."""
        if not self._sessions:
            return None, 0
        P = int(page_size)
        max_pages = max(0, (len(tokens) - 1) // P)
        if max_pages < min_pages:
            return None, 0
        toks = tuple(int(t) for t in tokens)
        fps = _fingerprints(toks, P, max_pages)
        with self._lock:
            for depth in range(len(fps), max(0, min_pages - 1), -1):
                key = (model_id, P, bool(quantized), fps[depth - 1])
                bucket = self._index.get(key)
                if not bucket:
                    continue
                for sid in list(bucket):
                    rec = self._sessions.get(sid)
                    if rec is None:
                        bucket.pop(sid, None)
                        continue
                    if rec.model_stamp != model_stamp:
                        self.note_promotion(rec.tier, "stale")
                        self._drop_locked(rec, "stale_model")
                        continue
                    span = depth * P
                    if rec.kv_len >= span and rec.tokens[:span] == toks[:span]:
                        self.touch(sid)
                        return rec, depth
            return None, 0

    def placement(self, tokens, *, model_id, page_size: int):
        """Router-side placement hint: the deepest token-verified resident
        session for ``tokens``' whole-page prefix, with NO side effects —
        no LRU touch, no promotion counters, no stamp fence (the router
        does not know each replica's checkpoint stamp; the engine-side
        promote still enforces it).  Both quantization variants are
        scanned — steering is per-model, not per-pool-layout.  Returns
        the record or None."""
        P = int(page_size)
        max_pages = max(0, (len(tokens) - 1) // P)
        if max_pages < 1 or not self._sessions:
            return None
        toks = tuple(int(t) for t in tokens)
        fps = _fingerprints(toks, P, max_pages)
        with self._lock:
            for depth in range(len(fps), 0, -1):
                for quantized in (False, True):
                    key = (model_id, P, quantized, fps[depth - 1])
                    bucket = self._index.get(key)
                    if not bucket:
                        continue
                    span = depth * P
                    for sid in bucket:
                        rec = self._sessions.get(sid)
                        if (rec is not None and rec.kv_len >= span
                                and rec.tokens[:span] == toks[:span]):
                            return rec
            return None

    def fetch(self, session_id: str):
        """The session's blob for promotion, or None (with the record
        dropped and the corrupt/miss counters bumped) when the copy is
        unreadable.  Tier "hbm" has no blob yet — the pages only exist in
        the owning engine's radix cache — so a cross-replica wake before
        demotion completes is also a None (the caller recomputes)."""
        from penroz_tpu.serve import metrics as serve_metrics
        from penroz_tpu.utils import checkpoint
        with self._lock:
            rec = self._sessions.get(session_id)
            if rec is None:
                return None
            if rec.tier == "hbm":
                return None
            if rec.tier == "host":
                return self._host.get(session_id)
            try:
                return checkpoint.load_tier_blob(session_id)
            except ValueError:
                self.corrupt_blobs += 1
                serve_metrics.TIER_CORRUPT.inc()
                self.note_promotion("disk", "corrupt")
                self._drop_locked(rec, "corrupt")
                return None
            except KeyError:
                self.note_promotion("disk", "miss")
                self._drop_locked(rec, "blob_missing")
                return None

    def note_promotion(self, tier: str, outcome: str):
        from penroz_tpu.serve import metrics as serve_metrics
        with self._lock:
            self.promotions[(tier, outcome)] += 1
        serve_metrics.TIER_PROMOTIONS.inc(tier=tier, outcome=outcome)

    def touch(self, session_id: str):
        with self._lock:
            rec = self._sessions.get(session_id)
            if rec is not None:
                rec.last_use = time.time()
                self._sessions.move_to_end(session_id)

    # -- removal -------------------------------------------------------------

    def _drop_locked(self, rec: _Session, reason: str):
        from penroz_tpu.utils import checkpoint
        self._sessions.pop(rec.session_id, None)
        self._host.pop(rec.session_id, None)
        if rec.tier == "disk":
            checkpoint.delete_tier_blob(rec.session_id)
        self._index_remove(rec)
        self.drops[reason] += 1

    def drop(self, session_id: str, reason: str = "api") -> bool:
        """Evict one session from every tier (``DELETE /sessions/{id}``).
        A tier-"hbm" record's pinned pages are released by the owning
        engine when its demotion queue reaches the now-unregistered id."""
        with self._lock:
            rec = self._sessions.get(session_id)
            if rec is None:
                return False
            self._drop_locked(rec, reason)
            return True

    def drop_owner(self, owner, reason: str = "engine_reset") -> int:
        """Drop every tier-"hbm" session pinned by engine ``owner`` — its
        pool (and the pinned pages) just died in a crash-recovery
        reallocation, reload, or shutdown.  Host/disk-tier sessions
        survive: their bytes left HBM already."""
        with self._lock:
            victims = [r for r in self._sessions.values()
                       if r.tier == "hbm" and r.owner == owner]
            for rec in victims:
                self._drop_locked(rec, reason)
            return len(victims)

    # -- introspection -------------------------------------------------------

    def get(self, session_id: str):
        with self._lock:
            return self._sessions.get(session_id)

    def resident_sessions(self) -> int:
        return len(self._sessions)

    def sessions_by_tier(self) -> dict:
        with self._lock:
            out = {t: 0 for t in TIERS_ALL}
            for rec in self._sessions.values():
                out[rec.tier] += 1
            return out

    def pages_by_tier(self) -> dict:
        with self._lock:
            out = {t: 0 for t in TIERS_ALL}
            for rec in self._sessions.values():
                out[rec.tier] += rec.pages
            return out

    def tier_bytes(self) -> dict:
        """Bytes held OUTSIDE the paged pool, per lower tier (tier-"hbm"
        sessions live in pool pages the memledger already counts as
        ``hibernating``, so they are excluded here — no double count)."""
        with self._lock:
            return {"host_tier": self._tier_bytes_locked("host"),
                    "disk_tier": self._tier_bytes_locked("disk")}

    def list_sessions(self) -> list:
        now = time.time()
        with self._lock:
            return [{
                "session_id": r.session_id,
                "tenant": r.tenant,
                "model_id": r.model_id,
                "tier": r.tier,
                "tokens": r.kv_len,
                "pages": r.pages,
                "nbytes": r.nbytes,
                "replica": r.replica,
                "age_s": max(0.0, now - r.created),
                "idle_s": max(0.0, now - r.last_use),
            } for r in self._sessions.values()]

    def stats(self) -> dict:
        with self._lock:
            promos: collections.Counter = collections.Counter()
            for (_, outcome), n in self.promotions.items():
                promos[outcome] += n
            return {
                "sessions_resident": len(self._sessions),
                "sessions_by_tier": {t: sum(1 for r in self._sessions.values()
                                            if r.tier == t)
                                     for t in TIERS_ALL},
                "tier_bytes": {"host_tier": self._tier_bytes_locked("host"),
                               "disk_tier": self._tier_bytes_locked("disk")},
                "tier_promotions": {o: promos.get(o, 0) for o in OUTCOMES},
                "tier_demotions": {t: self.demotions.get(t, 0)
                                   for t in ("host", "disk")},
                "tier_corrupt_blobs": self.corrupt_blobs,
            }

    def reset(self):
        """Test/bench hook: drop every session (disk files included) and
        zero the lifetime counters."""
        with self._lock:
            for rec in list(self._sessions.values()):
                self._drop_locked(rec, "reset")
            self._sessions.clear()
            self._host.clear()
            self._index.clear()
            self.hibernated = 0
            self.demotions.clear()
            self.promotions.clear()
            self.corrupt_blobs = 0
            self.drops.clear()


TIERS = TierStore()


def reset() -> None:
    TIERS.reset()
