"""Hierarchical KV tier store: session hibernation below the HBM radix cache.

The radix prefix cache (ops/kv_cache.py) keeps whole-page KV for *recent*
prompts in a reserved region of the paged pool — but HBM is the scarcest
tier there is, and production chat traffic is millions of sessions that
are idle between turns.  This module generalizes that cache into a
three-tier page store:

    HBM radix cache  →  pinned host-RAM blob cache  →  disk/shm blob store
    (reserved pool       (``PENROZ_TIER_HOST_MB``)      (``PENROZ_TIER_DISK_PATH``
     region, fast                                        / ``PENROZ_TIER_DISK_MB``)
     aliasing)

Lifecycle of a hibernated session (serve/decode_scheduler.py drives it):

1. **Hibernate** — a retirement carrying a ``session_id`` inserts the row's
   full prompt+generated history into the radix cache (the preempt-to-
   prefix-cache template) and *pins* the chain under a hibernation hold;
   the ledger counts those pages ``hibernating``.  Registration here is
   cheap host bookkeeping — the retirement hot path never exports.
2. **Demote** (async, off the hot path) — the engine worker drains its
   demotion queue at loop boundaries: pages are exported to a host blob
   (``export_pages``), the hold is unpinned (the pages stay radix-resident
   and *evictable*, so resume is still HBM-fast until LRU pressure takes
   them), and the session's tier becomes ``host``.  Host-cap overflow
   spills LRU host blobs to the disk tier (CRC container via
   utils/checkpoint.py); disk-cap overflow drops LRU sessions entirely.
3. **Promote on match** — an admission whose prompt's page fingerprints
   hit a hibernated session imports the blob's pages into freshly
   ``insert()``-created radix slots (``import_pages``) and aliases them
   like a normal radix hit; the un-hibernated suffix chunk-prefills as
   usual.  Content-addressed: no ``session_id`` needed to wake, so a
   session hibernated on one replica wakes on any other — and, for the
   disk tier, across ``decode_scheduler.reset()`` / engine restarts.

The store is PROCESS-WIDE (one instance, like qos.QUOTAS): every engine
replica registers into and promotes from the same tiers.  A session
hibernated by a breaker-open or since-reset replica therefore stays
wakeable as long as its blob has left HBM.  Model reloads are fenced by a
per-session checkpoint stamp — a stale session is dropped at match time,
never served.

Corruption policy: a disk blob that fails CRC/container validation is a
*miss* (``penroz_tier_corrupt_blobs_total``), never an error or wrong
tokens — the admission recomputes.

Per-tenant residency quotas ride the QoS machinery
(``PENROZ_QOS_TENANT_TIER_MB`` + ``PUT /tenants/{id}/quota`` overrides):
a hibernation that would put the tenant over cap evicts that tenant's LRU
sessions first and is refused if the new session alone cannot fit.

Durability: the registry is JOURNAL-BACKED when ``PENROZ_JOURNAL_PATH``
is set (serve/journal.py) — every register/demote/promote/drop appends a
CRC-framed record, and :meth:`TierStore.recover` (run once at app
startup) replays the journal, cross-checks it against a scan of the
disk tier (header-validate blobs, fence stale model stamps, sweep
unreferenced blobs and torn temp files), and re-admits the disk-tier
sessions — so hibernated sessions survive ``kill -9`` and resume from
disk instead of cold.  HBM- and host-tier copies are volatile by
design: only bytes that reached the disk tier outlive the process.
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import time

log = logging.getLogger(__name__)

HOST_MB_ENV = "PENROZ_TIER_HOST_MB"
DISK_MB_ENV = "PENROZ_TIER_DISK_MB"

_DEFAULT_HOST_MB = 64.0
_DEFAULT_DISK_MB = 256.0

TIERS_ALL = ("hbm", "host", "disk")

#: Promotion outcomes (the ``penroz_tier_promotions_total`` outcome label
#: values): ``ok`` full wake, ``partial`` radix alloc exhausted mid-import,
#: ``stale`` model stamp changed since hibernation, ``corrupt`` disk blob
#: failed CRC, ``miss`` blob vanished.
OUTCOMES = ("ok", "partial", "stale", "corrupt", "miss")


def _env_mb(name: str, default: float) -> float:
    try:
        return max(0.0, float(os.environ.get(name, default)))
    except ValueError:
        return float(default)


def host_cap_bytes() -> int:
    return int(_env_mb(HOST_MB_ENV, _DEFAULT_HOST_MB) * 1e6)


def disk_cap_bytes() -> int:
    return int(_env_mb(DISK_MB_ENV, _DEFAULT_DISK_MB) * 1e6)


class _Session:
    """One hibernated session's residency record.  ``tier`` names the
    DEEPEST copy ("hbm" = pinned radix pages awaiting demotion, "host" =
    blob in the host cache, "disk" = blob on disk); the radix cache may
    still hold the pages after demotion, which just makes resume cheaper.
    ``owner`` identifies the engine holding the pinned pages while tier
    is "hbm" (``id(engine)``) — a crash/reset of that engine drops the
    record via :meth:`TierStore.drop_owner` because the pages died with
    the pool."""

    __slots__ = ("session_id", "tenant", "model_id", "model_stamp",
                 "tokens", "kv_len", "page_size", "quantized", "nbytes",
                 "tier", "owner", "replica", "created", "last_use", "fps")

    def __init__(self, session_id, tenant, model_id, model_stamp, tokens,
                 kv_len, page_size, quantized, nbytes, owner, replica, fps):
        self.session_id = session_id
        self.tenant = tenant
        self.model_id = model_id
        self.model_stamp = model_stamp
        self.tokens = tokens
        self.kv_len = int(kv_len)
        self.page_size = int(page_size)
        self.quantized = bool(quantized)
        self.nbytes = int(nbytes)
        self.tier = "hbm"
        self.owner = owner
        self.replica = replica
        self.created = time.time()
        self.last_use = self.created
        self.fps = fps

    @property
    def pages(self) -> int:
        return self.kv_len // self.page_size


def _fingerprints(tokens, page_size: int, max_pages: int) -> list:
    """Rolling page-aligned prefix fingerprints, shortest first —
    ``fps[k-1]`` covers the first ``k`` full pages.  Same hash chain as
    the router's affinity index (serve/router.py), so both indexes agree
    on what "the same prefix" means."""
    fps, h = [], 0
    for k in range(min(max_pages, len(tokens) // page_size)):
        h = hash((h, tuple(int(t) for t in
                           tokens[k * page_size:(k + 1) * page_size])))
        fps.append(h)
    return fps


class TierStore:
    """Process-wide registry of hibernated sessions + the host/disk blob
    tiers.  Thread-safe: engine workers (register/demote/promote) and API
    threads (list/delete) interleave freely.  Holds no engine references
    — engines push state in and look content up, so the store survives
    any engine's crash, reload, or ``decode_scheduler.reset()``."""

    def __init__(self):
        self._lock = threading.RLock()
        # session_id -> _Session, LRU order (move_to_end on touch)
        self._sessions: collections.OrderedDict = collections.OrderedDict()
        # session_id -> host-tier blob dict (pinned host RAM)
        self._host: dict = {}
        # (model_id, page_size, quantized, fp) -> {session_id: depth}
        # One entry per covered page depth per session: a prompt that
        # agrees with a session for only k of its pages still finds it.
        self._index: dict = {}
        self.hibernated = 0              # lifetime registrations
        self.demotions = collections.Counter()    # tier -> count
        self.promotions = collections.Counter()   # (tier, outcome) -> count
        self.corrupt_blobs = 0
        self.drops = collections.Counter()        # reason -> count
        self.last_recovery: dict = {}    # recover() summary (startup)
        self._replaying = False          # recover() must not re-journal

    # -- write-ahead journal --------------------------------------------------

    def _journal(self, kind: str, **fields):
        """Best-effort WAL append for one registry mutation (no-op while
        the journal is disabled or recovery itself is replaying)."""
        from penroz_tpu.serve import journal
        if self._replaying or not journal.JOURNAL.enabled():
            return
        journal.JOURNAL.append(kind, **fields)

    def _maybe_compact_locked(self):
        from penroz_tpu.serve import journal
        if self._replaying:
            return
        # Cheap live-count upper bound first; the snapshot walk only runs
        # when the dead ratio actually trips.
        if journal.JOURNAL.should_compact(self._live_record_count_locked()):
            journal.JOURNAL.compact(self._snapshot_records_locked())

    def _live_record_count_locked(self) -> int:
        from penroz_tpu.serve import qos
        return (len(self._sessions) + len(qos.QUOTAS.overrides())
                + len(qos.QUOTAS.tier_overrides()))

    def _snapshot_records_locked(self) -> list:
        """The current registry + override state as journal records — what
        compaction rewrites the log down to.  Adapter registrations are
        re-derived from their (already durable) checkpoints."""
        from penroz_tpu.serve import qos
        from penroz_tpu.utils import checkpoint
        recs = []
        for r in self._sessions.values():
            recs.append({"t": "register", "ts": r.created,
                         "session_id": r.session_id, "tenant": r.tenant,
                         "model_id": r.model_id,
                         "model_stamp": r.model_stamp,
                         "tokens": [int(t) for t in r.tokens],
                         "kv_len": r.kv_len, "page_size": r.page_size,
                         "quantized": r.quantized, "nbytes": r.nbytes,
                         "replica": r.replica, "tier": r.tier})
        now = time.time()
        for tenant, rate in qos.QUOTAS.overrides().items():
            recs.append({"t": "quota", "ts": now, "tenant": tenant,
                         "rate": rate})
        for tenant, mb in qos.QUOTAS.tier_overrides().items():
            recs.append({"t": "quota", "ts": now, "tenant": tenant,
                         "tier_mb": mb})
        for aid in checkpoint.list_adapter_ids():
            recs.append({"t": "adapter", "ts": now, "adapter_id": aid})
        return recs

    # -- registration / demotion --------------------------------------------

    def _index_add(self, rec: _Session):
        for depth, fp in enumerate(rec.fps, start=1):
            key = (rec.model_id, rec.page_size, rec.quantized, fp)
            self._index.setdefault(key, {})[rec.session_id] = depth

    def _index_remove(self, rec: _Session):
        for fp in rec.fps:
            key = (rec.model_id, rec.page_size, rec.quantized, fp)
            bucket = self._index.get(key)
            if bucket is not None:
                bucket.pop(rec.session_id, None)
                if not bucket:
                    del self._index[key]

    def _tenant_bytes_locked(self, tenant: str) -> int:
        return sum(r.nbytes for r in self._sessions.values()
                   if r.tenant == tenant)

    def register(self, session_id: str, *, tenant, model_id, model_stamp,
                 tokens, kv_len, page_size, quantized, nbytes, owner,
                 replica) -> bool:
        """Record a freshly hibernated session (tier "hbm": the engine
        still holds its pinned radix pages).  Re-registering an existing
        ``session_id`` replaces it — a multi-turn session's next
        retirement supersedes the previous hibernation.  Enforces the
        tenant's tier quota by evicting that tenant's LRU sessions;
        returns False (nothing registered) when even that cannot fit the
        new session."""
        from penroz_tpu.serve import qos
        tokens = tuple(int(t) for t in tokens)
        pages = int(kv_len) // int(page_size)
        if pages < 1:
            return False
        fps = _fingerprints(tokens, int(page_size), pages)
        with self._lock:
            old = self._sessions.get(session_id)
            if old is not None:
                self._drop_locked(old, "replaced")
            cap = qos.QUOTAS.tier_bytes_for(tenant)
            if cap > 0:
                if int(nbytes) > cap:
                    self.drops["quota_refused"] += 1
                    return False
                while (self._tenant_bytes_locked(tenant) + int(nbytes) > cap):
                    victim = next((r for r in self._sessions.values()
                                   if r.tenant == tenant), None)
                    if victim is None:
                        break
                    self._drop_locked(victim, "quota")
            rec = _Session(session_id, tenant, model_id, model_stamp,
                           tokens, kv_len, page_size, quantized, nbytes,
                           owner, replica, fps)
            self._sessions[session_id] = rec
            self._index_add(rec)
            self.hibernated += 1
            self._journal("register", session_id=session_id, tenant=tenant,
                          model_id=model_id, model_stamp=model_stamp,
                          tokens=[int(t) for t in tokens],
                          kv_len=int(kv_len), page_size=int(page_size),
                          quantized=bool(quantized), nbytes=int(nbytes),
                          replica=replica)
        from penroz_tpu.serve import metrics as serve_metrics
        serve_metrics.SESSIONS_HIBERNATED.inc()
        return True

    def demote_to_host(self, session_id: str, blob: dict) -> bool:
        """Land a demoted session's blob in the host tier (the engine
        worker just ran ``export_pages`` off the hot path) and rebalance
        the lower tiers: host-cap overflow spills LRU host blobs to disk,
        disk-cap overflow drops LRU disk sessions."""
        from penroz_tpu.serve import metrics as serve_metrics
        from penroz_tpu.utils import checkpoint
        with self._lock:
            rec = self._sessions.get(session_id)
            if rec is None or rec.tier != "hbm":
                return False
            rec.tier = "host"
            rec.owner = None
            rec.nbytes = checkpoint.page_blob_nbytes(blob)
            self._host[session_id] = blob
            self.demotions["host"] += 1
            serve_metrics.TIER_DEMOTIONS.inc(tier="host")
            self._journal("demote", session_id=session_id, tier="host",
                          nbytes=rec.nbytes)
            self._enforce_caps_locked()
        return True

    def _tier_bytes_locked(self, tier: str) -> int:
        return sum(r.nbytes for r in self._sessions.values()
                   if r.tier == tier)

    def _lru_locked(self, tier: str):
        return next((r for r in self._sessions.values() if r.tier == tier),
                    None)

    def _enforce_caps_locked(self):
        from penroz_tpu.serve import metrics as serve_metrics
        from penroz_tpu.utils import checkpoint
        host_cap = host_cap_bytes()
        while self._tier_bytes_locked("host") > host_cap:
            rec = self._lru_locked("host")
            if rec is None:
                break
            blob = self._host.pop(rec.session_id)
            try:
                checkpoint.save_tier_blob(rec.session_id, blob)
            except OSError:
                log.warning("disk-tier write failed; dropping session %s",
                            rec.session_id, exc_info=True)
                self._drop_locked(rec, "disk_write_failed")
                continue
            rec.tier = "disk"
            rec.nbytes = checkpoint.tier_blob_nbytes(rec.session_id)
            self.demotions["disk"] += 1
            serve_metrics.TIER_DEMOTIONS.inc(tier="disk")
            self._journal("demote", session_id=rec.session_id, tier="disk",
                          nbytes=rec.nbytes)
        disk_cap = disk_cap_bytes()
        while self._tier_bytes_locked("disk") > disk_cap:
            rec = self._lru_locked("disk")
            if rec is None:
                break
            self._drop_locked(rec, "disk_cap")

    # -- lookup / promotion --------------------------------------------------

    def match(self, tokens, *, model_id, model_stamp, page_size, quantized,
              min_pages: int = 1):
        """Deepest hibernated session agreeing with ``tokens``' whole-page
        prefix: returns ``(record, depth_pages)`` or ``(None, 0)``.  The
        usable token count is capped at ``len(tokens) - 1`` (the radix
        match rule: one real token must remain to produce first-sample
        logits).  Sessions hibernated under a different model stamp
        (weights reloaded since) are dropped on sight — stale KV is never
        served.  Fingerprint candidates are verified token-for-token, so
        a hash collision degrades to a miss, not a wrong alias."""
        if not self._sessions:
            return None, 0
        P = int(page_size)
        max_pages = max(0, (len(tokens) - 1) // P)
        if max_pages < min_pages:
            return None, 0
        toks = tuple(int(t) for t in tokens)
        fps = _fingerprints(toks, P, max_pages)
        with self._lock:
            for depth in range(len(fps), max(0, min_pages - 1), -1):
                key = (model_id, P, bool(quantized), fps[depth - 1])
                bucket = self._index.get(key)
                if not bucket:
                    continue
                for sid in list(bucket):
                    rec = self._sessions.get(sid)
                    if rec is None:
                        bucket.pop(sid, None)
                        continue
                    if rec.model_stamp != model_stamp:
                        self.note_promotion(rec.tier, "stale")
                        self._drop_locked(rec, "stale_model")
                        continue
                    span = depth * P
                    if rec.kv_len >= span and rec.tokens[:span] == toks[:span]:
                        self.touch(sid)
                        self._journal("promote", session_id=sid,
                                      tier=rec.tier, depth=depth)
                        return rec, depth
            return None, 0

    def placement(self, tokens, *, model_id, page_size: int):
        """Router-side placement hint: the deepest token-verified resident
        session for ``tokens``' whole-page prefix, with NO side effects —
        no LRU touch, no promotion counters, no stamp fence (the router
        does not know each replica's checkpoint stamp; the engine-side
        promote still enforces it).  Both quantization variants are
        scanned — steering is per-model, not per-pool-layout.  Returns
        the record or None."""
        P = int(page_size)
        max_pages = max(0, (len(tokens) - 1) // P)
        if max_pages < 1 or not self._sessions:
            return None
        toks = tuple(int(t) for t in tokens)
        fps = _fingerprints(toks, P, max_pages)
        with self._lock:
            for depth in range(len(fps), 0, -1):
                for quantized in (False, True):
                    key = (model_id, P, quantized, fps[depth - 1])
                    bucket = self._index.get(key)
                    if not bucket:
                        continue
                    span = depth * P
                    for sid in bucket:
                        rec = self._sessions.get(sid)
                        if (rec is not None and rec.kv_len >= span
                                and rec.tokens[:span] == toks[:span]):
                            return rec
            return None

    def fetch(self, session_id: str):
        """The session's blob for promotion, or None (with the record
        dropped and the corrupt/miss counters bumped) when the copy is
        unreadable.  Tier "hbm" has no blob yet — the pages only exist in
        the owning engine's radix cache — so a cross-replica wake before
        demotion completes is also a None (the caller recomputes)."""
        from penroz_tpu.serve import metrics as serve_metrics
        from penroz_tpu.utils import checkpoint
        with self._lock:
            rec = self._sessions.get(session_id)
            if rec is None:
                return None
            if rec.tier == "hbm":
                return None
            if rec.tier == "host":
                return self._host.get(session_id)
            try:
                return checkpoint.load_tier_blob(session_id)
            except ValueError:
                self.corrupt_blobs += 1
                serve_metrics.TIER_CORRUPT.inc()
                self.note_promotion("disk", "corrupt")
                self._drop_locked(rec, "corrupt")
                return None
            except KeyError:
                self.note_promotion("disk", "miss")
                self._drop_locked(rec, "blob_missing")
                return None

    def note_promotion(self, tier: str, outcome: str):
        from penroz_tpu.serve import metrics as serve_metrics
        with self._lock:
            self.promotions[(tier, outcome)] += 1
        serve_metrics.TIER_PROMOTIONS.inc(tier=tier, outcome=outcome)

    def touch(self, session_id: str):
        with self._lock:
            rec = self._sessions.get(session_id)
            if rec is not None:
                rec.last_use = time.time()
                self._sessions.move_to_end(session_id)

    # -- removal -------------------------------------------------------------

    def _drop_locked(self, rec: _Session, reason: str):
        from penroz_tpu.utils import checkpoint
        self._sessions.pop(rec.session_id, None)
        self._host.pop(rec.session_id, None)
        if rec.tier == "disk":
            checkpoint.delete_tier_blob(rec.session_id)
        self._index_remove(rec)
        self.drops[reason] += 1
        self._journal("drop", session_id=rec.session_id, reason=reason)
        self._maybe_compact_locked()

    def drop(self, session_id: str, reason: str = "api") -> bool:
        """Evict one session from every tier (``DELETE /sessions/{id}``).
        A tier-"hbm" record's pinned pages are released by the owning
        engine when its demotion queue reaches the now-unregistered id."""
        with self._lock:
            rec = self._sessions.get(session_id)
            if rec is None:
                return False
            self._drop_locked(rec, reason)
            return True

    def drop_owner(self, owner, reason: str = "engine_reset") -> int:
        """Drop every tier-"hbm" session pinned by engine ``owner`` — its
        pool (and the pinned pages) just died in a crash-recovery
        reallocation, reload, or shutdown.  Host/disk-tier sessions
        survive: their bytes left HBM already."""
        with self._lock:
            victims = [r for r in self._sessions.values()
                       if r.tier == "hbm" and r.owner == owner]
            for rec in victims:
                self._drop_locked(rec, reason)
            return len(victims)

    # -- restart recovery ----------------------------------------------------

    def recover(self) -> dict:
        """Rebuild the registry after a process restart: replay the
        journal into final per-session states, cross-check the survivors
        against the disk tier (blob exists + container header validates
        + model stamp is still current), re-admit what checks out, apply
        journaled quota overrides, and sweep everything unreferenced —
        orphan atomic-write temp files AND finished blobs no record
        claims.  Called once from ``create_app()`` before routes are
        built; idempotent and a cheap no-op when the journal is off.

        Only disk-tier sessions recover: HBM pages and the pinned
        host-RAM cache died with the process.  Recovered records carry
        ``owner=None, replica=None`` so the router's steer-to-home
        degrades to normal placement when the home replica no longer
        exists."""
        from penroz_tpu.serve import journal
        from penroz_tpu.serve import metrics as serve_metrics
        from penroz_tpu.serve import qos
        from penroz_tpu.utils import checkpoint
        t0 = time.monotonic()
        summary = {
            "journal_enabled": journal.JOURNAL.enabled(),
            "records_replayed": 0, "bad_records": 0, "truncated_bytes": 0,
            "replay_errors": 0, "sessions_recovered": 0,
            "sessions_volatile": 0, "sessions_stale": 0,
            "sessions_blob_missing": 0, "sessions_blob_corrupt": 0,
            "quota_overrides_replayed": 0, "adapter_records_seen": 0,
            "blobs_swept": 0, "temp_files_swept": 0, "replay_ms": 0.0,
        }
        records: list = []
        if journal.JOURNAL.enabled():
            # A SIGKILL mid-compaction can strand the rewrite temp.
            try:
                os.remove(f"{journal.journal_path()}.compact.tmp")
            except OSError:
                pass
            try:
                records = journal.JOURNAL.replay()
            except Exception:  # noqa: BLE001 — recovery must never crash startup
                summary["replay_errors"] += 1
                log.warning("journal replay failed; recovering to an "
                            "empty registry", exc_info=True)
            summary["records_replayed"] = len(records)
            summary["bad_records"] = journal.JOURNAL.bad_records
            summary["truncated_bytes"] = journal.JOURNAL.truncated_bytes
        # Fold the record stream into final per-session state (last write
        # wins; promote = LRU touch so recovered eviction order matches).
        finals: collections.OrderedDict = collections.OrderedDict()
        quota_rate: dict = {}
        quota_tier: dict = {}
        for rec in records:
            kind = rec.get("t")
            sid = rec.get("session_id")
            if kind == "register" and sid:
                finals.pop(sid, None)
                finals[sid] = dict(rec)
            elif kind == "demote" and sid in finals:
                finals[sid]["tier"] = rec.get("tier", "host")
                finals[sid]["nbytes"] = rec.get(
                    "nbytes", finals[sid].get("nbytes", 0))
            elif kind == "promote" and sid in finals:
                finals.move_to_end(sid)
            elif kind == "drop" and sid:
                finals.pop(sid, None)
            elif kind == "quota" and rec.get("tenant") is not None:
                if "rate" in rec:
                    quota_rate[rec["tenant"]] = rec["rate"]
                if "tier_mb" in rec:
                    quota_tier[rec["tenant"]] = rec["tier_mb"]
            elif kind == "adapter":
                summary["adapter_records_seen"] += 1
        with self._lock:
            self._replaying = True
            try:
                for sid, rec in finals.items():
                    if sid in self._sessions:
                        continue   # live (warm, in-process) record wins
                    if rec.get("tier", "hbm") != "disk":
                        summary["sessions_volatile"] += 1
                        continue
                    try:
                        self._recover_one_locked(rec, summary)
                    except Exception:  # noqa: BLE001 — skip, never crash
                        log.warning("could not recover session %r", sid,
                                    exc_info=True)
            finally:
                self._replaying = False
            referenced = [r.session_id for r in self._sessions.values()
                          if r.tier == "disk"]
        for tenant, rate in quota_rate.items():
            qos.QUOTAS.set_rate(tenant, rate)
            summary["quota_overrides_replayed"] += 1
        for tenant, mb in quota_tier.items():
            qos.QUOTAS.set_tier_mb(tenant, mb)
            summary["quota_overrides_replayed"] += 1
        # A failed replay means the reference set is unknown: sweep only
        # the (always-safe) atomic-write temps, never finished blobs —
        # a transient replay error must not destroy recoverable sessions.
        summary.update(checkpoint.sweep_tier_orphans(
            None if summary["replay_errors"] else referenced))
        if summary["sessions_recovered"]:
            serve_metrics.SESSIONS_RECOVERED.inc(
                summary["sessions_recovered"])
        summary["replay_ms"] = round((time.monotonic() - t0) * 1000.0, 3)
        self.last_recovery = summary
        with self._lock:
            self._maybe_compact_locked()
        if summary["sessions_recovered"] or summary["bad_records"]:
            log.info("restart recovery: %(sessions_recovered)d session(s) "
                     "restored, %(sessions_stale)d stale, "
                     "%(sessions_blob_missing)d missing, "
                     "%(sessions_blob_corrupt)d corrupt, "
                     "%(bad_records)d bad journal record(s) "
                     "(%(truncated_bytes)d torn bytes)", summary)
        return summary

    def _recover_one_locked(self, rec: dict, summary: dict):
        """Admit one journal-final disk-tier session if its blob and
        model stamp survive scrutiny (caller holds the lock with
        ``_replaying`` set; counted drops here re-journal explicitly so
        the next replay skips them)."""
        from penroz_tpu.serve import journal
        from penroz_tpu.utils import checkpoint
        sid = rec["session_id"]

        def _dead(counter: str, reason: str, delete_blob: bool):
            summary[counter] += 1
            if delete_blob:
                checkpoint.delete_tier_blob(sid)
            self.drops[reason] += 1
            if journal.JOURNAL.enabled():
                journal.JOURNAL.append("drop", session_id=sid, reason=reason)

        if not os.path.exists(checkpoint.tier_blob_path(sid)):
            _dead("sessions_blob_missing", "recover_blob_missing", False)
            return
        if not checkpoint.validate_tier_blob(sid):
            self.corrupt_blobs += 1
            _dead("sessions_blob_corrupt", "recover_blob_corrupt", True)
            return
        model_id = rec.get("model_id")
        try:
            current_stamp = os.path.getmtime(
                checkpoint._source_path(model_id))
        except OSError:
            current_stamp = None
        if current_stamp is None or rec.get("model_stamp") != current_stamp:
            _dead("sessions_stale", "recover_stale_model", True)
            return
        tokens = tuple(int(t) for t in rec.get("tokens", ()))
        kv_len = int(rec.get("kv_len", 0))
        page_size = int(rec.get("page_size", 0) or 0)
        if page_size < 1 or kv_len // page_size < 1:
            _dead("sessions_blob_corrupt", "recover_bad_record", True)
            return
        fps = _fingerprints(tokens, page_size, kv_len // page_size)
        sess = _Session(sid, rec.get("tenant"), model_id,
                        rec.get("model_stamp"), tokens, kv_len, page_size,
                        rec.get("quantized", False),
                        checkpoint.tier_blob_nbytes(sid), None, None, fps)
        sess.tier = "disk"
        sess.created = float(rec.get("ts") or sess.created)
        self._sessions[sid] = sess
        self._index_add(sess)
        summary["sessions_recovered"] += 1

    # -- introspection -------------------------------------------------------

    def get(self, session_id: str):
        with self._lock:
            return self._sessions.get(session_id)

    def resident_sessions(self) -> int:
        return len(self._sessions)

    def sessions_by_tier(self) -> dict:
        with self._lock:
            out = {t: 0 for t in TIERS_ALL}
            for rec in self._sessions.values():
                out[rec.tier] += 1
            return out

    def pages_by_tier(self) -> dict:
        with self._lock:
            out = {t: 0 for t in TIERS_ALL}
            for rec in self._sessions.values():
                out[rec.tier] += rec.pages
            return out

    def tier_bytes(self) -> dict:
        """Bytes held OUTSIDE the paged pool, per lower tier (tier-"hbm"
        sessions live in pool pages the memledger already counts as
        ``hibernating``, so they are excluded here — no double count)."""
        with self._lock:
            return {"host_tier": self._tier_bytes_locked("host"),
                    "disk_tier": self._tier_bytes_locked("disk")}

    def list_sessions(self) -> list:
        now = time.time()
        with self._lock:
            return [{
                "session_id": r.session_id,
                "tenant": r.tenant,
                "model_id": r.model_id,
                "tier": r.tier,
                "tokens": r.kv_len,
                "pages": r.pages,
                "nbytes": r.nbytes,
                "replica": r.replica,
                "age_s": max(0.0, now - r.created),
                "idle_s": max(0.0, now - r.last_use),
            } for r in self._sessions.values()]

    def stats(self) -> dict:
        with self._lock:
            promos: collections.Counter = collections.Counter()
            for (_, outcome), n in self.promotions.items():
                promos[outcome] += n
            return {
                "sessions_resident": len(self._sessions),
                "sessions_by_tier": {t: sum(1 for r in self._sessions.values()
                                            if r.tier == t)
                                     for t in TIERS_ALL},
                "tier_bytes": {"host_tier": self._tier_bytes_locked("host"),
                               "disk_tier": self._tier_bytes_locked("disk")},
                "tier_promotions": {o: promos.get(o, 0) for o in OUTCOMES},
                "tier_demotions": {t: self.demotions.get(t, 0)
                                   for t in ("host", "disk")},
                "tier_corrupt_blobs": self.corrupt_blobs,
                "restart_recovery": dict(self.last_recovery),
            }

    def reset(self):
        """Test/bench hook: drop every session (disk files included) and
        zero the lifetime counters."""
        with self._lock:
            for rec in list(self._sessions.values()):
                self._drop_locked(rec, "reset")
            self._sessions.clear()
            self._host.clear()
            self._index.clear()
            self.hibernated = 0
            self.demotions.clear()
            self.promotions.clear()
            self.corrupt_blobs = 0
            self.drops.clear()
            self.last_recovery = {}
            self._replaying = False


TIERS = TierStore()


def reset() -> None:
    TIERS.reset()
