"""Page-granularity HBM capacity ledger + engine crash flight recorder.

PR 6 made the serving engine observable in *time* (traces, histograms,
tick telemetry); this module makes it observable in *space*.  Every page
of an engine's paged KV pool is attributed to exactly ONE owner state:

- ``free``             — unassigned pages of the static per-row partition
- ``row``              — pages a live decode row's KV actually occupies
                         (attributed onward to its request's tenant and
                         adapter)
- ``prefix_pinned``    — radix prefix-cache pages aliased by a live row
                         (refs > 0; eviction-proof)
- ``prefix_evictable`` — cached prefix pages no row currently pins
                         (LRU-evictable on the next insert)
- ``preempted``        — cache pages pinned by a QUEUED preempted
                         session's resume hold (serve/qos.py preemption:
                         the zero-recompute resume guarantee)
- ``reserved``         — the prefix-cache region's unallocated tail (the
                         radix free list)
- ``transit``          — pages of a disaggregated-prefill hand-off row
                         mid-import on a decode replica (admitted but not
                         yet emitting; the partition invariant must sum
                         through the hand-off window too)

plus a byte ledger for the non-paged components (contiguous / int8 KV,
the stacked LoRA adapter pack, model params, the adapter host cache).

The ledger deliberately does NOT shadow-count at mutation sites:
:meth:`MemoryLedger.snapshot` *derives* ownership from the authoritative
structures (row table + lengths + prefix pins, the radix tree, queued
resume holds) so the report can never drift from the state it describes.
Drift between independently derived views is exactly what
:meth:`MemoryLedger.audit` hunts: with ``PENROZ_MEMLEDGER_STRICT=1`` (on
in tests) every retirement, preemption, and crash recovery re-proves

    owned + free == pool capacity, zero orphan owners,
    every radix refcount == the pin count derivable from live rows
    and queued resume holds

and raises :class:`LedgerAuditError` on the first violation — the
checker that would have caught the PR 8 unpin-underflow class the day it
was written.

The **flight recorder** is the postmortem half: on every
``engine_crash`` / circuit-open the engine's pre-crash ledger snapshot,
tick-timeline tail, per-class/per-tenant queue depths, and recent trace
ids land in a bounded process-wide ring served by ``GET /debug/dump`` —
the state you wish you had *after* the engine reset threw it away.

Surfaces: ``GET /memory/`` (serve/app.py), ``penroz_pool_pages{state}``
/ ``penroz_tenant_kv_pages{tenant}`` / ``penroz_hbm_bytes{component}``
(+ high-water marks and a token-burn-rate time-to-exhaustion estimate)
on ``GET /metrics``, per-engine ``memory`` blocks in
``/serving_stats/``, and the dashboard's stacked memory panel.
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import time

import jax

from penroz_tpu.ops import kv_cache as KV
from penroz_tpu.serve import tierstore

log = logging.getLogger(__name__)

ENABLE_ENV = "PENROZ_MEMLEDGER"
STRICT_ENV = "PENROZ_MEMLEDGER_STRICT"
DUMP_RING_ENV = "PENROZ_DEBUG_DUMP_RING"
DUMP_TICKS_ENV = "PENROZ_DEBUG_DUMP_TICKS"

#: Every paged-pool page is in exactly one of these states; their sum is
#: the pool capacity (the audited invariant).  ``hibernating`` = radix
#: pages pinned by a session hold awaiting tier demotion
#: (decode_scheduler._hib_holds → serve/tierstore.py).
PAGE_STATES = ("free", "row", "prefix_pinned", "prefix_evictable",
               "preempted", "reserved", "transit", "hibernating")

#: Fixed keys of the per-engine byte ledger (``hbm_bytes``); the
#: aggregate adds ``adapter_host_cache`` (process-wide, host RAM).
BYTE_COMPONENTS = ("kv_values", "kv_scales", "kv_block_table",
                   "lora_pack", "params", "ssm_state")

#: Sliding window for the token-burn-rate estimate (matches the
#: decode_scheduler tokens/sec window).
_BURN_WINDOW_S = 30.0


def enabled() -> bool:
    """Ledger + flight recorder on by default; ``PENROZ_MEMLEDGER=0`` is
    the kill switch (snapshots degrade to empty, recorder drops)."""
    return os.environ.get(ENABLE_ENV, "1") != "0"


def strict() -> bool:
    """Leak-sanitizer mode: audit at every retirement / preemption /
    crash recovery and RAISE on violations (on in tests)."""
    return os.environ.get(STRICT_ENV, "0") == "1"


def _env_i(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        log.warning("Unparseable %s=%r; using default %d", name,
                    os.environ.get(name), default)
        return default


class LedgerAuditError(AssertionError):
    """A strict-mode ledger audit found leaked/orphaned pages or a
    refcount that disagrees with the derivable pin set.  AssertionError
    subclass: an audit failure IS a failed invariant assertion."""


def _tree_bytes(tree) -> int:
    """Per-device bytes of every array leaf in a pytree (LoRA pack,
    params).  Routed through ``KV.array_device_bytes`` so a param dict
    sharded over a serving mesh is charged its shard bytes — what one
    device's HBM actually holds — not the logical global size; unmeshed
    leaves report exactly what they always did."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if getattr(leaf, "dtype", None) is not None and hasattr(leaf, "shape"):
            total += KV.array_device_bytes(leaf)
    return total


class MemoryLedger:
    """One engine's slice of the capacity ledger.

    Owned by :class:`serve.decode_scheduler.DecodeEngine`; ``snapshot``
    and ``audit`` take the engine's condition lock (an RLock — safe to
    call from seams already holding it).  Counters here are the
    engine-SCOPED drop/underflow attribution the process-wide
    ``ops/kv_cache.py`` globals cannot provide; the globals stay
    authoritative for the byte-compatible ``/metrics`` totals.
    """

    def __init__(self, engine):
        self._engine = engine
        # Engine-scoped pool-capacity retirements (the process-wide
        # mirror is KV.record_pool_drop / pool_drop_count()).
        self.pool_capacity_drops = 0
        self.dropped_tokens = 0
        # Capacity-pressure events: pool-capacity truncations + QoS
        # preemptions (both are "the pool is too small for the load").
        self.pressure_events = 0
        self.audit_failures = 0
        # Unpin underflows counted per prefix-cache INSTANCE; crash
        # recovery replaces the cache, so retired instances' counts
        # accumulate into the carry (lifetime observability).
        self._underflow_carry = 0
        self.high_water: dict = {}

    # -- engine-scoped counters ---------------------------------------------

    @property
    def unpin_underflows(self) -> int:
        cache = getattr(self._engine, "_prefix_cache", None)
        live = cache.unpin_underflows if cache is not None else 0
        return self._underflow_carry + live

    def note_pool_drop(self, tokens: int):
        self.pool_capacity_drops += 1
        self.dropped_tokens += max(0, int(tokens))
        self.pressure_events += 1

    def note_pressure(self):
        self.pressure_events += 1

    def on_realloc(self, old_cache):
        """Crash recovery replaced the engine state: fold the dying
        prefix cache's instance counters into the lifetime carry."""
        if old_cache is not None:
            self._underflow_carry += old_cache.unpin_underflows

    # -- the snapshot walk ---------------------------------------------------

    def _resume_pages(self) -> set:
        """Pages held by QUEUED preempted sessions' resume pins (caller
        holds the engine lock)."""
        pages: set = set()
        for req in self._engine._pending:
            for nd in req.resume_nodes:
                pages.add(nd.page)
        return pages

    def _hib_pages(self) -> set:
        """Pages pinned by session-hibernation holds awaiting demotion."""
        pages: set = set()
        for hold in getattr(self._engine, "_hib_holds", {}).values():
            for nd in hold["nodes"]:
                pages.add(nd.page)
        return pages

    def snapshot(self) -> dict:
        """Derive the full ownership map from the authoritative engine
        structures.  Consistent when called from the worker thread or
        with the engine quiescent; concurrent HTTP reads see
        torn-but-valid state (same contract as ``stats()``)."""
        e = self._engine
        with e._cond:
            return self._snapshot_locked()

    def _snapshot_locked(self) -> dict:
        e = self._engine
        kv = e._kv
        paged = isinstance(kv, KV.PagedKVState)
        states = {s: 0 for s in PAGE_STATES}
        tenant_pages: dict = {}
        adapter_pages: dict = {}
        page_size = 0
        total = 0
        if paged and enabled():
            page_size = kv.page_size
            total = kv.num_pool_pages
            resume_pages = self._resume_pages()
            row_pages = 0
            transit_pages = 0
            for i, state in enumerate(e._rows):
                if state is None:
                    continue
                used = -(-int(e._lengths[i]) // page_size)  # ceil
                owned = max(0, used - len(state.prefix_nodes))
                if getattr(state, "transit", False):
                    transit_pages += owned
                else:
                    row_pages += owned
                tenant = state.req.tenant
                tenant_pages[tenant] = tenant_pages.get(tenant, 0) + owned
                if state.req.adapter is not None:
                    aid = state.req.adapter.adapter_id
                    adapter_pages[aid] = adapter_pages.get(aid, 0) + owned
            cache = e._prefix_cache
            hib_pages = self._hib_pages()
            pinned = evictable = preempted = reserved = hibernating = 0
            cache_pages = 0
            if cache is not None:
                cache_pages = cache.capacity_pages
                reserved = cache.free_pages
                for nd in cache.iter_nodes():
                    if nd.page in resume_pages:
                        preempted += 1
                    elif nd.page in hib_pages:
                        hibernating += 1
                    elif nd.refs > 0:
                        pinned += 1
                    else:
                        evictable += 1
            states.update({
                "row": row_pages,
                "transit": transit_pages,
                "free": (total - cache_pages) - row_pages - transit_pages,
                "prefix_pinned": pinned,
                "prefix_evictable": evictable,
                "preempted": preempted,
                "reserved": reserved,
                "hibernating": hibernating,
            })
        hbm = {k: 0 for k in BYTE_COMPONENTS}
        if enabled():
            hbm.update(kv.hbm_components())
            if e._lora_pack is not None:
                hbm["lora_pack"] = _tree_bytes(e._lora_pack)
            hbm["params"] = (_tree_bytes(e._model.params)
                             + _tree_bytes(e._model.buffers))
        # High-water marks: per-state peaks plus total pages in use.
        used_total = total - states["free"] if paged else 0
        for key, v in [*states.items(), ("used", used_total)]:
            if key != "free":
                self.high_water[key] = max(self.high_water.get(key, 0), v)
        return {
            "paged": paged,
            "page_size": page_size,
            "pool_pages_total": total,
            "pool_pages": states,
            "tenant_pages": tenant_pages,
            "adapter_pages": adapter_pages,
            "stage_pools": self._stage_pools_locked(),
            "hbm_bytes": hbm,
            "high_water_pages": dict(self.high_water),
            "time_to_exhaustion_s": self._time_to_exhaustion(
                states["free"], page_size),
            "kv_pool_capacity_drops": self.pool_capacity_drops,
            "unpin_underflows": self.unpin_underflows,
            "pressure_events": self.pressure_events,
            "audit_failures": self.audit_failures,
        }

    def _stage_pools_locked(self) -> list:
        """Per-pipeline-stage pool attribution: stage ``s`` owns attention
        layers ``kv_bounds[s]`` of the paged cache, so its device holds
        ``kv_pool_bytes`` of pool HBM for the SAME ``pool_pages`` page
        partition (pages are a per-layer-replicated concept: every stage
        sees every logical page, in its own layers only — which is
        exactly why per-device KV HBM drops ~1/S).  Empty list when the
        engine is not a pipeline group."""
        e = self._engine
        pipe = getattr(e, "_pipe", None)
        kv = e._kv
        if (pipe is None or not isinstance(kv, KV.PagedKVState)
                or not enabled()):
            return []
        return [{"stage": s,
                 "kv_layers": hi - lo,
                 "pool_pages": kv.num_pool_pages,
                 "kv_pool_bytes": KV.stage_pool_bytes(kv, lo, hi)}
                for s, (lo, hi) in enumerate(pipe.kv_bounds)]

    def _time_to_exhaustion(self, free_pages: int, page_size: int):
        """Free row-region KV tokens over the recent token burn rate —
        'at the current emission rate, the pool runs dry in N seconds'.
        None when idle or not paged (no rate → no estimate; absent, not
        zero, so a quiet engine never looks exhausted)."""
        if page_size <= 0:
            return None
        now = time.monotonic()
        window = [(t, n) for t, n in self._engine._token_window
                  if now - t <= _BURN_WINDOW_S]
        span = (now - window[0][0]) if window else 0.0
        if span <= 0.2:
            return None
        rate = sum(n for _, n in window) / span
        if rate <= 0:
            return None
        return round(free_pages * page_size / rate, 1)

    # -- the leak sanitizer --------------------------------------------------

    def audit(self, where: str) -> list[str]:
        """Re-derive every ownership claim two independent ways and
        compare.  Returns the violation list; in strict mode a non-empty
        list raises :class:`LedgerAuditError` (the engine treats that as
        the corruption it is)."""
        e = self._engine
        with e._cond:
            problems = self._audit_locked()
        if problems:
            self.audit_failures += 1
            msg = (f"memory-ledger audit failed at {where} "
                   f"(engine {e.model_id}): " + "; ".join(problems))
            if strict():
                raise LedgerAuditError(msg)
            log.warning(msg)
        return problems

    def _audit_locked(self) -> list[str]:
        e = self._engine
        kv = e._kv
        if not isinstance(kv, KV.PagedKVState) or not enabled():
            return []
        problems: list[str] = []
        cache = e._prefix_cache
        if cache is not None:
            problems.extend(f"radix: {p}" for p in cache.page_audit())
            # Refcount cross-check: a node's refs must equal the pins
            # derivable from live rows' prefix_nodes plus queued resume
            # holds — an unpaired pin/unpin (the PR 8 underflow class)
            # shows up HERE as a mismatch instead of silent drift.
            expected: collections.Counter = collections.Counter()
            holders: list = []
            for state in e._rows:
                if state is not None:
                    holders.extend(state.prefix_nodes)
            for req in e._pending:
                holders.extend(req.resume_nodes)
            for hold in getattr(e, "_hib_holds", {}).values():
                holders.extend(hold["nodes"])
            for nd in holders:
                expected[id(nd)] += 1
            in_tree = set()
            for nd in cache.iter_nodes():
                in_tree.add(id(nd))
                want = expected.get(id(nd), 0)
                if nd.refs != want:
                    problems.append(
                        f"node page {nd.page}: refs={nd.refs} but {want} "
                        f"derivable pin(s)")
            orphans = [nid for nid in expected if nid not in in_tree]
            if orphans:
                problems.append(
                    f"{len(orphans)} pinned node(s) no longer in the "
                    f"tree (orphan pins)")
        snap = self._snapshot_locked()
        states = snap["pool_pages"]
        owned = sum(states.values())
        if owned != snap["pool_pages_total"]:
            problems.append(
                f"page states sum to {owned} != pool capacity "
                f"{snap['pool_pages_total']} ({states})")
        for s, n in states.items():
            if n < 0:
                problems.append(f"negative page count {s}={n}")
        # Pipeline groups: re-prove the partition invariant per stage
        # pool (every stage sees the full logical page partition over its
        # own layers), and the stage byte attribution must tile the pool
        # HBM exactly — a stage slice that drifted from kv_bounds would
        # double-count or leak pool bytes here.
        for entry in snap["stage_pools"]:
            if entry["pool_pages"] != snap["pool_pages_total"]:
                problems.append(
                    f"stage {entry['stage']}: pool_pages="
                    f"{entry['pool_pages']} != pool capacity "
                    f"{snap['pool_pages_total']}")
        if snap["stage_pools"]:
            stage_bytes = sum(en["kv_pool_bytes"]
                              for en in snap["stage_pools"])
            kv_bytes = (snap["hbm_bytes"]["kv_values"]
                        + snap["hbm_bytes"]["kv_scales"])
            if stage_bytes != kv_bytes:
                problems.append(
                    f"stage pool bytes sum to {stage_bytes} != kv pool "
                    f"HBM {kv_bytes}")
        return problems


# ---------------------------------------------------------------------------
# Flight recorder (GET /debug/dump)
# ---------------------------------------------------------------------------


class FlightRecorder:
    """Bounded process-wide ring of pre-crash engine snapshots.

    ``record`` runs in the crashing worker thread BEFORE ``_fail_all`` /
    ``_alloc_state`` throw the evidence away; it must never make a bad
    situation worse, so every capture step is best-effort (a partial
    entry with the reason beats no entry)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(
            maxlen=max(1, _env_i(DUMP_RING_ENV, 8)))
        self.recorded = 0

    def record(self, engine, reason: str, error: str | None = None):
        if not enabled():
            return
        entry = {
            "unix_ts": time.time(),
            "reason": reason,
            "error": error,
            "model_id": engine.model_id,
            "block_size": engine.block_size,
        }
        try:
            now = time.monotonic()
            ticks = list(engine._tick_timeline)[-max(
                1, _env_i(DUMP_TICKS_ENV, 32)):]
            entry.update({
                "crashes_total": engine._crashes_total,
                "engine_resets": engine._engine_resets,
                "active_rows": engine.active_rows,
                "queue_depth": engine.queue_depth,
                "ledger": engine._ledger.snapshot(),
                "tick_timeline": [
                    {"age_s": round(now - t["t"], 3),
                     **{k: v for k, v in t.items() if k != "t"}}
                    for t in ticks],
                "queue_depth_by_class": engine._pending.class_depths(),
                "queue_depth_by_tenant": engine._pending.tenant_depths(),
                "recent_traces": _recent_trace_ids(),
            })
        except Exception:  # noqa: BLE001 — a postmortem must not crash the crash path
            log.exception("Flight recorder: partial capture for %s (%s)",
                          engine.model_id, reason)
            entry["partial"] = True
        with self._lock:
            self._ring.append(entry)
            self.recorded += 1
        log.warning("Flight recorder: captured %s for engine %s "
                    "(GET /debug/dump)", reason, engine.model_id)

    def dump(self) -> dict:
        with self._lock:
            return {"capacity": self._ring.maxlen,
                    "recorded": self.recorded,
                    "entries": list(self._ring)}

    def reset(self):
        with self._lock:
            self._ring = collections.deque(
                maxlen=max(1, _env_i(DUMP_RING_ENV, 8)))
            self.recorded = 0


FLIGHT_RECORDER = FlightRecorder()


def _recent_trace_ids(limit: int = 16) -> dict:
    """Request ids of recently completed + currently live traces — the
    correlation keys a postmortem follows into ``GET /trace/{id}``."""
    from penroz_tpu.utils import tracing
    try:
        done = [t.request_id for t in tracing.completed(limit=limit)]
        live = [t.request_id for t in tracing.live()]
        return {"completed": done, "live": live[:limit]}
    except Exception:  # noqa: BLE001 — best-effort postmortem context
        return {"completed": [], "live": []}


# ---------------------------------------------------------------------------
# Cross-engine aggregation (GET /memory/, /metrics gauges)
# ---------------------------------------------------------------------------


def _engine_snapshots() -> list[tuple]:
    """(engine, snapshot) pairs via the registry, snapshotted through the
    one locked accessor each — no caller reaches into engine state."""
    from penroz_tpu.serve import decode_scheduler as ds
    with ds._REG_LOCK:
        engines = [e for e in ds._ENGINES.values() if not e._shutdown]
    return [(e, e.memory_snapshot()) for e in engines]


def memory_stats() -> dict:
    """The ``GET /memory/`` payload: per-engine ledger snapshots plus
    cross-engine totals and the process-wide counters the ledger's
    engine-scoped counts refine (kept byte-compatible on /metrics)."""
    from penroz_tpu.serve import adapters as adapters_mod
    pairs = _engine_snapshots()
    per = [dict(snap, model_id=e.model_id, block_size=e.block_size,
                capacity=e.capacity, replica=getattr(e, "replica", 0),
                role=getattr(e, "role", "decode"),
                disagg_transport=getattr(e, "disagg_transport", "d2d"))
           for e, snap in pairs]
    pool = {s: sum(p["pool_pages"][s] for p in per) for s in PAGE_STATES}
    tenant: dict = {}
    hwm: dict = {}
    for p in per:
        for t, n in p["tenant_pages"].items():
            tenant[t] = tenant.get(t, 0) + n
        for s, n in p["high_water_pages"].items():
            hwm[s] = hwm.get(s, 0) + n
    hbm = {k: sum(p["hbm_bytes"].get(k, 0) for p in per)
           for k in BYTE_COMPONENTS}
    hbm["adapter_host_cache"] = adapters_mod.REGISTRY.cache_bytes()
    # Off-HBM KV tiers (hibernated session blobs): process-wide like the
    # adapter host cache, reported alongside it so /memory/ shows where
    # every cached byte lives.
    hbm.update(tierstore.TIERS.tier_bytes())
    ttes = [p["time_to_exhaustion_s"] for p in per
            if p["time_to_exhaustion_s"] is not None]
    return {
        "memledger_enabled": enabled(),
        "engines": per,
        "pool_pages": pool,
        "tenant_pages": tenant,
        "hbm_bytes": hbm,
        "high_water_pages": hwm,
        "time_to_exhaustion_s": min(ttes) if ttes else None,
        "kv_pool_capacity_drops": KV.pool_drop_count(),
        "unpin_underflows": KV.unpin_underflow_count(),
        "pressure_events": sum(p["pressure_events"] for p in per),
        "audit_failures": sum(p["audit_failures"] for p in per),
        "flight_records": FLIGHT_RECORDER.recorded,
    }


def pool_page_totals() -> dict:
    """penroz_pool_pages{state} gauge callback."""
    per = [snap for _, snap in _engine_snapshots()]
    return {s: sum(p["pool_pages"][s] for p in per) for s in PAGE_STATES}


def pool_page_hwm_totals() -> dict:
    """penroz_pool_pages_hwm{state} gauge callback."""
    out: dict = {}
    for _, snap in _engine_snapshots():
        for s, n in snap["high_water_pages"].items():
            out[s] = out.get(s, 0) + n
    return out


def tenant_page_totals() -> dict:
    """penroz_tenant_kv_pages{tenant} gauge callback."""
    out: dict = {}
    for _, snap in _engine_snapshots():
        for t, n in snap["tenant_pages"].items():
            out[t] = out.get(t, 0) + n
    return out


def hbm_byte_totals() -> dict:
    """penroz_hbm_bytes{component} gauge callback."""
    from penroz_tpu.serve import adapters as adapters_mod
    per = [snap for _, snap in _engine_snapshots()]
    out = {k: sum(p["hbm_bytes"].get(k, 0) for p in per)
           for k in BYTE_COMPONENTS}
    out["adapter_host_cache"] = adapters_mod.REGISTRY.cache_bytes()
    out.update(tierstore.TIERS.tier_bytes())
    return out


def min_time_to_exhaustion():
    """penroz_kv_time_to_exhaustion_s gauge callback: the most-pressed
    engine's estimate; None (absent series) when no engine has a burn
    rate — 'unknown' must stay distinct from 'exhausted now'."""
    ttes = [snap["time_to_exhaustion_s"] for _, snap in _engine_snapshots()
            if snap["time_to_exhaustion_s"] is not None]
    return min(ttes) if ttes else None


def reset():
    """Test hook: drop the flight-recorder ring (per-engine ledgers die
    with their engines via decode_scheduler.reset())."""
    FLIGHT_RECORDER.reset()
