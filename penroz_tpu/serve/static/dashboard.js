/* penroz-tpu dashboard: polls /progress/ and /stats/ and renders training
 * curves + histograms on plain <canvas> (no chart library). */
"use strict";

const $ = (id) => document.getElementById(id);

function getQueryState() {
  const p = new URLSearchParams(location.search);
  return { modelId: p.get("model_id") || "", filter: p.get("filter") || "" };
}

function setQueryState(modelId, filter) {
  const p = new URLSearchParams();
  if (modelId) p.set("model_id", modelId);
  if (filter) p.set("filter", filter);
  history.replaceState(null, "", `${location.pathname}?${p}`);
}

/* ---- tiny canvas plotting helpers ------------------------------------- */

const COLORS = ["#7fd1b9", "#e0b35c", "#7aa2f7", "#e06c75", "#b58cd9",
                "#56b6c2", "#98c379", "#d19a66"];

function prepCanvas(canvas) {
  const ctx = canvas.getContext("2d");
  ctx.clearRect(0, 0, canvas.width, canvas.height);
  return ctx;
}

function drawAxes(ctx, w, h, pad) {
  ctx.strokeStyle = "#2a3642";
  ctx.beginPath();
  ctx.moveTo(pad, 8); ctx.lineTo(pad, h - pad); ctx.lineTo(w - 8, h - pad);
  ctx.stroke();
}

function drawLabel(ctx, text, x, y, color = "#5d7285") {
  ctx.fillStyle = color;
  ctx.font = "11px sans-serif";
  ctx.fillText(text, x, y);
}

/* Draw one or more series as lines. series: [{name, xs, ys}] */
function lineChart(canvas, series, opts = {}) {
  const ctx = prepCanvas(canvas);
  const w = canvas.width, h = canvas.height, pad = 46;
  drawAxes(ctx, w, h, pad);
  const pts = series.flatMap(s => s.ys.filter(Number.isFinite));
  if (!pts.length) { drawLabel(ctx, "no data", w / 2 - 20, h / 2); return; }
  let lo = Math.min(...pts), hi = Math.max(...pts);
  if (lo === hi) { lo -= 1; hi += 1; }
  const xMax = Math.max(...series.map(s => s.xs.length ? Math.max(...s.xs) : 1));
  const xMin = Math.min(...series.map(s => s.xs.length ? Math.min(...s.xs) : 0));
  const sx = (x) => pad + (x - xMin) / Math.max(1e-9, xMax - xMin) * (w - pad - 16);
  const sy = (y) => (h - pad) - (y - lo) / (hi - lo) * (h - pad - 16);

  series.forEach((s, i) => {
    ctx.strokeStyle = COLORS[i % COLORS.length];
    ctx.lineWidth = 1.5;
    ctx.beginPath();
    let started = false;
    s.xs.forEach((x, j) => {
      const y = s.ys[j];
      if (!Number.isFinite(y)) return;
      if (!started) { ctx.moveTo(sx(x), sy(y)); started = true; }
      else ctx.lineTo(sx(x), sy(y));
    });
    ctx.stroke();
  });
  drawLabel(ctx, hi.toPrecision(4), 4, 16);
  drawLabel(ctx, lo.toPrecision(4), 4, h - pad);
  if (opts.legend) {
    series.forEach((s, i) => {
      drawLabel(ctx, s.name, pad + 8 + i * 130, 16, COLORS[i % COLORS.length]);
    });
  }
}

/* Max-normalized bar series on a canvas. */
function drawBars(canvas, ys, color) {
  const ctx = prepCanvas(canvas);
  const w = canvas.width, h = canvas.height, pad = 8;
  const hi = Math.max(...ys, 1e-12);
  const bw = (w - 2 * pad) / ys.length;
  ctx.fillStyle = color;
  ys.forEach((v, i) => {
    const bh = v / hi * (h - 2 * pad);
    ctx.fillRect(pad + i * bw, h - pad - bh, Math.max(1, bw - 1), bh);
  });
  return ctx;
}

/* Histogram as filled bars. data: {x: edges, y: densities} */
function histChart(canvas, data) {
  const w = canvas.width, h = canvas.height, pad = 8;
  if (!data || !data.x || !data.x.length) {
    drawLabel(prepCanvas(canvas), "no data", w / 2 - 20, h / 2); return;
  }
  const n = data.y.length;
  const ctx = drawBars(canvas, data.y, "#3f7f6b");
  drawLabel(ctx, Number(data.x[0]).toPrecision(3), pad, h - 1);
  drawLabel(ctx, Number(data.x[n - 1]).toPrecision(3), w - 50, h - 1);
}

/* ---- data fetch + render ---------------------------------------------- */

async function fetchJson(url) {
  const res = await fetch(url);
  if (!res.ok) throw new Error(`${url}: HTTP ${res.status}`);
  return res.json();
}

function renderProgress(data) {
  const progress = data.progress || [];
  const epochs = progress.map(p => p.epoch);
  const badge = $("status-badge");
  const code = data.status && data.status.code || "—";
  badge.textContent = code;
  badge.className = "badge " + (code === "Error" ? "err" :
    code === "Training" ? "busy" : "ok");

  lineChart($("cost-chart"), [{
    name: "log10(cost)", xs: epochs,
    ys: progress.map(p => Math.log10(Math.max(p.cost, 1e-12))),
  }], { legend: true });

  lineChart($("avg-cost-chart"), [{
    name: "avg cost",
    xs: (data.average_cost_history || []).map((_, i) => i),
    ys: data.average_cost_history || [],
  }]);

  lineChart($("speed-chart"), [{
    name: "tokens/sec", xs: epochs,
    ys: progress.map(p => p.speedPerSec),
  }]);

  // weight update ratios: one series per weight index (log10)
  const nWeights = progress.length ?
    (progress[progress.length - 1].weight_upd_ratio || []).length : 0;
  const series = [];
  for (let wi = 0; wi < nWeights; wi++) {
    const ys = progress.map(p => {
      const r = (p.weight_upd_ratio || [])[wi];
      return r == null ? NaN : Math.log10(Math.max(r, 1e-12));
    });
    if (ys.some(Number.isFinite)) series.push({ name: `w${wi}`, xs: epochs, ys });
  }
  lineChart($("ratio-chart"), series.slice(0, COLORS.length), { legend: false });
}

function matchesFilter(name, idx, filter) {
  if (!filter) return true;
  const f = filter.toLowerCase();
  return name.toLowerCase().includes(f) || String(idx) === f;
}

function renderStats(stats, filter) {
  const grid = $("hist-grid");
  grid.innerHTML = "";
  if (!stats) {
    grid.innerHTML = "<div class='cell'><div class='title'>no stats yet</div></div>";
    return;
  }
  const addCell = (title, meta, draw) => {
    const cell = document.createElement("div");
    cell.className = "cell";
    const canvas = document.createElement("canvas");
    canvas.width = 300; canvas.height = 120;
    cell.innerHTML = `<div class="title">${title}</div><div class="meta">${meta}</div>`;
    cell.appendChild(canvas);
    grid.appendChild(cell);
    draw(canvas);
  };
  const addHistCell = (title, meta, histData) =>
    addCell(title, meta, (canvas) => histChart(canvas, histData));

  (stats.layers || []).forEach((layer, i) => {
    if (!layer || !matchesFilter(layer.algo, i, filter)) return;
    const act = layer.activation;
    addHistCell(`L${i} ${layer.algo} activations`,
      `μ=${act.mean.toPrecision(3)} σ=${act.std.toPrecision(3)} ` +
      `sat=${(act.saturated * 100).toFixed(1)}%`, act.histogram);
    if (layer.gradient) {
      addHistCell(`L${i} ${layer.algo} ∂cost/∂act`,
        `μ=${layer.gradient.mean.toPrecision(3)} σ=${layer.gradient.std.toPrecision(3)}`,
        layer.gradient.histogram);
    }
  });
  (stats.weights || []).forEach((wstat, i) => {
    if (!wstat || !matchesFilter("weight " + wstat.shape, i, filter)) return;
    addHistCell(`W${i} ${wstat.shape} ∂cost/∂w`,
      `w: μ=${wstat.data.mean.toPrecision(3)} σ=${wstat.data.std.toPrecision(3)}`,
      wstat.gradient.histogram);
  });
  // MoE routing: per-expert fraction bars (uniform = balanced; a single
  // tall bar = expert collapse).
  Object.entries(stats.moe_router_fractions || {}).forEach(([name, fr]) => {
    if (!matchesFilter(name, -1, filter)) return;
    const max = Math.max(...fr, 1e-9);
    addCell(name, `${fr.length} experts, max=${(max * 100).toFixed(1)}%`,
      (canvas) => drawBars(canvas, fr, "#4c8dd6"));
  });
}

/* ---- serving tile (continuous batching, /serving_stats/) --------------- */

/* Rolling client-side history so the tile shows a trajectory, not just the
 * latest sample (the endpoint reports instantaneous aggregates). */
const servingHistory = [];

function renderServing(data) {
  const meta = $("serving-meta");
  const canvas = $("serving-chart");
  if (!meta || !canvas) return;
  if (!data) {
    meta.textContent = "serving stats unavailable";
    return;
  }
  const drops = data.kv_pool_capacity_drops || 0;
  if (!data.continuous_batching_enabled && !(data.engines || []).length) {
    meta.textContent =
      `continuous batching off (PENROZ_CONTINUOUS_BATCHING=1 to enable)` +
      ` · KV pool drops ${drops}`;
    lineChart(canvas, []);
    return;
  }
  const occ = data.batch_occupancy || 0;
  const tps = data.decode_tokens_per_sec || 0;
  /* Prefix-cache + chunked-prefill observability (null-safe: the fields
   * only carry values when PENROZ_PREFIX_CACHE / chunked admission ran). */
  const hitRate = data.prefix_cache_hit_rate;
  const prefixTxt = hitRate == null ? "prefix cache off"
    : `prefix hits ${(hitRate * 100).toFixed(0)}% · evicted ` +
      `${data.prefix_cache_evicted_pages || 0} pages`;
  const stall = data.prefill_chunk_stall_ms_p99;
  /* Speculative decoding (PENROZ_SPEC_DECODE=1): accept rate of the
   * prompt-lookup drafts and tokens emitted per decode step — the >1
   * tokens/step headroom speculation buys (null-safe: accept rate is
   * null until the first draft). */
  const acceptRate = data.spec_accept_rate;
  const specTxt = !data.spec_decode_enabled ? "spec off"
    : `spec accept ${acceptRate == null ? "—"
         : (acceptRate * 100).toFixed(0) + "%"} · ` +
      `${(data.tokens_per_decode_step || 0).toFixed(2)} tok/step`;
  /* Compiled multi-step decode (PENROZ_SCHED_SUPERSTEP): tokens emitted
   * per device dispatch — ≈ the superstep size when fused decode runs
   * unconstrained, 1.0 on the legacy per-token dispatch loop (null-safe:
   * no value until the first decode dispatch). */
  const tpd = data.tokens_per_dispatch_avg;
  const multistepTxt = tpd == null
    ? `${data.dispatches_total || 0} dispatches`
    : `${tpd.toFixed(2)} tok/dispatch (${data.dispatches_total || 0} ` +
      `dispatches)`;
  /* Fault-tolerance readouts (PR 3): shed/timeout counters and the engine
   * circuit breaker — an open breaker is the "stop paging the dashboard,
   * the engine is crash-looping" signal. */
  /* Multi-tenant LoRA (PENROZ_LORA_MAX_LIVE slots per engine): live
   * adapters sharing the decode batch and the rows currently bound to
   * one — "lora off" until any adapter occupies a slot. */
  const loraAdapters = data.lora_active_adapters || 0;
  const loraTxt = loraAdapters === 0 ? "lora off"
    : `lora ${loraAdapters} adapters · ${data.lora_rows || 0} rows`;
  /* Constant-memory sequence rows (ops/ssm.py): rows carrying O(1)
   * recurrent state and the (generation-length-independent) HBM bytes of
   * their state planes — "ssm off" when no served arch has ssm blocks. */
  const ssmBytes = data.ssm_state_bytes || 0;
  const ssmTxt = ssmBytes === 0 ? "ssm off"
    : `ssm ${data.ssm_rows || 0} rows · ` +
      `${(ssmBytes / (1024 * 1024)).toFixed(1)}MB state`;
  const crashes = data.crashes_total || 0;
  const breakerTxt = data.breaker_open
    ? `breaker OPEN (${crashes} crashes, ${data.engine_resets || 0} resets)`
    : `breaker ok (${crashes} crashes)`;
  const shedTxt = `shed ${data.queue_rejections || 0} · ` +
    `quota shed ${data.quota_rejections || 0} · ` +
    `timeouts ${data.deadline_timeouts || 0}`;
  /* Multi-tenant QoS (serve/qos.py): per-class p99 TTFT breakdown, the
   * preemption counter with its zero-recompute resume credit, and the
   * per-tenant token totals — "qos idle" until any non-default class,
   * tenant, or preemption shows up. */
  const ttftCls = data.ttft_ms_p99_by_class || {};
  const clsTxt = ["interactive", "standard", "batch"]
    .filter((c) => ttftCls[c] != null)
    .map((c) => `${c.slice(0, 5)} ${ttftCls[c].toFixed(0)}ms`)
    .join(" / ");
  const tenants = Object.entries(data.tenant_tokens || {});
  const tenantTxt = tenants.length === 0 ? ""
    : ` · tenants ${tenants.slice(0, 4)
        .map(([t, n]) => `${t}:${n}`).join(" ")}` +
      (tenants.length > 4 ? ` +${tenants.length - 4}` : "");
  const preempts = data.preemptions_total || 0;
  const qosTxt = (!clsTxt && !preempts && !tenants.length) ? "qos idle"
    : `ttft p99 [${clsTxt || "—"}] · preempts ${preempts} ` +
      `(${data.preempted_resume_cached_tokens || 0} tok resumed cached)` +
      tenantTxt;
  /* Replica router (PENROZ_SCHED_REPLICAS > 1): affinity hit rate of the
   * prefix-fingerprint steering plus the failover count — "router off"
   * on the single-engine registry. */
  const replicas = data.router_replicas || 0;
  const affRate = data.router_affinity_hit_rate;
  const routerTxt = replicas === 0 ? "router off"
    : `router ${replicas} replicas · affinity ` +
      `${affRate == null ? "—" : (affRate * 100).toFixed(0) + "%"} · ` +
      `failovers ${data.router_failovers || 0}`;
  /* Disaggregated prefill (PENROZ_DISAGG_PREFILL=1): per-replica role
   * chips (P = prefill-only, D = decode) plus the hand-off health line —
   * "disagg off" when no prefill replica is live. */
  const prefillReplicas = data.disagg_prefill_replicas || 0;
  const roleChips = (data.engines || [])
    .map((e) => `r${e.replica}:${(e.role || "decode")[0].toUpperCase()}`)
    .join(" ");
  const handoffP99 = data.disagg_handoff_ms_p99;
  const roleChanges = data.disagg_role_changes || 0;
  const disaggTxt = prefillReplicas === 0 ? "disagg off"
    : `disagg ${roleChips} · ${data.disagg_transport || "d2d"} · ` +
      `handoffs ${data.disagg_imports || 0} ` +
      `(${data.disagg_handoff_failures || 0} failed) · handoff p99 ` +
      `${handoffP99 == null ? "—" : handoffP99.toFixed(0) + "ms"}` +
      `${roleChanges ? ` · flips ${roleChanges}` : ""}`;
  /* Pipeline-parallel serving (PENROZ_SERVE_PIPE_STAGES >= 2): stage
   * count of the widest group, the lifetime bubble (idle stage-tick)
   * fraction from the schedule telemetry, and hand-off health — a
   * nonzero host-fallback count means a pipe.handoff fault re-staged
   * activations through the host (contained, numerics identical).
   * "pipe off" on unpiped engines. */
  const pipeStages = data.pipe_stages || 1;
  const bubble = data.pipe_bubble_fraction;
  const pipeTxt = pipeStages <= 1 ? "pipe off"
    : `pipe ${pipeStages} stages · bubble ` +
      `${bubble == null ? "—" : (bubble * 100).toFixed(0) + "%"} · ` +
      `handoffs ${data.pipe_handoffs || 0}` +
      `${data.pipe_handoff_host_fallbacks
         ? ` (${data.pipe_handoff_host_fallbacks} host)` : ""}`;
  /* Session hibernation / KV tiering (session_id on /generate/): resident
   * sessions split by tier, promotion outcome tallies, and the resume-TTFT
   * tail — "sessions off" until any session hibernates. */
  const resident = data.sessions_resident || 0;
  const byTier = data.sessions_by_tier || {};
  const promos = data.tier_promotions || {};
  const promoOk = (promos.ok || 0) + (promos.partial || 0);
  const promoBad = (promos.stale || 0) + (promos.corrupt || 0) +
    (promos.miss || 0);
  const resumeP99 = data.session_resume_ttft_ms_p99;
  const tierTxt = (resident === 0 && !data.sessions_hibernated)
    ? "sessions off"
    : `sessions ${resident} (hbm ${byTier.hbm || 0} / host ` +
      `${byTier.host || 0} / disk ${byTier.disk || 0}) · wakes ` +
      `${promoOk}${promoBad ? ` (${promoBad} missed)` : ""} · resume p99 ` +
      `${resumeP99 == null ? "—" : resumeP99.toFixed(0) + "ms"}` +
      `${data.tier_corrupt_blobs ? ` · CORRUPT ${data.tier_corrupt_blobs}`
         : ""}`;
  /* Crash durability (PR 18): write-ahead journal health, what the last
   * restart recovered, detached-but-running resumable streams, and the
   * tick watchdog — "journal off" when PENROZ_JOURNAL_PATH is unset. */
  const jr = data.journal || {};
  const rec = data.restart_recovery || {};
  const streams = data.streams || {};
  const stuck = data.engines_stuck || 0;
  const durTxt = (!jr.enabled && !streams.active && !stuck)
    ? "journal off"
    : `journal ${jr.records || 0} rec` +
      `${jr.append_errors ? ` (${jr.append_errors} ERR)` : ""}` +
      `${jr.bad_records ? ` · torn ${jr.bad_records}` : ""}` +
      `${rec.sessions_recovered ? ` · restored ${rec.sessions_recovered}`
         : ""}` +
      `${streams.detached ? ` · detached streams ${streams.detached}`
         : ""}` +
      `${stuck ? ` · STUCK ${stuck}` : ""}`;
  meta.textContent =
    `rows ${data.active_rows}/${data.capacity} (occupancy ` +
    `${(occ * 100).toFixed(0)}%) · queue ${data.queue_depth} · ` +
    `${shedTxt} · ${breakerTxt}` +
    `${data.draining ? " · DRAINING" : ""} · ` +
    `${tps.toFixed(1)} tok/s · adm p50 ` +
    `${data.admission_latency_ms_p50 == null ? "—"
       : data.admission_latency_ms_p50.toFixed(1) + "ms"} · ` +
    `chunk stall p99 ${stall == null ? "—" : stall.toFixed(1) + "ms"} · ` +
    `${multistepTxt} · ` +
    `${specTxt} · ${loraTxt} · ${ssmTxt} · ${prefixTxt} · ${qosTxt} · ` +
    `${routerTxt} · ` +
    `${disaggTxt} · ${pipeTxt} · ${tierTxt} · ${durTxt} · ` +
    `KV pool drops ${drops}`;
  servingHistory.push({ occ: occ * 100, tps });
  if (servingHistory.length > 200) servingHistory.shift();
  const xs = servingHistory.map((_, i) => i);
  lineChart(canvas, [
    { name: "tokens/sec", xs, ys: servingHistory.map(h => h.tps) },
    { name: "occupancy %", xs, ys: servingHistory.map(h => h.occ) },
  ], { legend: true });
}

/* ---- tick telemetry strip (/serving_stats/ tick_timeline) -------------- */

/* Bars: per-tick dispatch wall time, colored by phase composition
 * (unified mixed > prefill chunk > verify > plain shared step); line:
 * batch occupancy.  This is the "what is the tick loop actually doing
 * between dispatches" panel — a green bar is a ragged unified tick whose
 * ONE dispatch carried prefill chunks alongside decode rows, a tall
 * amber bar is a phased chunk stall, a purple run is spec-decode verify
 * traffic, the teal line sagging is an underfed batch. */
function renderTickStrip(data) {
  const canvas = $("tick-strip");
  const meta = $("tick-meta");
  if (!canvas || !meta) return;
  const timeline = (data && data.tick_timeline) || [];
  if (!timeline.length) {
    meta.textContent = "no ticks yet";
    prepCanvas(canvas);
    return;
  }
  const fmt = (v) => (v == null ? "—" : v.toFixed(1) + "ms");
  meta.textContent =
    `${timeline.length} recent ticks · dispatch p50 ${fmt(data.tick_ms_p50)}` +
    ` p99 ${fmt(data.tick_ms_p99)} · itl p50 ${fmt(data.itl_ms_p50)}` +
    ` p99 ${fmt(data.itl_ms_p99)} · ttft p99 ${fmt(data.ttft_ms_p99)}`;
  const ticks = timeline.slice().reverse();  // chronological left → right
  const ctx = prepCanvas(canvas);
  const w = canvas.width, h = canvas.height, pad = 8;
  const hi = Math.max(...ticks.map(t => t.dispatch_ms), 1e-9);
  const bw = (w - 2 * pad) / ticks.length;
  ticks.forEach((t, i) => {
    const bh = Math.max(1, t.dispatch_ms / hi * (h - 2 * pad));
    ctx.fillStyle =
      t.unified && t.prefill_chunks > 0 && t.shared_rows > 0 ? "#98c379"
      : t.prefill_chunks > 0 ? "#e0b35c"
      : t.verify_rows > 0 ? "#b58cd9" : "#7aa2f7";
    ctx.fillRect(pad + i * bw, h - pad - bh, Math.max(1, bw - 1), bh);
  });
  ctx.strokeStyle = "#7fd1b9";
  ctx.lineWidth = 1.5;
  ctx.beginPath();
  ticks.forEach((t, i) => {
    const x = pad + i * bw + bw / 2;
    const y = h - pad - t.occupancy * (h - 2 * pad);
    if (i === 0) ctx.moveTo(x, y); else ctx.lineTo(x, y);
  });
  ctx.stroke();
  drawLabel(ctx, `${hi.toFixed(1)}ms`, 4, 12);
  drawLabel(ctx, "mixed", w - 248, 12, "#98c379");
  drawLabel(ctx, "chunk", w - 200, 12, "#e0b35c");
  drawLabel(ctx, "verify", w - 150, 12, "#b58cd9");
  drawLabel(ctx, "step", w - 100, 12, "#7aa2f7");
  drawLabel(ctx, "occupancy", w - 68, 12, "#7fd1b9");
}

/* ---- HBM capacity ledger (/memory/) ------------------------------------ */

/* Owner states in stacked-bar order (occupied states bottom-up, free on
 * top) with their colors — mirrors serve/memledger.py PAGE_STATES. */
const MEM_STATES = ["row", "prefix_pinned", "prefix_evictable", "preempted",
                    "hibernating", "reserved", "free"];
const MEM_COLORS = {
  row: "#7aa2f7", prefix_pinned: "#b58cd9", prefix_evictable: "#56b6c2",
  preempted: "#d19a66", hibernating: "#c678dd", reserved: "#5d7285",
  free: "#22303c",
};

function fmtBytes(n) {
  if (n >= 1073741824) return (n / 1073741824).toFixed(2) + "GiB";
  if (n >= 1048576) return (n / 1048576).toFixed(1) + "MiB";
  if (n >= 1024) return (n / 1024).toFixed(1) + "KiB";
  return `${n}B`;
}

/* Rolling client-side stacked history of the pool page states (same idea
 * as servingHistory: /memory/ reports an instantaneous partition). */
const memoryHistory = [];

function renderMemory(data) {
  const meta = $("memory-meta");
  const canvas = $("memory-chart");
  if (!meta || !canvas) return;
  if (!data) {
    meta.textContent = "memory ledger unavailable";
    prepCanvas(canvas);
    return;
  }
  if (!data.memledger_enabled) {
    meta.textContent = "memory ledger off (PENROZ_MEMLEDGER=1 to enable)";
    prepCanvas(canvas);
    return;
  }
  const pool = data.pool_pages || {};
  const total = MEM_STATES.reduce((a, s) => a + (pool[s] || 0), 0);
  const used = total - (pool.free || 0);
  const hwmUsed = (data.high_water_pages || {}).used || 0;
  const pagesTxt = total === 0
    ? "no paged pool (PAGED_KV_CACHE=1 for page-granular attribution)"
    : `pages ${used}/${total} used (rows ${pool.row || 0} · pinned ` +
      `${pool.prefix_pinned || 0} · evictable ` +
      `${pool.prefix_evictable || 0} · preempted ${pool.preempted || 0} ` +
      `· hibernating ${pool.hibernating || 0} ` +
      `· reserved ${pool.reserved || 0} · free ${pool.free || 0}) · ` +
      `hwm ${hwmUsed}`;
  const tenants = Object.entries(data.tenant_pages || {});
  const tenantTxt = tenants.length === 0 ? ""
    : ` · tenant pages ${tenants.slice(0, 4)
        .map(([t, n]) => `${t}:${n}`).join(" ")}` +
      (tenants.length > 4 ? ` +${tenants.length - 4}` : "");
  const hbm = data.hbm_bytes || {};
  const hbmTotal = Object.values(hbm).reduce((a, b) => a + b, 0);
  const kvBytes = (hbm.kv_values || 0) + (hbm.kv_scales || 0) +
    (hbm.kv_block_table || 0);
  /* Hibernated-session blob bytes live OFF the device — call them out
   * separately so the tile reads "HBM X (kv Y) · tiered Z". */
  const tierBytes = (hbm.host_tier || 0) + (hbm.disk_tier || 0);
  const hbmTxt = ` · HBM ${fmtBytes(hbmTotal - tierBytes)} ` +
    `(kv ${fmtBytes(kvBytes)})` +
    (tierBytes ? ` · tiered ${fmtBytes(tierBytes)} ` +
      `(host ${fmtBytes(hbm.host_tier || 0)} / disk ` +
      `${fmtBytes(hbm.disk_tier || 0)})` : "");
  const tte = data.time_to_exhaustion_s;
  const tteTxt = ` · exhaustion ${tte == null ? "—" : tte.toFixed(0) + "s"}`;
  /* Leak/pressure health readouts: any nonzero underflow or audit
   * failure is a pin-accounting bug, not load. */
  const healthTxt = ` · pool drops ${data.kv_pool_capacity_drops || 0}` +
    ` · underflows ${data.unpin_underflows || 0}` +
    ` · audit failures ${data.audit_failures || 0}` +
    ` · flight records ${data.flight_records || 0}`;
  meta.textContent = pagesTxt + tenantTxt + hbmTxt + tteTxt + healthTxt;

  memoryHistory.push({ pool, total });
  if (memoryHistory.length > 200) memoryHistory.shift();
  const ctx = prepCanvas(canvas);
  const w = canvas.width, h = canvas.height, pad = 8;
  const hi = Math.max(...memoryHistory.map((m) => m.total), 1);
  const bw = (w - 2 * pad) / memoryHistory.length;
  memoryHistory.forEach((m, i) => {
    let y = h - pad;
    MEM_STATES.forEach((s) => {
      const bh = (m.pool[s] || 0) / hi * (h - 2 * pad);
      if (bh <= 0) return;
      ctx.fillStyle = MEM_COLORS[s];
      ctx.fillRect(pad + i * bw, y - bh, Math.max(1, bw - 1), bh);
      y -= bh;
    });
  });
  drawLabel(ctx, `${hi} pages`, 4, 12);
  let lx = w - 516;
  MEM_STATES.forEach((s) => {
    drawLabel(ctx, s.replace("prefix_", ""), lx, 12, MEM_COLORS[s]);
    lx += 74;
  });
}

/* ---- per-request trace waterfall (/trace/, /trace/{id}) ---------------- */

const SPAN_COLORS = {
  queue: "#5d7285", prefill: "#e0b35c", prefill_chunk: "#c77d0a",
  decode: "#7aa2f7", decode_step: "#56b6c2", verify: "#b58cd9",
  recovery: "#e06c75", legacy_generate: "#98c379",
  preempt: "#d19a66", resume: "#7fd1b9",
};

function flattenSpans(span, depth, out) {
  out.push({ span, depth });
  (span.children || []).forEach((c) => flattenSpans(c, depth + 1, out));
  return out;
}

function renderWaterfall(tree) {
  const canvas = $("trace-waterfall");
  const meta = $("trace-meta");
  if (!canvas || !meta) return;
  if (!tree || !tree.root) {
    meta.textContent =
      "no traces yet (serve a /generate/ request, or paste a request id)";
    prepCanvas(canvas);
    return;
  }
  const total = tree.root.duration_ms != null ? tree.root.duration_ms
    : Math.max(1, ...flattenSpans(tree.root, 0, [])
        .map(r => r.span.t1_ms == null ? r.span.t0_ms : r.span.t1_ms));
  const reason = (tree.meta && tree.meta.retire_reason) ||
    (tree.finished ? "finished" : "in flight");
  meta.textContent = `request ${tree.request_id} · ` +
    `${total.toFixed(1)}ms · ${reason}` +
    (tree.dropped_spans ? ` · ${tree.dropped_spans} spans dropped` : "");
  const rows = flattenSpans(tree.root, 0, []).slice(0, 24);
  const ctx = prepCanvas(canvas);
  const w = canvas.width, pad = 6, rowH = 15;
  const sx = (ms) => pad + 170 + (ms / Math.max(total, 1e-9))
    * (w - pad * 2 - 170);
  rows.forEach(({ span, depth }, i) => {
    const y = pad + i * rowH;
    const t0 = span.t0_ms || 0;
    const t1 = span.t1_ms == null ? total : span.t1_ms;
    ctx.fillStyle = SPAN_COLORS[span.name] || "#3f7f6b";
    ctx.fillRect(sx(t0), y + 3, Math.max(2, sx(t1) - sx(t0)), rowH - 5);
    const dur = span.duration_ms == null ? "…"
      : span.duration_ms.toFixed(1) + "ms";
    drawLabel(ctx, `${"  ".repeat(depth)}${span.name} ${dur}`,
              pad, y + rowH - 3);
  });
}

async function refreshTrace() {
  const input = $("trace-id");
  let id = input ? input.value.trim() : "";
  try {
    if (!id) {
      const list = await fetchJson("/trace/");
      if (list.traces && list.traces.length) id = list.traces[0].request_id;
      else if (list.live && list.live.length) id = list.live[0].request_id;
    }
    renderWaterfall(id
      ? await fetchJson(`/trace/${encodeURIComponent(id)}`) : null);
  } catch (e) {
    renderWaterfall(null);
  }
}

async function refresh() {
  const modelId = $("model-id").value.trim();
  const filter = $("layer-filter").value.trim();
  setQueryState(modelId, filter);
  try {
    const serving = await fetchJson("/serving_stats/");
    renderServing(serving);
    renderTickStrip(serving);
  } catch (e) {
    renderServing(null);
    renderTickStrip(null);
  }
  try {
    renderMemory(await fetchJson("/memory/"));
  } catch (e) {
    renderMemory(null);
  }
  await refreshTrace();
  if (!modelId) return;
  try {
    const progress = await fetchJson(`/progress/?model_id=${encodeURIComponent(modelId)}`);
    renderProgress(progress);
  } catch (e) {
    $("status-badge").textContent = "not found";
    $("status-badge").className = "badge err";
    return;
  }
  try {
    const stats = await fetchJson(`/stats/?model_id=${encodeURIComponent(modelId)}`);
    renderStats(stats, filter);
  } catch (e) {
    renderStats(null, filter);
  }
}

let autoTimer = null;
function setupAuto() {
  if (autoTimer) { clearInterval(autoTimer); autoTimer = null; }
  if ($("auto-refresh").checked) autoTimer = setInterval(refresh, 5000);
}

window.addEventListener("DOMContentLoaded", () => {
  const state = getQueryState();
  $("model-id").value = state.modelId;
  $("layer-filter").value = state.filter;
  $("refresh-btn").addEventListener("click", refresh);
  $("auto-refresh").addEventListener("change", setupAuto);
  [$("model-id"), $("layer-filter"), $("trace-id")].forEach(el => {
    if (el) el.addEventListener("keydown",
      (e) => { if (e.key === "Enter") refresh(); });
  });
  if (state.modelId) refresh();
});
