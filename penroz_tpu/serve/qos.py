"""SLO-tiered multi-tenant QoS: priority classes, weighted fair admission,
and per-tenant token-rate quotas.

PR 3 made overload survivable (bounded queue, deadlines, breaker) but every
tenant still shared a single FIFO: one batch tenant flooding ``/generate/``
inflated interactive p99 TTFT and the 429s landed on the victim.  This
module holds the two host-side policy pieces the scheduler composes into
SLO isolation:

- :class:`WFQueue` — the admission queue as per-``(tenant, class)``
  sub-queues drained by deficit-weighted round robin.  Each sub-queue earns
  ``weight(class)`` pops per scheduling round, so an interactive trickle
  keeps draining at its weighted share no matter how deep a batch tenant's
  backlog grows.  Every mutation happens under the engine's condition lock
  (the class itself is not internally locked — same discipline as the
  ``collections.deque`` it replaces).
- :class:`QuotaManager` — a token bucket per tenant id over *emitted +
  prefilled* tokens.  An exhausted bucket 429s that tenant's NEW admissions
  (with a refill-derived ``Retry-After``) while its in-flight rows run to
  completion; other tenants never see the shed.

Knobs::

    PENROZ_QOS_WEIGHTS             interactive:8,standard:4,batch:1
    PENROZ_QOS_MAX_QUEUE_<CLASS>   per-class queue bound (0 = unbounded)
    PENROZ_SCHED_MAX_QUEUE         aggregate bound (fallback; pre-QoS env)
    PENROZ_QOS_TENANT_TOKENS_PER_S default tenant token rate (0 = unlimited)
    PENROZ_QOS_PREEMPT             1 (default) = interactive arrivals may
                                   preempt lower-class rows (scheduler-side)

Per-tenant rate overrides arrive via ``PUT /tenants/{id}/quota`` and live
only in :data:`QUOTAS` (process state, not env).  Tenant identity is the
explicit ``tenant`` field when given, else the LoRA ``adapter`` id, else
``"default"`` — so adapter-per-tenant deployments (PR 5) get quotas with
zero request changes.
"""

from __future__ import annotations

import collections
import math
import os
import threading
import time

PRIORITIES = ("interactive", "standard", "batch")
DEFAULT_PRIORITY = "standard"
DEFAULT_TENANT = "default"

WEIGHTS_ENV = "PENROZ_QOS_WEIGHTS"
_DEFAULT_WEIGHTS = {"interactive": 8, "standard": 4, "batch": 1}
CLASS_QUEUE_ENVS = {
    cls: f"PENROZ_QOS_MAX_QUEUE_{cls.upper()}" for cls in PRIORITIES}
TENANT_RATE_ENV = "PENROZ_QOS_TENANT_TOKENS_PER_S"
TENANT_TIER_ENV = "PENROZ_QOS_TENANT_TIER_MB"
PREEMPT_ENV = "PENROZ_QOS_PREEMPT"


def validate_priority(priority) -> str:
    if priority is None:
        return DEFAULT_PRIORITY
    if priority not in PRIORITIES:
        raise ValueError(
            f"priority must be one of {PRIORITIES}, got {priority!r}")
    return priority


def tenant_of(tenant, adapter) -> str:
    """Tenant identity: explicit field > adapter id > shared default."""
    if tenant:
        return str(tenant)
    if adapter:
        return str(adapter)
    return DEFAULT_TENANT


def weights() -> dict:
    """Per-class DRR weights from ``PENROZ_QOS_WEIGHTS`` (unlisted classes
    keep their defaults; junk entries are ignored, never fatal — a typo in
    an env var must not take serving down)."""
    out = dict(_DEFAULT_WEIGHTS)
    spec = os.environ.get(WEIGHTS_ENV, "")
    for part in spec.split(","):
        part = part.strip()
        if not part or ":" not in part:
            continue
        cls, _, w = part.partition(":")
        cls = cls.strip()
        try:
            w = int(w)
        except ValueError:
            continue
        if cls in _DEFAULT_WEIGHTS and w >= 1:
            out[cls] = w
    return out


def class_queue_bound(cls: str) -> int | None:
    """Per-class queue bound, or None when only the aggregate bound (the
    pre-QoS ``PENROZ_SCHED_MAX_QUEUE``) applies.  0 = explicitly unbounded."""
    raw = os.environ.get(CLASS_QUEUE_ENVS[cls])
    if raw is None:
        return None
    try:
        return max(0, int(raw))
    except ValueError:
        return None


def preempt_enabled() -> bool:
    return os.environ.get(PREEMPT_ENV, "1") == "1"


class WFQueue:
    """Per-(tenant, class) sub-queues drained by deficit round robin with
    unit cost: on each visit a sub-queue's deficit grows by its class
    weight and every pop spends 1, so over a full rotation each active
    sub-queue is served proportionally to its weight.  With only default
    traffic (one sub-queue) this degrades to the exact FIFO it replaced."""

    def __init__(self):
        self._queues: dict = {}          # (tenant, cls) -> deque[Request]
        self._active: list = []          # rotation order of non-empty keys
        self._deficits: dict = {}
        self._cursor = 0
        self._len = 0
        self._class_depth = collections.Counter()
        self._class_tokens = collections.Counter()

    def __len__(self) -> int:
        return self._len

    def __bool__(self) -> bool:
        return self._len > 0

    def class_depth(self, cls: str) -> int:
        return self._class_depth[cls]

    def class_tokens(self, cls: str) -> int:
        """Queued PROMPT tokens of ``cls`` — the router's within-class
        load signal (a 100k-token prompt is not the same wait as a
        20-token one, which equal queue *depths* would claim)."""
        return self._class_tokens[cls]

    def class_depths(self) -> dict:
        return {cls: self._class_depth[cls] for cls in PRIORITIES}

    def tenant_depths(self) -> dict:
        """Queued requests per tenant (flight-recorder postmortems: WHOSE
        work was waiting when the engine died)."""
        depths: dict = {}
        for (tenant, _cls), dq in self._queues.items():
            if dq:
                depths[tenant] = depths.get(tenant, 0) + len(dq)
        return depths

    def _key(self, req):
        return (req.tenant, req.priority)

    def _activate(self, key):
        if key not in self._deficits:
            self._deficits[key] = 0
            self._active.append(key)

    def push(self, req) -> None:
        key = self._key(req)
        dq = self._queues.get(key)
        if dq is None:
            dq = self._queues[key] = collections.deque()
        dq.append(req)
        self._activate(key)
        self._len += 1
        self._class_depth[req.priority] += 1
        self._class_tokens[req.priority] += len(req.prompt)

    def push_front(self, req) -> None:
        """Head-requeue (adapter-slot-busy backoff, preemption resume):
        the request must be the next one served from its sub-queue."""
        key = self._key(req)
        dq = self._queues.get(key)
        if dq is None:
            dq = self._queues[key] = collections.deque()
        dq.appendleft(req)
        self._activate(key)
        self._len += 1
        self._class_depth[req.priority] += 1
        self._class_tokens[req.priority] += len(req.prompt)

    def _retire_key(self, idx, key):
        self._active.pop(idx)
        self._deficits.pop(key, None)
        self._queues.pop(key, None)
        if self._cursor > idx:
            self._cursor -= 1

    def _take(self, idx, key):
        req = self._queues[key].popleft()
        self._len -= 1
        self._class_depth[req.priority] -= 1
        self._class_tokens[req.priority] -= len(req.prompt)
        if not self._queues[key]:
            self._retire_key(idx, key)
        return req

    def pop(self):
        """Next request by DRR order (None when empty)."""
        wts = None
        while self._active:
            if self._cursor >= len(self._active):
                self._cursor = 0
            key = self._active[self._cursor]
            if not self._queues.get(key):
                self._retire_key(self._cursor, key)
                continue
            if self._deficits[key] >= 1:
                self._deficits[key] -= 1
                return self._take(self._cursor, key)
            if wts is None:
                wts = weights()
            self._deficits[key] += wts.get(key[1], 1)
            self._cursor += 1
        return None

    def pop_class(self, cls: str):
        """Oldest queued request of ``cls`` across tenants (the preemption
        admit path pulls the waiting interactive request specifically —
        DRR order would happily hand the freed row to the flood)."""
        best_idx, best_key, best_t = None, None, None
        for idx, key in enumerate(self._active):
            if key[1] != cls:
                continue
            dq = self._queues.get(key)
            if not dq:
                continue
            t = dq[0].enqueue_t
            if best_t is None or t < best_t:
                best_idx, best_key, best_t = idx, key, t
        if best_key is None:
            return None
        return self._take(best_idx, best_key)

    def oldest_enqueue_t(self):
        """Earliest head-of-queue enqueue time (burst-coalescing probe)."""
        heads = [dq[0].enqueue_t for dq in self._queues.values() if dq]
        return min(heads) if heads else None

    def purge(self, should_drop) -> list:
        """Remove (and return, in FIFO order per sub-queue) every queued
        request for which ``should_drop(req)`` is true."""
        dropped = []
        for key in list(self._queues):
            dq = self._queues[key]
            keep = collections.deque()
            for req in dq:
                if should_drop(req):
                    dropped.append(req)
                    self._len -= 1
                    self._class_depth[req.priority] -= 1
                    self._class_tokens[req.priority] -= len(req.prompt)
                else:
                    keep.append(req)
            if keep:
                self._queues[key] = keep
            else:
                idx = self._active.index(key) if key in self._deficits else -1
                if idx >= 0:
                    self._retire_key(idx, key)
                else:
                    self._queues.pop(key, None)
        return dropped

    def drain(self) -> list:
        """Remove and return everything (engine failure path)."""
        out = []
        for key in list(self._queues):
            out.extend(self._queues[key])
        self._queues.clear()
        self._active.clear()
        self._deficits.clear()
        self._cursor = 0
        self._len = 0
        self._class_depth.clear()
        self._class_tokens.clear()
        return out

    def __iter__(self):
        for dq in self._queues.values():
            yield from dq


class TenantQuotaExceeded(RuntimeError):
    """Raised at submit when the tenant's token bucket is exhausted;
    carries the refill-derived ``Retry-After`` hint."""

    def __init__(self, tenant: str, retry_after: int):
        super().__init__(
            f"tenant {tenant!r} token quota exhausted; "
            f"retry in ~{retry_after}s")
        self.tenant = tenant
        self.retry_after = int(retry_after)


class _Bucket:
    __slots__ = ("tokens", "last")

    def __init__(self, tokens: float, last: float):
        self.tokens = tokens
        self.last = last


class QuotaManager:
    """Per-tenant token buckets over emitted + prefilled tokens.

    Rate 0 (the default) disables quota for that tenant entirely — no
    bucket state is even kept, so the pre-QoS deployment pays nothing.
    Burst capacity is one second of rate; :meth:`charge` may drive a
    bucket negative (in-flight rows finish their work), which simply
    extends the refill time the next admission's 429 reports."""

    def __init__(self):
        self._lock = threading.Lock()
        self._buckets: dict[str, _Bucket] = {}
        self._overrides: dict[str, float] = {}
        self._tier_overrides: dict[str, float] = {}
        self.rejections = collections.Counter()   # tenant -> shed count
        self.charged = collections.Counter()      # tenant -> tokens charged

    def _env_rate(self) -> float:
        try:
            return max(0.0, float(os.environ.get(TENANT_RATE_ENV, "0")))
        except ValueError:
            return 0.0

    def rate_for(self, tenant: str) -> float:
        with self._lock:
            if tenant in self._overrides:
                return self._overrides[tenant]
        return self._env_rate()

    def set_rate(self, tenant: str, rate: float | None) -> None:
        """Admin override (``PUT /tenants/{id}/quota``); None clears it
        back to the env default."""
        with self._lock:
            if rate is None:
                self._overrides.pop(tenant, None)
            else:
                self._overrides[tenant] = max(0.0, float(rate))
            self._buckets.pop(tenant, None)   # re-seed at the new burst

    def overrides(self) -> dict:
        with self._lock:
            return dict(self._overrides)

    def _env_tier_mb(self) -> float:
        try:
            return max(0.0, float(os.environ.get(TENANT_TIER_ENV, "0")))
        except ValueError:
            return 0.0

    def tier_bytes_for(self, tenant: str) -> float:
        """The tenant's hibernated-KV residency cap in BYTES (tier store
        admission, serve/tierstore.py).  0 = unlimited — like token rate
        0, the default deployment pays nothing for the machinery."""
        with self._lock:
            if tenant in self._tier_overrides:
                return self._tier_overrides[tenant] * 1e6
        return self._env_tier_mb() * 1e6

    def set_tier_mb(self, tenant: str, mb: float | None) -> None:
        """Admin override of the tier-residency cap (``PUT
        /tenants/{id}/quota``); None clears back to the env default."""
        with self._lock:
            if mb is None:
                self._tier_overrides.pop(tenant, None)
            else:
                self._tier_overrides[tenant] = max(0.0, float(mb))

    def tier_overrides(self) -> dict:
        with self._lock:
            return dict(self._tier_overrides)

    def _refill(self, tenant: str, rate: float, now: float) -> _Bucket:
        # Callers hold self._lock.
        burst = max(rate, 1.0)
        b = self._buckets.get(tenant)
        if b is None:
            b = self._buckets[tenant] = _Bucket(burst, now)
            return b
        b.tokens = min(burst, b.tokens + (now - b.last) * rate)
        b.last = now
        return b

    def admit(self, tenant: str, now: float | None = None) -> None:
        """Gate a new admission; raises :class:`TenantQuotaExceeded` when
        the bucket is non-positive.  In-flight work is never touched."""
        rate = self.rate_for(tenant)
        if rate <= 0:
            return
        if now is None:
            now = time.monotonic()
        with self._lock:
            b = self._refill(tenant, rate, now)
            if b.tokens > 0:
                return
            retry = max(1, math.ceil((1.0 - b.tokens) / rate))
            self.rejections[tenant] += 1
        raise TenantQuotaExceeded(tenant, min(retry, 60))

    def charge(self, tenant: str, n: int, now: float | None = None) -> None:
        """Debit ``n`` tokens (prefilled or emitted); may go negative."""
        if n <= 0:
            return
        rate = self.rate_for(tenant)
        if rate <= 0:
            return
        if now is None:
            now = time.monotonic()
        with self._lock:
            b = self._refill(tenant, rate, now)
            b.tokens -= n
            self.charged[tenant] += n

    def stats(self) -> dict:
        with self._lock:
            return {
                "overrides": dict(self._overrides),
                "tier_overrides": dict(self._tier_overrides),
                "rejections": dict(self.rejections),
                "charged": dict(self.charged),
            }

    def reset(self) -> None:
        with self._lock:
            self._buckets.clear()
            self._overrides.clear()
            self._tier_overrides.clear()
            self.rejections.clear()
            self.charged.clear()


QUOTAS = QuotaManager()


def reset() -> None:
    """Test hook: clear process-wide quota state."""
    QUOTAS.reset()
